"""L0 byte-level codecs: LEB128, RLE, Delta, Boolean run-length.

Byte-compatible with the reference implementation's encoding layer
(/root/reference/backend/encoding.js). The encoders here are rewritten
for Python (bytearray-backed, arbitrary-precision ints) but produce
bit-identical output for the same value sequences:

- LEB128 unsigned/signed varints (minimal encodings), bounded at 64 bits
  on decode and 53 bits for the JS-safe-integer entry points.
- RLE columns: records of (count, value) where count > 0 is a repetition,
  count < 0 a literal run, count == 0 a null run (encoding.js:536-556).
- Delta columns: RLE over successive differences (encoding.js:922).
- Boolean columns: alternating run lengths starting with false
  (encoding.js:1053).
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from .errors import DecodeError, EncodeError
from .obs.metrics import get_metrics

MAX_SAFE_INTEGER = 2**53 - 1
MIN_SAFE_INTEGER = -(2**53 - 1)


class DecodeCache:
    """Bounded LRU of decoded artefacts keyed by the raw chunk bytes.

    A change gossiped to N documents, or replayed across sync rounds, is
    parsed once: the decoded object is cached under the chunk bytes (the
    change hash is the sha256 of those bytes, so byte-keying IS hash-keying
    without paying the digest on every lookup). Cached values are shared
    between callers — treat them as immutable; callers that need to attach
    per-delivery state must copy (columnar.decode_change_cached returns a
    shallow copy per hit for exactly that reason).

    Capacity bounds the working set by entry count; `max_bytes` additionally
    bounds it by the total size of the cached chunk bytes (the key), so a
    few huge document chunks cannot pin unbounded host memory however small
    the entry count stays. Oldest-used entries evict first under either
    bound. Hits/misses/evictions are counted on the process-wide metrics
    registry under the instrument names ``<name>.{hits,misses,evictions}``,
    and ``<name>.bytes`` gauges the bytes currently pinned; caches
    constructed with the same name share one set of instruments (the bytes
    gauge aggregates across them).
    """

    __slots__ = ("capacity", "max_bytes", "name", "_entries", "_bytes",
                 "_m_hits", "_m_misses", "_m_evictions", "_m_bytes")

    #: per-name aggregate of pinned bytes across cache instances (the two
    #: module-level caches share the default name and one gauge)
    _name_bytes: dict = {}

    def __init__(self, capacity: int, name: str = "codecs.decode_cache",
                 max_bytes: int | None = None):
        if capacity <= 0:
            raise ValueError("DecodeCache capacity must be positive")  # amlint: disable=AM401 — API-usage validation
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError("DecodeCache max_bytes must be positive")  # amlint: disable=AM401 — API-usage validation
        self.capacity = capacity
        self.max_bytes = max_bytes
        self.name = name
        self._entries: dict = {}
        self._bytes = 0
        metrics = get_metrics()
        self._m_hits = metrics.counter(
            f"{name}.hits", "decode calls served from the LRU"
        )
        self._m_misses = metrics.counter(
            f"{name}.misses", "decode calls that parsed the bytes"
        )
        self._m_evictions = metrics.counter(
            f"{name}.evictions", "entries dropped by the LRU capacity bound"
        )
        self._m_bytes = metrics.gauge(
            f"{name}.bytes", "chunk bytes currently pinned by the LRU"
        )

    def __len__(self) -> int:
        return len(self._entries)

    @staticmethod
    def _cost(key) -> int:
        """Byte cost of one entry: the chunk bytes ARE the key, and the
        decoded value's size tracks the chunk size, so the key length is
        the budgeted proxy."""
        try:
            return len(key)
        except TypeError:
            return 0

    def _account(self, delta: int) -> None:
        self._bytes += delta
        total = self._name_bytes.get(self.name, 0) + delta
        self._name_bytes[self.name] = total
        self._m_bytes.set(total)

    def get(self, key):
        """The cached value for `key` (refreshing its recency), else None."""
        entry = self._entries.pop(key, None)
        if entry is None:
            self._m_misses.inc()
            return None
        self._entries[key] = entry  # dicts iterate in insertion order: re-
        self._m_hits.inc()          # inserting makes this the newest entry
        return entry

    def put(self, key, value) -> None:
        if key in self._entries:
            self._entries.pop(key)
            self._account(-self._cost(key))
        elif len(self._entries) >= self.capacity:
            self._evict_oldest()
        self._entries[key] = value
        self._account(self._cost(key))
        if self.max_bytes is not None:
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                self._evict_oldest()

    def _evict_oldest(self) -> None:
        oldest = next(iter(self._entries))
        self._entries.pop(oldest)
        self._account(-self._cost(oldest))
        self._m_evictions.inc()

    def clear(self) -> None:
        self._entries.clear()
        self._account(-self._bytes)


def hex_to_bytes(value: str) -> bytes:
    if not isinstance(value, str):
        raise TypeError("value is not a string")  # amlint: disable=AM401 — argument-type validation
    try:
        return bytes.fromhex(value)
    except ValueError:
        raise DecodeError("value is not hexadecimal") from None


def bytes_to_hex(data) -> str:
    return bytes(data).hex()


class Encoder:
    """Append-only byte buffer with LEB128 primitives."""

    def __init__(self):
        self.buf = bytearray()

    @property
    def buffer(self) -> bytes:
        self.finish()
        return bytes(self.buf)

    def append_byte(self, value: int) -> None:
        self.buf.append(value)

    def append_uint(self, value: int, max_bits: int = 64) -> int:
        """LEB128-encode a nonnegative integer. Returns bytes written."""
        if not isinstance(value, int) or isinstance(value, bool):
            raise EncodeError("value is not an integer")
        if value < 0 or value >= (1 << max_bits):
            raise EncodeError("number out of range")
        n = 0
        while True:
            byte = value & 0x7F
            value >>= 7
            if value:
                self.buf.append(byte | 0x80)
                n += 1
            else:
                self.buf.append(byte)
                return n + 1

    def append_int(self, value: int, max_bits: int = 64) -> int:
        """LEB128-encode a signed integer. Returns bytes written."""
        if not isinstance(value, int) or isinstance(value, bool):
            raise EncodeError("value is not an integer")
        if value < -(1 << (max_bits - 1)) or value >= (1 << (max_bits - 1)):
            raise EncodeError("number out of range")
        n = 0
        while True:
            byte = value & 0x7F
            value >>= 7
            if (value == 0 and not (byte & 0x40)) or (value == -1 and (byte & 0x40)):
                self.buf.append(byte)
                return n + 1
            self.buf.append(byte | 0x80)
            n += 1

    def append_uint32(self, value: int) -> int:
        return self.append_uint(value, 32)

    def append_int32(self, value: int) -> int:
        return self.append_int(value, 32)

    def append_uint53(self, value: int) -> int:
        if not isinstance(value, int) or isinstance(value, bool):
            raise EncodeError("value is not an integer")
        if value < 0 or value > MAX_SAFE_INTEGER:
            raise EncodeError("number out of range")
        return self.append_uint(value, 64)

    def append_int53(self, value: int) -> int:
        if not isinstance(value, int) or isinstance(value, bool):
            raise EncodeError("value is not an integer")
        if value < MIN_SAFE_INTEGER or value > MAX_SAFE_INTEGER:
            raise EncodeError("number out of range")
        return self.append_int(value, 64)

    def append_raw_bytes(self, data) -> int:
        self.buf.extend(data)
        return len(data)

    def append_raw_string(self, value: str) -> int:
        if not isinstance(value, str):
            raise TypeError("value is not a string")  # amlint: disable=AM401 — argument-type validation
        return self.append_raw_bytes(value.encode("utf-8", "surrogatepass"))

    def append_prefixed_bytes(self, data) -> "Encoder":
        self.append_uint53(len(data))
        self.append_raw_bytes(data)
        return self

    def append_prefixed_string(self, value: str) -> "Encoder":
        if not isinstance(value, str):
            raise TypeError("value is not a string")  # amlint: disable=AM401 — argument-type validation
        self.append_prefixed_bytes(value.encode("utf-8", "surrogatepass"))
        return self

    def append_hex_string(self, value: str) -> "Encoder":
        self.append_prefixed_bytes(hex_to_bytes(value))
        return self

    def finish(self) -> None:
        pass


class Decoder:
    """Cursor over a byte buffer with LEB128 primitives."""

    def __init__(self, buffer):
        if not isinstance(buffer, (bytes, bytearray, memoryview)):
            raise TypeError(f"Not a byte array: {buffer!r}")  # amlint: disable=AM401 — argument-type validation
        self.buf = bytes(buffer)
        self.offset = 0

    @property
    def done(self) -> bool:
        return self.offset == len(self.buf)

    def reset(self) -> None:
        self.offset = 0

    def skip(self, num_bytes: int) -> None:
        if self.offset + num_bytes > len(self.buf):
            raise DecodeError("cannot skip beyond end of buffer")
        self.offset += num_bytes

    def read_byte(self) -> int:
        self.offset += 1
        return self.buf[self.offset - 1]

    def _read_leb_bytes(self):
        """Reads raw LEB128 bytes (up to 10); returns (unsigned_value, shift, last_byte)."""
        result = 0
        shift = 0
        # amlint: disable=AM106 — scalar parity oracle: the per-byte walk
        # the vectorized passes (tpu/decode.py) are differentially tested
        # against, and the canonical raiser for malformed varints
        while self.offset < len(self.buf):
            byte = self.buf[self.offset]
            if shift == 63 and byte > 1 and byte != 0x7F:
                raise DecodeError("number out of range")
            if shift > 63:
                raise DecodeError("number out of range")
            result |= (byte & 0x7F) << shift
            shift += 7
            self.offset += 1
            if not (byte & 0x80):
                return result, shift, byte
        raise DecodeError("buffer ended with incomplete number")

    def read_uint(self, max_bits: int = 64) -> int:
        value, _shift, _last = self._read_leb_bytes()
        if value >= (1 << max_bits):
            raise DecodeError("number out of range")
        return value

    def read_int(self, max_bits: int = 64) -> int:
        value, shift, last = self._read_leb_bytes()
        if last & 0x40 and shift < 70:
            value -= 1 << shift  # sign-extend
        if value < -(1 << (max_bits - 1)) or value >= (1 << (max_bits - 1)):
            raise DecodeError("number out of range")
        return value

    def read_uint32(self) -> int:
        return self.read_uint(32)

    def read_int32(self) -> int:
        return self.read_int(32)

    def read_uint53(self) -> int:
        value = self.read_uint(64)
        if value > MAX_SAFE_INTEGER:
            raise DecodeError("number out of range")
        return value

    def read_int53(self) -> int:
        value = self.read_int(64)
        if value < MIN_SAFE_INTEGER or value > MAX_SAFE_INTEGER:
            raise DecodeError("number out of range")
        return value

    def read_raw_bytes(self, length: int) -> bytes:
        start = self.offset
        if start + length > len(self.buf):
            raise DecodeError("subarray exceeds buffer size")
        self.offset += length
        return self.buf[start : self.offset]

    def read_raw_string(self, length: int) -> str:
        return self.read_raw_bytes(length).decode("utf-8", "surrogatepass")

    def read_prefixed_bytes(self) -> bytes:
        return self.read_raw_bytes(self.read_uint53())

    def read_prefixed_string(self) -> str:
        return self.read_prefixed_bytes().decode("utf-8", "surrogatepass")

    def read_hex_string(self) -> str:
        return bytes_to_hex(self.read_prefixed_bytes())


class RLEEncoder(Encoder):
    """Run-length encoder for int/uint/utf8 columns (nullable).

    State machine identical to encoding.js:558 (states: empty, loneValue,
    repetition, literal, nulls) so that byte output matches the reference
    for any value sequence.
    """

    def __init__(self, type_: str):
        super().__init__()
        self.type = type_
        self.state = "empty"
        self.last_value = None
        self.count = 0
        self.literal = []

    def append_value(self, value, repetitions: int = 1) -> None:
        self._append_value(value, repetitions)

    def _append_value(self, value, repetitions: int = 1) -> None:
        if repetitions <= 0:
            return
        st = self.state
        if st == "empty":
            self.state = (
                "nulls" if value is None else ("loneValue" if repetitions == 1 else "repetition")
            )
            self.last_value = value
            self.count = repetitions
        elif st == "loneValue":
            if value is None:
                self.flush()
                self.state = "nulls"
                self.count = repetitions
            elif value == self.last_value:
                self.state = "repetition"
                self.count = 1 + repetitions
            elif repetitions > 1:
                self.flush()
                self.state = "repetition"
                self.count = repetitions
                self.last_value = value
            else:
                self.state = "literal"
                self.literal = [self.last_value]
                self.last_value = value
        elif st == "repetition":
            if value is None:
                self.flush()
                self.state = "nulls"
                self.count = repetitions
            elif value == self.last_value:
                self.count += repetitions
            elif repetitions > 1:
                self.flush()
                self.state = "repetition"
                self.count = repetitions
                self.last_value = value
            else:
                self.flush()
                self.state = "loneValue"
                self.last_value = value
        elif st == "literal":
            if value is None:
                self.literal.append(self.last_value)
                self.flush()
                self.state = "nulls"
                self.count = repetitions
            elif value == self.last_value:
                self.flush()
                self.state = "repetition"
                self.count = 1 + repetitions
            elif repetitions > 1:
                self.literal.append(self.last_value)
                self.flush()
                self.state = "repetition"
                self.count = repetitions
                self.last_value = value
            else:
                self.literal.append(self.last_value)
                self.last_value = value
        elif st == "nulls":
            if value is None:
                self.count += repetitions
            elif repetitions > 1:
                self.flush()
                self.state = "repetition"
                self.count = repetitions
                self.last_value = value
            else:
                self.flush()
                self.state = "loneValue"
                self.last_value = value

    def flush(self) -> None:
        st = self.state
        if st == "loneValue":
            self.append_int32(-1)
            self._append_raw_value(self.last_value)
        elif st == "repetition":
            self.append_int53(self.count)
            self._append_raw_value(self.last_value)
        elif st == "literal":
            self.append_int53(-len(self.literal))
            for v in self.literal:
                self._append_raw_value(v)
        elif st == "nulls":
            self.append_int32(0)
            self.append_uint53(self.count)
        self.state = "empty"

    def _append_raw_value(self, value) -> None:
        if self.type == "int":
            self.append_int53(value)
        elif self.type == "uint":
            self.append_uint53(value)
        elif self.type == "utf8":
            self.append_prefixed_string(value)
        else:
            raise EncodeError(f"Unknown RLEEncoder datatype: {self.type}")

    def finish(self) -> None:
        if self.state == "literal":
            self.literal.append(self.last_value)
        # Don't write anything if the only values we have seen are nulls
        if self.state != "nulls" or len(self.buf) > 0:
            self.flush()


class RLEDecoder(Decoder):
    """Counterpart to RLEEncoder."""

    def __init__(self, type_: str, buffer):
        super().__init__(buffer)
        self.type = type_
        self.last_value = None
        self.count = 0
        self.state = None

    @property
    def done(self) -> bool:
        return self.count == 0 and self.offset == len(self.buf)

    def reset(self) -> None:
        self.offset = 0
        self.last_value = None
        self.count = 0
        self.state = None

    def read_value(self):
        if self.done:
            return None
        if self.count == 0:
            self._read_record()
        self.count -= 1
        if self.state == "literal":
            value = self._read_raw_value()
            if value == self.last_value:
                raise DecodeError("Repetition of values is not allowed in literal")
            self.last_value = value
            return value
        return self.last_value

    def skip_values(self, num_skip: int) -> None:
        while num_skip > 0 and not self.done:
            if self.count == 0:
                self.count = self.read_int53()
                if self.count > 0:
                    if self.count <= num_skip:
                        self._skip_raw_values(1)
                    else:
                        self.last_value = self._read_raw_value()
                    self.state = "repetition"
                elif self.count < 0:
                    self.count = -self.count
                    self.state = "literal"
                else:
                    self.count = self.read_uint53()
                    self.last_value = None
                    self.state = "nulls"
            consume = min(num_skip, self.count)
            if self.state == "literal":
                self._skip_raw_values(consume)
            num_skip -= consume
            self.count -= consume

    def _read_record(self) -> None:
        self.count = self.read_int53()
        if self.count > 1:
            value = self._read_raw_value()
            if self.state in ("repetition", "literal") and self.last_value == value:
                raise DecodeError("Successive repetitions with the same value are not allowed")
            self.state = "repetition"
            self.last_value = value
        elif self.count == 1:
            raise DecodeError("Repetition count of 1 is not allowed, use a literal instead")
        elif self.count < 0:
            self.count = -self.count
            if self.state == "literal":
                raise DecodeError("Successive literals are not allowed")
            self.state = "literal"
        else:
            if self.state == "nulls":
                raise DecodeError("Successive null runs are not allowed")
            self.count = self.read_uint53()
            if self.count == 0:
                raise DecodeError("Zero-length null runs are not allowed")
            self.last_value = None
            self.state = "nulls"

    def _read_raw_value(self):
        if self.type == "int":
            return self.read_int53()
        if self.type == "uint":
            return self.read_uint53()
        if self.type == "utf8":
            return self.read_prefixed_string()
        raise DecodeError(f"Unknown RLEDecoder datatype: {self.type}")

    def _skip_raw_values(self, num: int) -> None:
        if self.type == "utf8":
            for _ in range(num):
                self.skip(self.read_uint53())
        else:
            # amlint: disable=AM106 — scalar parity oracle (see _read_leb_bytes)
            while num > 0 and self.offset < len(self.buf):
                if not (self.buf[self.offset] & 0x80):
                    num -= 1
                self.offset += 1
            if num > 0:
                raise DecodeError("cannot skip beyond end of buffer")


class DeltaEncoder(RLEEncoder):
    """RLE over successive differences (good for opId counters)."""

    def __init__(self):
        super().__init__("int")
        self.absolute_value = 0

    def append_value(self, value, repetitions: int = 1) -> None:
        if repetitions <= 0:
            return
        if value is not None:
            super().append_value(value - self.absolute_value, 1)
            self.absolute_value = value
            if repetitions > 1:
                super().append_value(0, repetitions - 1)
        else:
            super().append_value(value, repetitions)


class DeltaDecoder(RLEDecoder):
    """Counterpart to DeltaEncoder."""

    def __init__(self, buffer):
        super().__init__("int", buffer)
        self.absolute_value = 0

    def reset(self) -> None:
        super().reset()
        self.absolute_value = 0

    def read_value(self):
        value = super().read_value()
        if value is None:
            return None
        self.absolute_value += value
        return self.absolute_value

    def skip_values(self, num_skip: int) -> None:
        while num_skip > 0 and not self.done:
            if self.count == 0:
                self._read_record()
            consume = min(num_skip, self.count)
            if self.state == "literal":
                for _ in range(consume):
                    self.last_value = self._read_raw_value()
                    self.absolute_value += self.last_value
            elif self.state == "repetition":
                self.absolute_value += consume * self.last_value
            num_skip -= consume
            self.count -= consume


class BooleanEncoder(Encoder):
    """Alternating false/true run lengths, starting with false."""

    def __init__(self):
        super().__init__()
        self.last_value = False
        self.count = 0

    def append_value(self, value, repetitions: int = 1) -> None:
        if value is not False and value is not True:
            raise EncodeError(f"Unsupported value for BooleanEncoder: {value}")
        if repetitions <= 0:
            return
        if self.last_value == value:
            self.count += repetitions
        else:
            self.append_uint53(self.count)
            self.last_value = value
            self.count = repetitions

    def finish(self) -> None:
        if self.count > 0:
            self.append_uint53(self.count)
            self.count = 0


class BooleanDecoder(Decoder):
    """Counterpart to BooleanEncoder."""

    def __init__(self, buffer):
        super().__init__(buffer)
        self.last_value = True  # negated the first time we read a count
        self.first_run = True
        self.count = 0

    @property
    def done(self) -> bool:
        return self.count == 0 and self.offset == len(self.buf)

    def reset(self) -> None:
        self.offset = 0
        self.last_value = True
        self.first_run = True
        self.count = 0

    def read_value(self):
        if self.done:
            return False
        while self.count == 0:
            self.count = self.read_uint53()
            self.last_value = not self.last_value
            if self.count == 0 and not self.first_run:
                raise DecodeError("Zero-length runs are not allowed")
            self.first_run = False
        self.count -= 1
        return self.last_value

    def skip_values(self, num_skip: int) -> None:
        while num_skip > 0 and not self.done:
            if self.count == 0:
                self.count = self.read_uint53()
                self.last_value = not self.last_value
                if self.count == 0 and not self.first_run:
                    raise DecodeError("Zero-length runs are not allowed")
                self.first_run = False
            consume = min(num_skip, self.count)
            num_skip -= consume
            self.count -= consume
