"""Shared helpers for the automerge_tpu framework.

Mirrors the semantics of the reference implementation's shared utilities
(/root/reference/src/common.js) with Python idioms.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import re
from functools import lru_cache

_OPID_RE = re.compile(r"^(\d+)@(.*)$")


class OpId:
    """A parsed operation ID (Lamport timestamp): counter@actorId.

    Reference: /root/reference/src/common.js:22 (parseOpId).
    """

    __slots__ = ("counter", "actor_id")

    def __init__(self, counter: int, actor_id: str):
        self.counter = counter
        self.actor_id = actor_id

    def __repr__(self):
        return f"OpId({self.counter}@{self.actor_id})"

    def __eq__(self, other):
        return (
            isinstance(other, OpId)
            and self.counter == other.counter
            and self.actor_id == other.actor_id
        )

    def __hash__(self):
        return hash((self.counter, self.actor_id))

    def __str__(self):
        return f"{self.counter}@{self.actor_id}"


def parse_op_id(op_id: str) -> OpId:
    m = _OPID_RE.match(op_id)
    if not m:
        raise ValueError(f"Not a valid opId: {op_id}")
    return OpId(int(m.group(1)), m.group(2))


def op_id_sort_key(op_id: str):
    """Sort key for string opIds in Lamport order (counter, then actorId).

    '_root' sorts before everything (reference columnar.js:859 sortOpIds).
    """
    if op_id == "_root":
        return (-1, "")
    p = parse_op_id(op_id)
    return (p.counter, p.actor_id)


def lamport_compare_key(ts: str):
    """Sort key matching the frontend's lamportCompare
    (/root/reference/frontend/apply_patch.js:33): strings that are not
    opIds are treated as {counter: 0, actorId: ts}.
    """
    m = _OPID_RE.match(ts)
    if m:
        return (int(m.group(1)), m.group(2))
    return (0, ts)


@lru_cache(maxsize=8192)
def utf16_key(s: str) -> bytes:
    """Sort key giving JavaScript's UTF-16 code-unit string ordering.

    The reference engine compares map keys with JS `<` (UTF-16 code units,
    see /root/reference/backend/new.js:1156); comparing the UTF-16-BE
    encoding byte-wise is equivalent. Cached: the farm's run-segmentation
    pass compares the same few map keys once per op (pure function of the
    string, so a bounded LRU is always safe).
    """
    return s.encode("utf-16-be", "surrogatepass")


def check_actor_id(actor_id) -> None:
    """Validate an actor ID (lowercase hex, even length).

    Reference: /root/reference/frontend/index.js:17.
    """
    if not isinstance(actor_id, str):
        raise TypeError(f"Unsupported type of actorId: {type(actor_id)}")
    if not re.fullmatch(r"[0-9a-f]+", actor_id):
        raise ValueError("actorId must consist only of lowercase hex digits")
    if len(actor_id) % 2 != 0:
        raise ValueError("actorId must consist of an even number of digits")
