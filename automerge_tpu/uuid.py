"""UUID generation for actor IDs and table row IDs, with a swappable factory
for deterministic tests (port of /root/reference/src/uuid.js)."""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import uuid as _stdlib_uuid


def _default_factory() -> str:
    return _stdlib_uuid.uuid4().hex


_factory = _default_factory


def make_uuid() -> str:
    return _factory()


def set_factory(factory) -> None:
    global _factory
    _factory = factory


def reset_factory() -> None:
    global _factory
    _factory = _default_factory
