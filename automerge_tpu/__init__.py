"""automerge_tpu: a TPU-native CRDT framework with the capabilities of
Automerge.

Public API (port of /root/reference/src/automerge.js): every function takes
an immutable document and returns a new one. The frontend/backend split is
the plugin boundary: `set_default_backend()` swaps the merge engine (the
pure-Python OpSet by default; the batched TPU engine for bulk workloads).
"""
from __future__ import annotations

from . import backend as _default_backend
from . import sync as _sync
from . import uuid as _uuid_module
from . import frontend as Frontend
from .columnar import decode_change, encode_change
from .errors import (
    AdmissionRejectedError,
    AutomergeError,
    BackpressureError,
    CausalityError,
    ChannelQuarantinedError,
    ChecksumError,
    DecodeError,
    DeviceFaultError,
    EncodeError,
    PackingLimitError,
    QuarantinedError,
    RetryExhaustedError,
    StoreCorruptError,
    StoreTornWriteError,
    SyncFrameError,
    SyncProtocolError,
    WorkerCrashError,
)
from .sync import decode_sync_state, encode_sync_state
from .sync_session import BackendDriver, SessionConfig, SyncSession
from .frontend import (
    Counter,
    Float64,
    Int,
    List,
    Map,
    Observable,
    Table,
    Text,
    Uint,
    get_actor_id,
    get_backend_state,
    get_conflicts,
    get_element_ids,
    get_last_local_change,
    get_object_by_id,
    get_object_id,
    set_actor_id,
)

__version__ = "0.1.0"

__all__ = [
    "init", "from_data", "change", "empty_change", "clone", "free",
    "load", "save", "merge", "get_changes", "get_all_changes", "apply_changes",
    "encode_change", "decode_change", "equals", "get_history", "uuid",
    "Frontend", "set_default_backend", "get_backend",
    "generate_sync_message", "receive_sync_message", "init_sync_state",
    "encode_sync_state", "decode_sync_state",
    "SyncSession", "SessionConfig", "BackendDriver",
    "get_object_id", "get_object_by_id", "get_actor_id", "set_actor_id",
    "get_conflicts", "get_last_local_change", "get_element_ids",
    "Text", "Table", "Counter", "Observable", "Int", "Uint", "Float64",
    "Map", "List",
    "AutomergeError", "DecodeError", "ChecksumError", "EncodeError",
    "CausalityError", "PackingLimitError", "SyncProtocolError",
    "SyncFrameError", "RetryExhaustedError", "ChannelQuarantinedError",
    "QuarantinedError", "DeviceFaultError", "WorkerCrashError",
    "StoreCorruptError", "StoreTornWriteError",
    "AdmissionRejectedError", "BackpressureError",
]

_backend = _default_backend  # swappable via set_default_backend()


def uuid():
    return _uuid_module.make_uuid()


def init(options=None):
    if isinstance(options, str):
        options = {"actorId": options}
    elif options is None:
        options = {}
    elif not isinstance(options, dict):
        raise TypeError(f"Unsupported options for init(): {options!r}")
    return Frontend.init(dict({"backend": _backend}, **options))


def from_data(initial_state, options=None):
    """Returns a new document initialized with the given state."""
    return change(init(options), {"message": "Initialization"},
                  lambda doc: doc.update(initial_state))


def change(doc, options=None, callback=None):
    new_doc, _request = Frontend.change(doc, options, callback)
    return new_doc


def empty_change(doc, options=None):
    new_doc, _request = Frontend.empty_change(doc, options)
    return new_doc


def _normalize_options(options):
    if isinstance(options, str):
        return {"actorId": options}
    return dict(options) if options else {}


def clone(doc, options=None):
    options = _normalize_options(options)
    state = _backend.clone(Frontend.get_backend_state(doc, "clone"))
    return _apply_patch(init(options), _backend.get_patch(state), state, [], options)


def free(doc):
    _backend.free(Frontend.get_backend_state(doc, "free"))


def load(data, options=None):
    options = _normalize_options(options)
    state = _backend.load(data)
    return _apply_patch(init(options), _backend.get_patch(state), state, [data], options)


def save(doc):
    return _backend.save(Frontend.get_backend_state(doc, "save"))


def merge(local_doc, remote_doc):
    local_state = Frontend.get_backend_state(local_doc, "merge")
    remote_state = Frontend.get_backend_state(remote_doc, "merge", "second")
    changes = _backend.get_changes_added(local_state, remote_state)
    updated_doc, _patch = apply_changes(local_doc, changes)
    return updated_doc


def get_changes(old_doc, new_doc):
    old_state = Frontend.get_backend_state(old_doc, "get_changes")
    new_state = Frontend.get_backend_state(new_doc, "get_changes", "second")
    return _backend.get_changes(new_state, _backend.get_heads(old_state))


def get_all_changes(doc):
    return _backend.get_all_changes(Frontend.get_backend_state(doc, "get_all_changes"))


def _apply_patch(doc, patch, backend_state, changes, options):
    new_doc = Frontend.apply_patch(doc, patch, backend_state)
    patch_callback = options.get("patchCallback") or doc._options.get("patchCallback")
    if patch_callback:
        patch_callback(patch, doc, new_doc, False, changes)
    return new_doc


def apply_changes(doc, changes, options=None):
    old_state = Frontend.get_backend_state(doc, "apply_changes")
    new_state, patch = _backend.apply_changes(old_state, changes)
    return _apply_patch(doc, patch, new_state, changes, options or {}), patch


def equals(val1, val2):
    """Deep structural equality on document values."""
    if isinstance(val1, (Map, dict)) and isinstance(val2, (Map, dict)):
        if sorted(val1.keys()) != sorted(val2.keys()):
            return False
        return all(equals(val1[k], val2[k]) for k in val1.keys())
    if isinstance(val1, (List, list)) and isinstance(val2, (List, list)):
        return len(val1) == len(val2) and all(equals(a, b) for a, b in zip(val1, val2))
    return val1 == val2


class _HistoryEntry:
    __slots__ = ("_binary", "_history", "_index", "_actor")

    def __init__(self, binary, history, index, actor):
        self._binary = binary
        self._history = history
        self._index = index
        self._actor = actor

    @property
    def change(self):
        return decode_change(self._binary)

    @property
    def snapshot(self):
        state = _backend.load_changes(_backend.init(), self._history[: self._index + 1])
        return Frontend.apply_patch(init(self._actor), _backend.get_patch(state), state)


def get_history(doc):
    """Returns the change history with lazy snapshot reconstruction
    (src/automerge.js:105)."""
    actor = Frontend.get_actor_id(doc)
    history = get_all_changes(doc)
    return [
        _HistoryEntry(binary, history, index, actor) for index, binary in enumerate(history)
    ]


def generate_sync_message(doc, sync_state):
    state = Frontend.get_backend_state(doc, "generate_sync_message")
    return _sync.generate_sync_message(state, sync_state)


def receive_sync_message(doc, old_sync_state, message):
    old_backend_state = Frontend.get_backend_state(doc, "receive_sync_message")
    backend_state, sync_state, patch = _sync.receive_sync_message(
        old_backend_state, old_sync_state, message
    )
    if patch is None:
        return doc, sync_state, patch
    changes = None
    if doc._options.get("patchCallback"):
        changes = _sync.decode_sync_message(message)["changes"]
    return _apply_patch(doc, patch, backend_state, changes, {}), sync_state, patch


def init_sync_state():
    return _sync.init_sync_state()


def set_default_backend(new_backend):
    """Swaps the backend implementation (the `backend=tpu` plug point)."""
    global _backend
    _backend = new_backend


def get_backend():
    return _backend
