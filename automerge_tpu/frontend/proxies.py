"""Mutable proxy wrappers handed to change callbacks.

The Python equivalent of the reference's ES6 Proxy layer
(/root/reference/frontend/proxies.js): MapProxy/ListProxy translate Python
mutation idioms (item assignment, append, slicing, del) into Context calls.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from .context import get_elem_id
from .datatypes import List, Map, Table, Text, WriteableTable


class MapProxy:
    """Mutable view of a map object inside a change block."""

    __slots__ = ("_context", "_object_id", "_path")

    def __init__(self, context, object_id, path):
        object.__setattr__(self, "_context", context)
        object.__setattr__(self, "_object_id", object_id)
        object.__setattr__(self, "_path", path)

    def _target(self):
        return self._context.get_object(self._object_id)

    # -- reads ---------------------------------------------------------------
    def __getitem__(self, key):
        if key not in self._target():
            raise KeyError(key)
        return self._context.get_object_field(self._path, self._object_id, key)

    def get(self, key, default=None):
        if key in self._target():
            return self._context.get_object_field(self._path, self._object_id, key)
        return default

    def __contains__(self, key):
        return key in self._target()

    def __len__(self):
        return len(self._target())

    def __iter__(self):
        return iter(self._target())

    def keys(self):
        return self._target().keys()

    def values(self):
        return [self[k] for k in self._target()]

    def items(self):
        return [(k, self[k]) for k in self._target()]

    def object_id(self):
        return self._object_id

    def __repr__(self):
        return f"MapProxy({dict(self._target())!r})"

    # -- writes --------------------------------------------------------------
    def __setitem__(self, key, value):
        self._context.set_map_key(self._path, key, value)

    def __delitem__(self, key):
        self._context.delete_map_key(self._path, key)

    def update(self, other):
        for key, value in other.items():
            self[key] = value

    def increment(self, key, delta=1):
        self._context.increment(self._path, key, delta)

    def __eq__(self, other):
        if isinstance(other, MapProxy):
            return dict(self._target()) == dict(other._target())
        if isinstance(other, dict):
            return dict(self._target()) == other
        return NotImplemented


class ListProxy:
    """Mutable view of a list object inside a change block."""

    __slots__ = ("_context", "_object_id", "_path")

    def __init__(self, context, object_id, path):
        self._context = context
        self._object_id = object_id
        self._path = path

    def _target(self):
        return self._context.get_object(self._object_id)

    # -- reads ---------------------------------------------------------------
    def __len__(self):
        return len(self._target())

    def __getitem__(self, index):
        target = self._target()
        if isinstance(index, slice):
            return [
                self._context.get_object_field(self._path, self._object_id, i)
                for i in range(*index.indices(len(target)))
            ]
        if index < 0:
            index += len(target)
        if not (0 <= index < len(target)):
            raise IndexError(index)
        return self._context.get_object_field(self._path, self._object_id, index)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def index(self, value):
        for i, v in enumerate(self):
            if v == value:
                return i
        raise ValueError(f"{value!r} is not in list")

    def __contains__(self, value):
        return any(v == value for v in self)

    def object_id(self):
        return self._object_id

    def __repr__(self):
        return f"ListProxy({list(self._target())!r})"

    def __eq__(self, other):
        if isinstance(other, ListProxy):
            return list(self._target()) == list(other._target())
        if isinstance(other, list):
            return list(self._target()) == other
        return NotImplemented

    # -- writes --------------------------------------------------------------
    def __setitem__(self, index, value):
        if isinstance(index, slice):
            start, stop, step = index.indices(len(self._target()))
            if step != 1:
                raise ValueError("Extended slices are not supported in change blocks")
            self._context.splice(self._path, start, max(0, stop - start), list(value))
            return
        if index < 0:
            index += len(self._target())
        self._context.set_list_index(self._path, index, value)

    def __delitem__(self, index):
        if isinstance(index, slice):
            start, stop, step = index.indices(len(self._target()))
            if step != 1:
                raise ValueError("Extended slices are not supported in change blocks")
            self._context.splice(self._path, start, max(0, stop - start), [])
            return
        if index < 0:
            index += len(self._target())
        self._context.splice(self._path, index, 1, [])

    def append(self, value):
        self._context.splice(self._path, len(self._target()), 0, [value])

    def extend(self, values):
        self._context.splice(self._path, len(self._target()), 0, list(values))

    def insert(self, index, value):
        self._context.splice(self._path, index, 0, [value])

    def insert_at(self, index, *values):
        self._context.splice(self._path, index, 0, list(values))
        return self

    def delete_at(self, index, num_delete=1):
        self._context.splice(self._path, index, num_delete, [])
        return self

    def pop(self, index=-1):
        target = self._target()
        if index < 0:
            index += len(target)
        value = self[index]
        self._context.splice(self._path, index, 1, [])
        return value

    def splice(self, start, deletions=0, insertions=()):
        self._context.splice(self._path, start, deletions, list(insertions))

    def increment(self, index, delta=1):
        self._context.increment(self._path, index, delta)

    def elem_id(self, index):
        return get_elem_id(self._target(), index)


def instantiate_proxy(context, path, object_id):
    obj = context.get_object(object_id)
    if isinstance(obj, Text):
        return obj.get_writeable(context, path)
    if isinstance(obj, Table):
        return WriteableTable(context, path, obj)
    if isinstance(obj, (List, list)) and not isinstance(obj, Map):
        return ListProxy(context, object_id, path)
    return MapProxy(context, object_id, path)


def root_object_proxy(context):
    """Returns the root proxy for a change callback (proxies.js:258)."""

    def instantiate_object(path, object_id):
        return instantiate_proxy(context, path, object_id)

    context.instantiate_object = instantiate_object
    return MapProxy(context, "_root", [])
