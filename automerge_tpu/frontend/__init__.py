"""Frontend: the document layer. Knows the actorId, assigns opIds to local
changes, and materialises Python objects from backend patches.

Port of /root/reference/frontend/index.js. Talks to the backend only via two
message types: change requests (frontend -> backend) and patches (backend ->
frontend); both are plain JSON-able dicts, so the backend can be the local
pure-Python engine, the TPU batched engine, or a remote process.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import time as _time

from ..uuid import make_uuid
from ..common import check_actor_id
from .apply_patch import clone_root_object, interpret_patch
from .context import Context
from .datatypes import (
    Counter,
    Float64,
    Int,
    List,
    Map,
    Table,
    Text,
    Uint,
)
from .observable import Observable
from .proxies import root_object_proxy

__all__ = [
    "init", "from_data", "change", "empty_change", "apply_patch",
    "get_object_id", "get_object_by_id", "get_actor_id", "set_actor_id",
    "get_conflicts", "get_last_local_change", "get_backend_state",
    "get_element_ids", "Context",
    "Text", "Table", "Counter", "Observable", "Float64", "Int", "Uint",
    "Map", "List",
]


def _update_root_object(doc, updated, state):
    """Returns a new immutable document root reflecting `updated` objects
    (index.js:34)."""
    new_doc = updated.get("_root")
    if new_doc is None:
        new_doc = clone_root_object(doc._cache["_root"])
        updated["_root"] = new_doc
    new_doc._options = doc._options
    new_doc._cache = updated
    new_doc._state = state
    for object_id, obj in doc._cache.items():
        if object_id not in updated:
            updated[object_id] = obj
    return new_doc


def _count_ops(ops):
    count = 0
    for op in ops:
        if op["action"] == "set" and "values" in op:
            count += len(op["values"])
        else:
            count += 1
    return count


def _make_change(doc, context, options):
    """Builds a change request from the context and round-trips it through
    the backend (index.js:78)."""
    actor = get_actor_id(doc)
    if not actor:
        raise ValueError("Actor ID must be initialized with set_actor_id() before making a change")
    state = dict(doc._state)
    state["seq"] += 1

    options = options or {}
    change_request = {
        "actor": actor,
        "seq": state["seq"],
        "startOp": state["maxOp"] + 1,
        "deps": state["deps"],
        "time": options["time"] if isinstance(options.get("time"), (int, float)) else round(_time.time()),
        "message": options.get("message") if isinstance(options.get("message"), str) else "",
        "ops": context.ops,
    }

    backend = doc._options.get("backend")
    if backend is not None:
        backend_state, patch, binary_change = backend.apply_local_change(
            state["backendState"], change_request
        )
        state["backendState"] = backend_state
        state["lastLocalChange"] = binary_change
        new_doc = _apply_patch_to_doc(doc, patch, state, True)
        patch_callback = options.get("patchCallback") or doc._options.get("patchCallback")
        if patch_callback:
            patch_callback(patch, doc, new_doc, True, [binary_change])
        return new_doc, change_request

    queued_request = {"actor": actor, "seq": change_request["seq"], "before": doc}
    state["requests"] = state["requests"] + [queued_request]
    state["maxOp"] = state["maxOp"] + _count_ops(change_request["ops"])
    state["deps"] = []
    return _update_root_object(doc, context.updated if context else {}, state), change_request


def get_last_local_change(doc):
    return doc._state.get("lastLocalChange")


def _apply_patch_to_doc(doc, patch, state, from_backend):
    actor = get_actor_id(doc)
    updated = {}
    interpret_patch(patch["diffs"], doc, updated)
    if from_backend:
        if "clock" not in patch:
            raise ValueError("patch is missing clock field")
        if patch["clock"].get(actor, 0) > state["seq"]:
            state["seq"] = patch["clock"][actor]
        state["clock"] = patch["clock"]
        state["deps"] = patch["deps"]
        state["maxOp"] = max(state["maxOp"], patch["maxOp"])
    return _update_root_object(doc, updated, state)


def init(options=None):
    """Creates an empty document object with no changes (index.js:166)."""
    if isinstance(options, str):
        options = {"actorId": options}
    elif options is None:
        options = {}
    elif not isinstance(options, dict):
        raise TypeError(f"Unsupported value for init() options: {options!r}")
    else:
        options = dict(options)

    if not options.get("deferActorId"):
        if options.get("actorId") is None:
            options["actorId"] = make_uuid()
        check_actor_id(options["actorId"])

    if options.get("observable"):
        patch_callback = options.get("patchCallback")
        observable = options["observable"]

        def combined(patch, before, after, local, changes):
            if patch_callback:
                patch_callback(patch, before, after, local, changes)
            observable.patch_callback(patch, before, after, local, changes)

        options["patchCallback"] = combined

    root = Map()
    root._object_id = "_root"
    cache = {"_root": root}
    state = {"seq": 0, "maxOp": 0, "requests": [], "clock": {}, "deps": []}
    if options.get("backend") is not None:
        state["backendState"] = options["backend"].init()
        state["lastLocalChange"] = None
    root._options = options
    root._cache = cache
    root._state = state
    return root


def from_data(initial_state, options=None):
    """Returns a new document initialized with the given state (index.js:207)."""
    return change(init(options), {"message": "Initialization"},
                  lambda doc: doc.update(initial_state))


def change(doc, options=None, callback=None):
    """Makes a local change via a mutation callback; returns (doc, request)
    (index.js:224)."""
    if doc._object_id != "_root":
        raise TypeError("The first argument to change() must be the document root")
    if callable(options) and callback is None:
        options, callback = None, options
    if isinstance(options, str):
        options = {"message": options}
    if options is not None and not isinstance(options, dict):
        raise TypeError("Unsupported type of options")

    actor_id = get_actor_id(doc)
    if not actor_id:
        raise ValueError("Actor ID must be initialized with set_actor_id() before making a change")
    context = Context(doc, actor_id)
    callback(root_object_proxy(context))

    if not context.updated:
        return doc, None
    return _make_change(doc, context, options)


def empty_change(doc, options=None):
    """Makes a change containing no operations (index.js:264)."""
    if doc._object_id != "_root":
        raise TypeError("The first argument to empty_change() must be the document root")
    if isinstance(options, str):
        options = {"message": options}
    if options is not None and not isinstance(options, dict):
        raise TypeError("Unsupported type of options")
    actor_id = get_actor_id(doc)
    if not actor_id:
        raise ValueError("Actor ID must be initialized with set_actor_id() before making a change")
    return _make_change(doc, Context(doc, actor_id), options)


def apply_patch(doc, patch, backend_state=None):
    """Applies a backend patch to the document root (index.js:288)."""
    if doc._object_id != "_root":
        raise TypeError("The first argument to apply_patch() must be the document root")
    state = dict(doc._state)

    if doc._options.get("backend") is not None:
        if backend_state is None:
            raise ValueError("apply_patch() must be called with the updated backend state")
        state["backendState"] = backend_state
        return _apply_patch_to_doc(doc, patch, state, True)

    if state["requests"]:
        base_doc = state["requests"][0]["before"]
        if patch.get("actor") == get_actor_id(doc):
            if state["requests"][0]["seq"] != patch.get("seq"):
                raise ValueError(
                    f"Mismatched sequence number: patch {patch.get('seq')} does not match "
                    f"next request {state['requests'][0]['seq']}"
                )
            state["requests"] = state["requests"][1:]
        else:
            state["requests"] = list(state["requests"])
    else:
        base_doc = doc
        state["requests"] = []

    new_doc = _apply_patch_to_doc(base_doc, patch, state, True)
    if not state["requests"]:
        return new_doc
    state["requests"][0] = dict(state["requests"][0])
    state["requests"][0]["before"] = new_doc
    return _update_root_object(doc, {}, state)


def get_object_id(obj):
    return getattr(obj, "_object_id", None)


def get_object_by_id(doc, object_id):
    return doc._cache.get(object_id)


def get_actor_id(doc):
    return doc._state.get("actorId") or doc._options.get("actorId")


def set_actor_id(doc, actor_id):
    check_actor_id(actor_id)
    state = dict(doc._state)
    state["actorId"] = actor_id
    return _update_root_object(doc, {}, state)


def get_conflicts(obj, key):
    """Returns the conflicting values at `key` if there is more than one
    (index.js:374)."""
    conflicts = getattr(obj, "_conflicts", None)
    if conflicts is None:
        return None
    try:
        entry = conflicts[key]
    except (KeyError, IndexError, TypeError):
        return None
    if entry and len(entry) > 1:
        return dict(entry)
    return None


def get_backend_state(doc, caller_name=None, arg_pos="first"):
    if doc is None or getattr(doc, "_object_id", None) != "_root":
        if caller_name:
            raise TypeError(
                f"The {arg_pos} argument to {caller_name} must be the document root"
            )
        raise TypeError("Argument is not an Automerge document root")
    return doc._state["backendState"]


def get_element_ids(lst):
    """Element IDs of each list element / text character (index.js:403)."""
    if isinstance(lst, Text):
        return [elem["elemId"] for elem in lst.elems]
    return list(lst._elem_ids)
