"""Frontend document value types: Map, List, Text, Table, Counter and the
explicit numeric wrappers.

Python equivalents of the reference's document layer types
(/root/reference/frontend/{text,table,counter,numbers}.js and the frozen
map/list objects produced by apply_patch.js). Documents are immutable
outside of change blocks: Map/List subclass dict/list but refuse mutation
unless instantiated as writable working copies by the patch interpreter.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import datetime as _dt

from ..common import parse_op_id


class Int:
    """Explicit int64 datatype wrapper (numbers.js:3)."""

    __slots__ = ("value",)

    def __init__(self, value):
        if not isinstance(value, int) or isinstance(value, bool):
            raise ValueError("Value must be an integer")
        if not (-(2**53 - 1) <= value <= 2**53 - 1):
            raise ValueError("Value out of range")
        self.value = value


class Uint:
    """Explicit uint64 datatype wrapper (numbers.js:13)."""

    __slots__ = ("value",)

    def __init__(self, value):
        if not isinstance(value, int) or isinstance(value, bool):
            raise ValueError("Value must be an integer")
        if not (0 <= value <= 2**53 - 1):
            raise ValueError("Value out of range")
        self.value = value


class Float64:
    """Explicit IEEE754 double datatype wrapper (numbers.js:23)."""

    __slots__ = ("value",)

    def __init__(self, value):
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise ValueError("Value must be a number")
        self.value = float(value)


class Counter:
    """A commutative increment-only register (counter.js:6). Behaves like an
    int in comparisons and arithmetic."""

    def __init__(self, value=0):
        self.value = value

    def __int__(self):
        return self.value

    def __index__(self):
        return self.value

    def __eq__(self, other):
        if isinstance(other, Counter):
            return self.value == other.value
        return self.value == other

    def __hash__(self):
        return hash(self.value)

    def __add__(self, other):
        return self.value + other

    def __radd__(self, other):
        return other + self.value

    def __sub__(self, other):
        return self.value - other

    def __lt__(self, other):
        return self.value < (other.value if isinstance(other, Counter) else other)

    def __le__(self, other):
        return self.value <= (other.value if isinstance(other, Counter) else other)

    def __gt__(self, other):
        return self.value > (other.value if isinstance(other, Counter) else other)

    def __ge__(self, other):
        return self.value >= (other.value if isinstance(other, Counter) else other)

    def __repr__(self):
        return f"Counter({self.value})"

    def increment(self, delta=1):
        raise TypeError("Counters can only be incremented inside a change block")

    def decrement(self, delta=1):
        raise TypeError("Counters can only be decremented inside a change block")


class WriteableCounter(Counter):
    """Counter bound to a change context (counter.js:46)."""

    def __init__(self, value, context, path, object_id, key):
        super().__init__(value)
        self._context = context
        self._path = path
        self._object_id = object_id
        self._key = key

    def increment(self, delta=1):
        self._context.increment(self._path, self._key, delta)
        self.value += delta
        return self.value

    def decrement(self, delta=1):
        return self.increment(-delta)


class Map(dict):
    """An immutable map object in a document. Mutation must go through a
    change block's proxy."""

    __slots__ = ("_object_id", "_conflicts", "_options", "_cache", "_state")

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._object_id = None
        self._conflicts = {}
        self._options = None
        self._cache = None
        self._state = None

    def _forbid(self, *a, **k):
        raise TypeError(
            "This object is read-only outside of a change block. "
            "Use automerge_tpu.change() to modify the document."
        )

    __setitem__ = _forbid
    __delitem__ = _forbid
    clear = _forbid
    pop = _forbid
    popitem = _forbid
    setdefault = _forbid
    update = _forbid

    def _unsafe_set(self, key, value):
        dict.__setitem__(self, key, value)

    def _unsafe_delete(self, key):
        dict.__delitem__(self, key)


class List(list):
    """An immutable list object in a document."""

    __slots__ = ("_object_id", "_conflicts", "_elem_ids")

    def __init__(self, *args):
        super().__init__(*args)
        self._object_id = None
        self._conflicts = []
        self._elem_ids = []

    def _forbid(self, *a, **k):
        raise TypeError(
            "This object is read-only outside of a change block. "
            "Use automerge_tpu.change() to modify the document."
        )

    __setitem__ = _forbid
    __delitem__ = _forbid
    __iadd__ = _forbid
    append = _forbid
    extend = _forbid
    insert = _forbid
    pop = _forbid
    remove = _forbid
    reverse = _forbid
    sort = _forbid
    clear = _forbid

    def _unsafe(self):
        return super()


class Text:
    """A sequence-of-graphemes CRDT (text.js:4). Internally a list of elems
    {elemId, pred, value}."""

    def __init__(self, text=None):
        if isinstance(text, str):
            self.elems = [{"value": ch} for ch in text]
        elif isinstance(text, (list, tuple)):
            self.elems = [{"value": v} for v in text]
        elif text is None:
            self.elems = []
        else:
            raise TypeError(f"Unsupported initial value for Text: {text!r}")
        self._object_id = None
        self.context = None
        self.path = None

    def __len__(self):
        return len(self.elems)

    def get(self, index):
        value = self.elems[index]["value"]
        if self.context is not None and isinstance(value, (Map, List, Text, Table)):
            object_id = value._object_id
            path = self.path + [{"key": index, "objectId": object_id}]
            return self.context.instantiate_object(path, object_id)
        return value

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [self.get(i) for i in range(*index.indices(len(self.elems)))]
        return self.get(index)

    def get_elem_id(self, index):
        return self.elems[index]["elemId"]

    def __iter__(self):
        for elem in self.elems:
            yield elem["value"]

    def __str__(self):
        return "".join(e["value"] for e in self.elems if isinstance(e["value"], str))

    def __eq__(self, other):
        if isinstance(other, Text):
            return [e["value"] for e in self.elems] == [e["value"] for e in other.elems]
        if isinstance(other, str):
            return str(self) == other
        return NotImplemented

    def __repr__(self):
        return f"Text({str(self)!r})"

    def to_spans(self):
        """Returns the content as strings interleaved with non-character
        elements (text.js:78)."""
        spans = []
        chars = ""
        for elem in self.elems:
            if isinstance(elem["value"], str):
                chars += elem["value"]
            else:
                if chars:
                    spans.append(chars)
                    chars = ""
                spans.append(elem["value"])
        if chars:
            spans.append(chars)
        return spans

    def get_writeable(self, context, path):
        if self._object_id is None:
            raise ValueError("get_writeable() requires the objectId to be set")
        instance = instantiate_text(self._object_id, self.elems)
        instance.context = context
        instance.path = path
        return instance

    def set(self, index, value):
        if self.context is not None:
            self.context.set_list_index(self.path, index, value)
        elif self._object_id is None:
            self.elems[index]["value"] = value
        else:
            raise TypeError("Text object cannot be modified outside of a change block")
        return self

    def __setitem__(self, index, value):
        self.set(index, value)

    def insert_at(self, index, *values):
        if self.context is not None:
            self.context.splice(self.path, index, 0, list(values))
        elif self._object_id is None:
            self.elems[index:index] = [{"value": v} for v in values]
        else:
            raise TypeError("Text object cannot be modified outside of a change block")
        return self

    def delete_at(self, index, num_delete=1):
        if self.context is not None:
            self.context.splice(self.path, index, num_delete, [])
        elif self._object_id is None:
            del self.elems[index : index + num_delete]
        else:
            raise TypeError("Text object cannot be modified outside of a change block")
        return self


def instantiate_text(object_id, elems):
    instance = Text.__new__(Text)
    instance._object_id = object_id
    instance.elems = elems
    instance.context = None
    instance.path = None
    return instance


class Table:
    """A collection of unordered rows keyed by UUID (table.js:25). Rows have
    no conflicts since their primary keys are unique. Each row object carries
    an `id` property equal to its key (table.js:152-156)."""

    def __init__(self):
        self.entries = {}
        self.op_ids = {}
        self._object_id = None

    def by_id(self, id_):
        return self.entries.get(id_)

    @property
    def ids(self):
        return [
            key
            for key, entry in self.entries.items()
            if isinstance(entry, (Map, dict)) and entry.get("id") == key
        ]

    @property
    def count(self):
        return len(self.ids)

    @property
    def rows(self):
        return [self.by_id(id_) for id_ in self.ids]

    def filter(self, fn):
        return [row for row in self.rows if fn(row)]

    def find(self, fn):
        for row in self.rows:
            if fn(row):
                return row
        return None

    def map(self, fn):
        return [fn(row) for row in self.rows]

    def sort(self, arg=None):
        """Sorts rows by a compare-key function, a column name, a list of
        column names, or by row ID (table.js:103)."""
        if callable(arg):
            return sorted(self.rows, key=arg)
        if isinstance(arg, str):
            return sorted(self.rows, key=lambda row: row.get(arg))
        if isinstance(arg, list):
            return sorted(self.rows, key=lambda row: [row.get(col) for col in arg])
        if arg is None:
            return sorted(self.rows, key=lambda row: row.get("id"))
        raise TypeError(f"Unsupported sorting argument: {arg!r}")

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return self.count

    def add(self, row):
        raise TypeError("A table can only be modified in a change block")

    def remove(self, id_):
        raise TypeError("A table can only be modified in a change block")

    def _set(self, id_, value, op_id):
        if isinstance(value, Map):
            dict.__setitem__(value, "id", id_)
        elif isinstance(value, dict):
            value["id"] = id_
        self.entries[id_] = value
        self.op_ids[id_] = op_id

    def _remove(self, id_):
        self.entries.pop(id_, None)
        self.op_ids.pop(id_, None)

    def _clone(self):
        if self._object_id is None:
            raise RuntimeError("clone() requires the objectId to be set")
        return instantiate_table(self._object_id, dict(self.entries), dict(self.op_ids))

    def to_dict(self):
        return {id_: self.by_id(id_) for id_ in self.ids}

    def __eq__(self, other):
        return isinstance(other, Table) and self.entries == other.entries

    def __repr__(self):
        return f"Table({len(self.entries)} rows)"


def instantiate_table(object_id, entries=None, op_ids=None):
    if not object_id:
        raise ValueError("instantiate_table requires an objectId to be given")
    table = Table()
    table._object_id = object_id
    table.entries = entries if entries is not None else {}
    table.op_ids = op_ids if op_ids is not None else {}
    return table


class WriteableTable:
    """Table view bound to a change context (table.js:217)."""

    def __init__(self, context, path, table):
        self.context = context
        self.path = path
        self.table = table
        self._object_id = table._object_id

    @property
    def count(self):
        return self.table.count

    @property
    def ids(self):
        return self.table.ids

    def by_id(self, id_):
        entry = self.table.entries.get(id_)
        if isinstance(entry, (Map, dict)) and entry.get("id") == id_:
            object_id = entry._object_id
            path = self.path + [{"key": id_, "objectId": object_id}]
            return self.context.instantiate_object(path, object_id)
        return None

    def add(self, row):
        return self.context.add_table_row(self.path, row)

    def remove(self, id_):
        entry = self.table.entries.get(id_)
        if isinstance(entry, (Map, dict)) and entry.get("id") == id_:
            self.context.delete_table_row(self.path, id_, self.table.op_ids[id_])
        else:
            raise KeyError(f"There is no row with ID {id_} in this table")

    @property
    def rows(self):
        return [self.by_id(id_) for id_ in self.ids]


DateValue = _dt.datetime


def timestamp_to_datetime(ms: int) -> _dt.datetime:
    return _dt.datetime.fromtimestamp(ms / 1000.0, tz=_dt.timezone.utc)


def datetime_to_timestamp(value: _dt.datetime) -> int:
    return round(value.timestamp() * 1000)
