"""Mutation context: records operations as the user mutates proxy objects in
a change block, and optimistically applies the corresponding patch.

Port of /root/reference/frontend/context.js.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import datetime as _dt

from ..uuid import make_uuid
from ..common import parse_op_id
from .apply_patch import interpret_patch
from .datatypes import (
    Counter,
    Float64,
    Int,
    List,
    Map,
    Table,
    Text,
    Uint,
    WriteableCounter,
    datetime_to_timestamp,
)

MAX_SAFE = 2**53 - 1


def _is_primitive(value):
    return value is None or isinstance(value, (str, bool, int, float))


def _strict_equals(a, b):
    """JS === semantics: value equality for primitives, identity for objects."""
    if _is_primitive(a) and _is_primitive(b):
        if isinstance(a, bool) or isinstance(b, bool):
            return a is b
        return a == b and (a is not None) == (b is not None)
    return a is b


class Context:
    def __init__(self, doc, actor_id, apply_patch_fn=None):
        self.actor_id = actor_id
        self.next_op_num = doc._state["maxOp"] + 1
        self.cache = doc._cache
        self.updated = {}
        self.ops = []
        self.apply_patch = apply_patch_fn if apply_patch_fn is not None else interpret_patch
        self.instantiate_object = None  # installed by proxies.root_object_proxy

    def add_op(self, operation):
        self.ops.append(operation)
        if operation["action"] == "set" and "values" in operation:
            self.next_op_num += len(operation["values"])
        elif operation["action"] == "del" and "multiOp" in operation:
            self.next_op_num += operation["multiOp"]
        else:
            self.next_op_num += 1

    def next_op_id(self):
        return f"{self.next_op_num}@{self.actor_id}"

    def get_value_description(self, value):
        """Describes a value in patch format (context.js:51)."""
        if isinstance(value, bool) or value is None or isinstance(value, str):
            return {"type": "value", "value": value}
        if isinstance(value, _dt.datetime):
            return {"type": "value", "value": datetime_to_timestamp(value), "datatype": "timestamp"}
        if isinstance(value, Int):
            return {"type": "value", "value": value.value, "datatype": "int"}
        if isinstance(value, Uint):
            return {"type": "value", "value": value.value, "datatype": "uint"}
        if isinstance(value, Float64):
            return {"type": "value", "value": value.value, "datatype": "float64"}
        if isinstance(value, Counter):
            return {"type": "value", "value": value.value, "datatype": "counter"}
        if isinstance(value, int):
            if -MAX_SAFE <= value <= MAX_SAFE:
                return {"type": "value", "value": value, "datatype": "int"}
            return {"type": "value", "value": float(value), "datatype": "float64"}
        if isinstance(value, float):
            return {"type": "value", "value": value, "datatype": "float64"}
        if isinstance(value, (Map, List, Text, Table, dict, list, tuple)):
            object_id = getattr(value, "_object_id", None)
            if object_id is None:
                raise ValueError(f"Object {value!r} has no objectId")
            type_ = self.get_object_type(object_id)
            if type_ in ("list", "text"):
                return {"objectId": object_id, "type": type_, "edits": []}
            return {"objectId": object_id, "type": type_, "props": {}}
        raise TypeError(f"Unsupported type of value: {type(value).__name__}")

    def get_values_descriptions(self, path, obj, key):
        """All conflicting values of a property, as opId -> description
        (context.js:100)."""
        if isinstance(obj, Table):
            value = obj.by_id(key)
            op_id = obj.op_ids.get(key)
            return {op_id: self.get_value_description(value)} if value is not None else {}
        if isinstance(obj, Text):
            value = obj.get(key)
            elem_id = obj.get_elem_id(key)
            return {elem_id: self.get_value_description(value)} if value is not None else {}
        conflicts = obj._conflicts[key] if isinstance(obj, Map) else obj._conflicts[key]
        if conflicts is None:
            raise ValueError(f"No children at key {key} of path {path}")
        return {op_id: self.get_value_description(v) for op_id, v in conflicts.items()}

    def get_property_value(self, obj, key, op_id):
        if isinstance(obj, Table):
            return obj.by_id(key)
        if isinstance(obj, Text):
            return obj.get(key)
        return obj._conflicts[key][op_id]

    def get_subpatch(self, patch, path):
        """Returns the subpatch at `path`, creating nodes as needed
        (context.js:142)."""
        if not path:
            return patch
        subpatch = patch
        obj = self.get_object("_root")
        for path_elem in path:
            key = path_elem["key"]
            values = self.get_values_descriptions(path, obj, key)
            if "props" in subpatch:
                if key not in subpatch["props"]:
                    subpatch["props"][key] = values
            elif "edits" in subpatch:
                for op_id, value in values.items():
                    subpatch["edits"].append(
                        {"action": "update", "index": key, "opId": op_id, "value": value}
                    )
            next_op_id = None
            for op_id, value in values.items():
                if value.get("objectId") == path_elem["objectId"]:
                    next_op_id = op_id
            if next_op_id is None:
                raise ValueError(f"Cannot find path object with objectId {path_elem['objectId']}")
            subpatch = values[next_op_id]
            obj = self.get_property_value(obj, key, next_op_id)
        return subpatch

    def get_object(self, object_id):
        obj = self.updated.get(object_id) or self.cache.get(object_id)
        if obj is None:
            raise ValueError(f"Target object does not exist: {object_id}")
        return obj

    def get_object_type(self, object_id):
        if object_id == "_root":
            return "map"
        obj = self.get_object(object_id)
        if isinstance(obj, Text):
            return "text"
        if isinstance(obj, Table):
            return "table"
        if isinstance(obj, (List, list)) and not isinstance(obj, Map):
            return "list"
        return "map"

    def get_object_field(self, path, object_id, key):
        """Returns the value of a field, wrapping objects in proxies."""
        obj = self.get_object(object_id)
        try:
            value = obj[key]
        except (KeyError, IndexError):
            return None
        if isinstance(value, Counter):
            return WriteableCounter(value.value, self, path, object_id, key)
        if isinstance(value, (Map, List, Text, Table)):
            child_id = value._object_id
            subpath = path + [{"key": key, "objectId": child_id}]
            return self.instantiate_object(subpath, child_id)
        return value

    def create_nested_objects(self, obj, key, value, insert, pred, elem_id=None):
        """Recursively creates document objects for a new value tree
        (context.js:230)."""
        if getattr(value, "_object_id", None):
            raise ValueError("Cannot create a reference to an existing document object")
        object_id = self.next_op_id()

        if isinstance(value, Text):
            op = {"action": "makeText", "obj": obj, "insert": insert, "pred": pred}
            if elem_id is not None:
                op["elemId"] = elem_id
            else:
                op["key"] = key
            self.add_op(op)
            subpatch = {"objectId": object_id, "type": "text", "edits": []}
            self.insert_list_items(subpatch, 0, [e["value"] for e in value.elems], True)
            return subpatch

        if isinstance(value, Table):
            if value.count > 0:
                raise ValueError("Assigning a non-empty Table object is not supported")
            op = {"action": "makeTable", "obj": obj, "insert": insert, "pred": pred}
            if elem_id is not None:
                op["elemId"] = elem_id
            else:
                op["key"] = key
            self.add_op(op)
            return {"objectId": object_id, "type": "table", "props": {}}

        if isinstance(value, (list, tuple)) and not isinstance(value, Map):
            op = {"action": "makeList", "obj": obj, "insert": insert, "pred": pred}
            if elem_id is not None:
                op["elemId"] = elem_id
            else:
                op["key"] = key
            self.add_op(op)
            subpatch = {"objectId": object_id, "type": "list", "edits": []}
            self.insert_list_items(subpatch, 0, list(value), True)
            return subpatch

        # Map object
        op = {"action": "makeMap", "obj": obj, "insert": insert, "pred": pred}
        if elem_id is not None:
            op["elemId"] = elem_id
        else:
            op["key"] = key
        self.add_op(op)
        props = {}
        for nested in sorted(value.keys()):
            op_id = self.next_op_id()
            value_patch = self.set_value(object_id, nested, value[nested], False, [])
            props[nested] = {op_id: value_patch}
        return {"objectId": object_id, "type": "map", "props": props}

    def set_value(self, object_id, key, value, insert, pred, elem_id=None):
        """Records an assignment and returns its value patch (context.js:289)."""
        if not object_id:
            raise ValueError("set_value needs an objectId")
        if key == "":
            raise ValueError("The key of a map entry must not be an empty string")

        if (
            isinstance(value, (dict, list, tuple, Map, List, Text, Table))
            and not isinstance(value, _dt.datetime)
        ):
            return self.create_nested_objects(object_id, key, value, insert, pred, elem_id)

        description = self.get_value_description(value)
        op = {"action": "set", "obj": object_id, "insert": insert, "value": description["value"], "pred": pred}
        if elem_id is not None:
            op["elemId"] = elem_id
        else:
            op["key"] = key
        if description.get("datatype") is not None:
            op["datatype"] = description["datatype"]
        self.add_op(op)
        return description

    def apply_at_path(self, path, callback):
        diff = {"objectId": "_root", "type": "map", "props": {}}
        callback(self.get_subpatch(diff, path))
        self.apply_patch(diff, self.cache["_root"], self.updated)

    def set_map_key(self, path, key, value):
        if not isinstance(key, str):
            raise TypeError(f"The key of a map entry must be a string, not {type(key).__name__}")
        object_id = "_root" if not path else path[-1]["objectId"]
        obj = self.get_object(object_id)
        if isinstance(obj.get(key), Counter):
            raise ValueError(
                "Cannot overwrite a Counter object; use increment() or decrement() to change its value."
            )
        if (
            not _strict_equals(obj.get(key), value)
            or len(obj._conflicts.get(key) or {}) > 1
            or value is None and key not in obj
        ):
            def cb(subpatch):
                pred = get_pred(obj, key)
                op_id = self.next_op_id()
                value_patch = self.set_value(object_id, key, value, False, pred)
                subpatch["props"][key] = {op_id: value_patch}

            self.apply_at_path(path, cb)

    def delete_map_key(self, path, key):
        object_id = "_root" if not path else path[-1]["objectId"]
        obj = self.get_object(object_id)
        if key in obj:
            pred = get_pred(obj, key)
            self.add_op({"action": "del", "obj": object_id, "key": key, "insert": False, "pred": pred})

            def cb(subpatch):
                subpatch["props"][key] = {}

            self.apply_at_path(path, cb)

    def insert_list_items(self, subpatch, index, values, new_object):
        """Inserts elements into a list/text, emitting multi-insert ops where
        all values are primitives of one datatype (context.js:370)."""
        lst = [] if new_object else self.get_object(subpatch["objectId"])
        if index < 0 or index > len(lst):
            raise IndexError(f"List index {index} is out of bounds for list of length {len(lst)}")
        if not values:
            return

        elem_id = get_elem_id(lst, index, insert=True)
        all_primitive = all(
            isinstance(v, (str, bool, int, float, _dt.datetime, Counter, Int, Uint, Float64))
            or v is None
            for v in values
        )
        descriptions = [self.get_value_description(v) for v in values] if all_primitive else []
        datatypes_same = all(
            d.get("datatype") == descriptions[0].get("datatype") for d in descriptions
        ) if descriptions else False

        if all_primitive and datatypes_same and len(values) > 1:
            next_elem_id = self.next_op_id()
            datatype = descriptions[0].get("datatype")
            plain_values = [d["value"] for d in descriptions]
            op = {"action": "set", "obj": subpatch["objectId"], "elemId": elem_id, "insert": True,
                  "values": plain_values, "pred": []}
            edit = {"action": "multi-insert", "elemId": next_elem_id, "index": index, "values": plain_values}
            if datatype is not None:
                op["datatype"] = datatype
                edit["datatype"] = datatype
            self.add_op(op)
            subpatch["edits"].append(edit)
        else:
            for offset, value in enumerate(values):
                next_elem_id = self.next_op_id()
                value_patch = self.set_value(
                    subpatch["objectId"], index + offset, value, True, [], elem_id
                )
                elem_id = next_elem_id
                subpatch["edits"].append(
                    {"action": "insert", "index": index + offset, "elemId": elem_id,
                     "opId": elem_id, "value": value_patch}
                )

    def set_list_index(self, path, index, value):
        object_id = "_root" if not path else path[-1]["objectId"]
        lst = self.get_object(object_id)
        if index >= len(lst):
            insertions = [None] * (index - len(lst))
            insertions.append(value)
            return self.splice(path, len(lst), 0, insertions)
        current = lst.get(index) if isinstance(lst, Text) else lst[index]
        if isinstance(current, Counter):
            raise ValueError(
                "Cannot overwrite a Counter object; use increment() or decrement() to change its value."
            )
        conflicts = lst._conflicts[index] if not isinstance(lst, Text) and index < len(lst._conflicts) else None
        if not _strict_equals(current, value) or len(conflicts or {}) > 1 or value is None:
            def cb(subpatch):
                pred = get_pred(lst, index)
                op_id = self.next_op_id()
                value_patch = self.set_value(object_id, index, value, False, pred, get_elem_id(lst, index))
                subpatch["edits"].append({"action": "update", "index": index, "opId": op_id, "value": value_patch})

            self.apply_at_path(path, cb)

    def splice(self, path, start, deletions, insertions):
        """Deletes `deletions` elements at `start` and inserts `insertions`
        (context.js:441). Consecutive deletions compress into multiOp dels."""
        object_id = "_root" if not path else path[-1]["objectId"]
        lst = self.get_object(object_id)
        length = len(lst)
        if start < 0 or deletions < 0 or start > length - deletions:
            raise IndexError(
                f"{deletions} deletions starting at index {start} are out of bounds "
                f"for list of length {length}"
            )
        if deletions == 0 and not insertions:
            return
        patch = {"diffs": {"objectId": "_root", "type": "map", "props": {}}}
        subpatch = self.get_subpatch(patch["diffs"], path)

        if deletions > 0:
            op = None
            last_elem_parsed = None
            last_pred_parsed = None
            for i in range(deletions):
                if isinstance(self.get_object_field(path, object_id, start + i), Counter):
                    raise TypeError("Unsupported operation: deleting a counter from a list")
                this_elem = get_elem_id(lst, start + i)
                this_elem_parsed = parse_op_id(this_elem)
                this_pred = get_pred(lst, start + i)
                this_pred_parsed = parse_op_id(this_pred[0]) if len(this_pred) == 1 else None
                if (
                    op is not None
                    and last_elem_parsed is not None
                    and last_pred_parsed is not None
                    and this_pred_parsed is not None
                    and last_elem_parsed.actor_id == this_elem_parsed.actor_id
                    and last_elem_parsed.counter + 1 == this_elem_parsed.counter
                    and last_pred_parsed.actor_id == this_pred_parsed.actor_id
                    and last_pred_parsed.counter + 1 == this_pred_parsed.counter
                ):
                    op["multiOp"] = op.get("multiOp", 1) + 1
                else:
                    if op is not None:
                        self.add_op(op)
                    op = {"action": "del", "obj": object_id, "elemId": this_elem,
                          "insert": False, "pred": this_pred}
                last_elem_parsed = this_elem_parsed
                last_pred_parsed = this_pred_parsed
            self.add_op(op)
            subpatch["edits"].append({"action": "remove", "index": start, "count": deletions})

        if insertions:
            self.insert_list_items(subpatch, start, insertions, False)
        self.apply_patch(patch["diffs"], self.cache["_root"], self.updated)

    def add_table_row(self, path, row):
        """Adds a row to a table; returns its generated UUID (context.js:508)."""
        if not isinstance(row, (dict, Map)) or isinstance(row, (list, List)):
            raise TypeError("A table row must be a map")
        if getattr(row, "_object_id", None):
            raise TypeError("Cannot reuse an existing object as table row")
        if "id" in row:
            raise TypeError('A table row must not have an "id" property; it is generated automatically')

        id_ = make_uuid()
        value_patch = self.set_value(path[-1]["objectId"], id_, row, False, [])

        def cb(subpatch):
            subpatch["props"][id_] = {value_patch["objectId"]: value_patch}

        self.apply_at_path(path, cb)
        return id_

    def delete_table_row(self, path, row_id, pred):
        object_id = path[-1]["objectId"]
        table = self.get_object(object_id)
        if table.by_id(row_id):
            self.add_op({"action": "del", "obj": object_id, "key": row_id, "insert": False, "pred": [pred]})

            def cb(subpatch):
                subpatch["props"][row_id] = {}

            self.apply_at_path(path, cb)

    def increment(self, path, key, delta):
        object_id = "_root" if not path else path[-1]["objectId"]
        obj = self.get_object(object_id)
        current = obj.get(key) if isinstance(obj, (Map, dict)) else obj[key]
        if not isinstance(current, Counter):
            raise TypeError("Only counter values can be incremented")

        type_ = self.get_object_type(object_id)
        value = current.value + delta
        op_id = self.next_op_id()
        pred = get_pred(obj, key)

        if type_ in ("list", "text"):
            elem_id = get_elem_id(obj, key, insert=False)
            self.add_op({"action": "inc", "obj": object_id, "elemId": elem_id, "value": delta,
                         "insert": False, "pred": pred})
        else:
            self.add_op({"action": "inc", "obj": object_id, "key": key, "value": delta,
                         "insert": False, "pred": pred})

        def cb(subpatch):
            if type_ in ("list", "text"):
                subpatch["edits"].append({"action": "update", "index": key, "opId": op_id,
                                          "value": {"value": value, "datatype": "counter"}})
            else:
                subpatch["props"][key] = {op_id: {"value": value, "datatype": "counter"}}

        self.apply_at_path(path, cb)


def get_pred(obj, key):
    """Previous operation IDs for a property (context.js:576)."""
    if isinstance(obj, Table):
        return [obj.op_ids[key]]
    if isinstance(obj, Text):
        return obj.elems[key]["pred"]
    if isinstance(obj, Map):
        return list(obj._conflicts[key].keys()) if obj._conflicts.get(key) else []
    if isinstance(obj, List):
        if key < len(obj._conflicts) and obj._conflicts[key]:
            return list(obj._conflicts[key].keys())
        return []
    return []


def get_elem_id(lst, index, insert=False):
    """Element ID at a list index (context.js:588)."""
    if insert:
        if index == 0:
            return "_head"
        index -= 1
    if isinstance(lst, Text):
        return lst.get_elem_id(index)
    if isinstance(lst, List):
        return lst._elem_ids[index]
    if isinstance(lst, list) and not lst:
        raise IndexError(f"Cannot find elemId at list index {index}")
    raise IndexError(f"Cannot find elemId at list index {index}")
