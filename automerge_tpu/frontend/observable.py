"""Per-object change subscription by patch-walking
(port of /root/reference/frontend/observable.js)."""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations


def _conflict_at(obj, key, op_id):
    conflicts = getattr(obj, "_conflicts", None)
    if conflicts is None:
        return None
    try:
        entry = conflicts[key]
    except (KeyError, IndexError, TypeError):
        return None
    if isinstance(entry, dict):
        return entry.get(op_id)
    return None


class Observable:
    """Allows callbacks to be registered for particular objects; when a patch
    touches such an object, the callback fires with the sub-diff and the
    before/after object states."""

    def __init__(self):
        self.observers = {}  # objectId -> list of callbacks

    def patch_callback(self, patch, before, after, local, changes):
        self._object_update(patch["diffs"], before, after, local, changes)

    def _object_update(self, diff, before, after, local, changes):
        if not isinstance(diff, dict) or not diff.get("objectId"):
            return
        for callback in self.observers.get(diff["objectId"], []):
            callback(diff, before, after, local, changes)

        type_ = diff.get("type")
        if type_ == "map" and diff.get("props"):
            for prop_name, prop in diff["props"].items():
                for op_id, subdiff in prop.items():
                    self._object_update(
                        subdiff,
                        _conflict_at(before, prop_name, op_id),
                        _conflict_at(after, prop_name, op_id),
                        local, changes,
                    )
        elif type_ == "table" and diff.get("props"):
            for row_id, prop in diff["props"].items():
                for op_id, subdiff in prop.items():
                    self._object_update(
                        subdiff,
                        before.by_id(row_id) if before is not None else None,
                        after.by_id(row_id) if after is not None else None,
                        local, changes,
                    )
        elif type_ == "list" and diff.get("edits"):
            offset = 0
            for edit in diff["edits"]:
                action = edit["action"]
                if action == "insert":
                    offset -= 1
                    self._object_update(
                        edit["value"], None,
                        _conflict_at(after, edit["index"], edit["elemId"]),
                        local, changes,
                    )
                elif action == "multi-insert":
                    offset -= len(edit["values"])
                elif action == "update":
                    self._object_update(
                        edit["value"],
                        _conflict_at(before, edit["index"] + offset, edit["opId"]),
                        _conflict_at(after, edit["index"], edit["opId"]),
                        local, changes,
                    )
                elif action == "remove":
                    offset += edit["count"]
        elif type_ == "text" and diff.get("edits"):
            offset = 0
            for edit in diff["edits"]:
                action = edit["action"]
                if action == "insert":
                    offset -= 1
                    self._object_update(
                        edit["value"], None,
                        after.get(edit["index"]) if after is not None else None,
                        local, changes,
                    )
                elif action == "multi-insert":
                    offset -= len(edit["values"])
                elif action == "update":
                    self._object_update(
                        edit["value"],
                        before.get(edit["index"] + offset) if before is not None else None,
                        after.get(edit["index"]) if after is not None else None,
                        local, changes,
                    )
                elif action == "remove":
                    offset += edit["count"]

    def observe(self, obj, callback):
        object_id = getattr(obj, "_object_id", None)
        if not object_id:
            raise TypeError("The observed object must be part of an Automerge document")
        self.observers.setdefault(object_id, []).append(callback)
