"""Patch interpreter: applies backend diffs to the immutable document tree.

Port of /root/reference/frontend/apply_patch.js. Conflict resolution picks
the value with the greatest Lamport opId (apply_patch.js:57-77).
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from ..common import lamport_compare_key, parse_op_id
from .datatypes import (
    Counter,
    List,
    Map,
    Table,
    Text,
    instantiate_table,
    instantiate_text,
    timestamp_to_datetime,
)


def get_value(patch, obj, updated):
    """Reconstructs a value from a value-or-object patch (apply_patch.js:10)."""
    if patch.get("objectId"):
        if obj is not None and getattr(obj, "_object_id", None) != patch["objectId"]:
            obj = None
        return interpret_patch(patch, obj, updated)
    if patch.get("datatype") == "timestamp":
        return timestamp_to_datetime(patch["value"])
    if patch.get("datatype") == "counter":
        return Counter(patch["value"])
    return patch.get("value")


def _lamport_key(op_id):
    return lamport_compare_key(op_id)


def apply_properties(props, obj, conflicts, updated):
    """Applies a `props` diff to a map object, updating values and the
    conflicts structure (apply_patch.js:57)."""
    if not props:
        return
    for key, prop in props.items():
        values = {}
        op_ids = sorted(prop.keys(), key=_lamport_key, reverse=True)
        for op_id in op_ids:
            subpatch = prop[op_id]
            if conflicts.get(key) and op_id in conflicts[key]:
                values[op_id] = get_value(subpatch, conflicts[key][op_id], updated)
            else:
                values[op_id] = get_value(subpatch, None, updated)
        if not op_ids:
            if key in obj:
                obj._unsafe_delete(key)
            conflicts.pop(key, None)
        else:
            obj._unsafe_set(key, values[op_ids[0]])
            conflicts[key] = values


def _clone_map_object(original, object_id):
    obj = Map(original if original is not None else {})
    obj._object_id = object_id
    obj._conflicts = dict(original._conflicts) if original is not None else {}
    return obj


def update_map_object(patch, obj, updated):
    object_id = patch["objectId"]
    if object_id not in updated:
        updated[object_id] = _clone_map_object(obj, object_id)
    target = updated[object_id]
    apply_properties(patch.get("props"), target, target._conflicts, updated)
    return target


def update_table_object(patch, obj, updated):
    object_id = patch["objectId"]
    if object_id not in updated:
        updated[object_id] = obj._clone() if obj is not None else instantiate_table(object_id)
    table = updated[object_id]
    for key, prop in (patch.get("props") or {}).items():
        op_ids = list(prop.keys())
        if not op_ids:
            table._remove(key)
        elif len(op_ids) == 1:
            subpatch = prop[op_ids[0]]
            table._set(key, get_value(subpatch, table.by_id(key), updated), op_ids[0])
        else:
            raise ValueError("Conflicts are not supported on properties of a table")
    return table


def _clone_list_object(original, object_id):
    lst = List(original if original is not None else [])
    lst._object_id = object_id
    lst._conflicts = list(original._conflicts) if original is not None else []
    lst._elem_ids = list(original._elem_ids) if original is not None else []
    return lst


def update_list_object(patch, obj, updated):
    object_id = patch["objectId"]
    if object_id not in updated:
        updated[object_id] = _clone_list_object(obj, object_id)
    lst = updated[object_id]
    conflicts = lst._conflicts
    elem_ids = lst._elem_ids
    base = super(List, lst)

    edits = patch["edits"]
    i = 0
    while i < len(edits):
        edit = edits[i]
        action = edit["action"]
        if action in ("insert", "update"):
            old_value = None
            if edit["index"] < len(conflicts) and conflicts[edit["index"]]:
                old_value = conflicts[edit["index"]].get(edit["opId"])
            last_value = get_value(edit["value"], old_value, updated)
            values = {edit["opId"]: last_value}
            # Successive updates for the same index indicate a conflict; edits
            # are sorted by Lamport timestamp so the last one wins
            while i < len(edits) - 1 and edits[i + 1]["index"] == edit["index"] \
                    and edits[i + 1]["action"] == "update":
                i += 1
                conflict = edits[i]
                old_value2 = None
                if conflict["index"] < len(conflicts) and conflicts[conflict["index"]]:
                    old_value2 = conflicts[conflict["index"]].get(conflict["opId"])
                last_value = get_value(conflict["value"], old_value2, updated)
                values[conflict["opId"]] = last_value
            if action == "insert":
                base.insert(edit["index"], last_value)
                conflicts.insert(edit["index"], values)
                elem_ids.insert(edit["index"], edit["elemId"])
            else:
                base.__setitem__(edit["index"], last_value)
                conflicts[edit["index"]] = values
        elif action == "multi-insert":
            start = parse_op_id(edit["elemId"])
            datatype = edit.get("datatype")
            new_elems, new_values, new_conflicts = [], [], []
            for offset, value in enumerate(edit["values"]):
                elem_id = f"{start.counter + offset}@{start.actor_id}"
                value = get_value({"value": value, "datatype": datatype}, None, updated)
                new_values.append(value)
                entry = {"value": value, "type": "value"}
                if datatype is not None:
                    entry["datatype"] = datatype
                new_conflicts.append({elem_id: entry})
                new_elems.append(elem_id)
            base.__setitem__(slice(edit["index"], edit["index"]), new_values)
            conflicts[edit["index"] : edit["index"]] = new_conflicts
            elem_ids[edit["index"] : edit["index"]] = new_elems
        elif action == "remove":
            base.__delitem__(slice(edit["index"], edit["index"] + edit["count"]))
            del conflicts[edit["index"] : edit["index"] + edit["count"]]
            del elem_ids[edit["index"] : edit["index"] + edit["count"]]
        i += 1
    return lst


def update_text_object(patch, obj, updated):
    object_id = patch["objectId"]
    if object_id in updated:
        elems = updated[object_id].elems
    elif obj is not None:
        elems = list(obj.elems)
    else:
        elems = []

    for edit in patch["edits"]:
        action = edit["action"]
        if action == "insert":
            value = get_value(edit["value"], None, updated)
            elems.insert(edit["index"], {"elemId": edit["elemId"], "pred": [edit["opId"]], "value": value})
        elif action == "multi-insert":
            start = parse_op_id(edit["elemId"])
            datatype = edit.get("datatype")
            new_elems = []
            for offset, value in enumerate(edit["values"]):
                value = get_value({"datatype": datatype, "value": value}, None, updated)
                elem_id = f"{start.counter + offset}@{start.actor_id}"
                new_elems.append({"elemId": elem_id, "pred": [elem_id], "value": value})
            elems[edit["index"] : edit["index"]] = new_elems
        elif action == "update":
            elem_id = elems[edit["index"]]["elemId"]
            value = get_value(edit["value"], elems[edit["index"]]["value"], updated)
            elems[edit["index"]] = {"elemId": elem_id, "pred": [edit["opId"]], "value": value}
        elif action == "remove":
            del elems[edit["index"] : edit["index"] + edit["count"]]

    updated[object_id] = instantiate_text(object_id, elems)
    return updated[object_id]


def interpret_patch(patch, obj, updated):
    """Applies a patch to the read-only object `obj`, placing a writable copy
    in `updated` (apply_patch.js:266)."""
    if (
        obj is not None
        and not patch.get("props")
        and not patch.get("edits")
        and patch["objectId"] not in updated
    ):
        return obj

    type_ = patch["type"]
    if type_ == "map":
        return update_map_object(patch, obj, updated)
    if type_ == "table":
        return update_table_object(patch, obj, updated)
    if type_ == "list":
        return update_list_object(patch, obj, updated)
    if type_ == "text":
        return update_text_object(patch, obj, updated)
    raise TypeError(f"Unknown object type: {type_}")


def clone_root_object(root):
    if root._object_id != "_root":
        raise ValueError(f"Not the root object: {root._object_id}")
    return _clone_map_object(root, "_root")
