"""The OpSet engine: stores all operations of all time and merges incoming
changes, emitting frontend patches.

Semantically equivalent to the reference engine (/root/reference/backend/new.js,
class BackendDoc), but re-architected: instead of RLE-columnar blocks of <=600
ops with Bloom-filter skip metadata, the document is a flat Python list of
fixed-width op rows in the same total order the reference maintains:

  - ops grouped by object: root-object ops first, then objects ordered by
    (counter, actorId) of their objectId  (new.js:59-74 seek order)
  - within a map object: keys in UTF-16 code-unit order, multiple ops on one
    key in ascending opId order  (new.js:1153-1224)
  - within a list object: elements in RGA document order, each element's ops
    (insert op then updates) in ascending opId order  (new.js:144-190)

This flat dense-row form is also the transcoding source for the TPU engine's
op tensors (automerge_tpu/tpu). Deletion is not a row: a 'del' op only appends
its opId to the succ lists of the ops it overwrites (new.js:1204-1217); an op
is visible iff it has no successors.

Patch generation reproduces the reference's incremental patch state machine
(updatePatchProperty, appendEdit/appendUpdate/convertInsertToUpdate,
new.js:747-1040) exactly, so patches are bit-identical JSON.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from .columnar import (
    ACTIONS,
    CHANGE_COLUMNS,
    DOC_OPS_COLUMNS,
    DOCUMENT_COLUMNS,
    OBJECT_TYPE,
    ColumnType,
    ValueType,
    ParsedOpId,
    decode_change_columns,
    decode_change_meta,
    decode_changes,
    decode_columns,
    decode_document_header,
    decode_value,
    encode_change,
    encode_document_header,
    encoder_by_column_id,
    make_decoders,
)
from .codecs import Encoder
from .common import parse_op_id, utf16_key
from .errors import CausalityError, DecodeError

# Row field indices, matching the doc/change column layout (new.js:10-12)
OBJ_ACTOR, OBJ_CTR, KEY_ACTOR, KEY_CTR, KEY_STR = 0, 1, 2, 3, 4
ID_ACTOR, ID_CTR, INSERT, ACTION, VAL_LEN, VAL_RAW = 5, 6, 7, 8, 9, 10
CHLD_ACTOR, CHLD_CTR = 11, 12
SUCC_NUM, SUCC_ACTOR, SUCC_CTR = 13, 14, 15
PRED_NUM, PRED_ACTOR, PRED_CTR = 13, 14, 15

_SET = ACTIONS.index("set")
_DEL = ACTIONS.index("del")
_INC = ACTIONS.index("inc")


def _empty_object_patch(object_id, type_):
    if type_ in ("list", "text"):
        return {"objectId": object_id, "type": type_, "edits": []}
    return {"objectId": object_id, "type": type_, "props": {}}


def _deep_copy_update(tree, path, value):
    """Updates tree[path[0]][path[1]][...] = value, copying nested nodes so
    previous versions are not mutated (new.js:24)."""
    if len(path) == 1:
        tree[path[0]] = value
    else:
        child = dict(tree.get(path[0]) or {})
        _deep_copy_update(child, path[1:], value)
        tree[path[0]] = child


def _op_id_delta(id1, id2, delta=1):
    p1, p2 = parse_op_id(id1), parse_op_id(id2)
    return p1.actor_id == p2.actor_id and p1.counter + delta == p2.counter


def append_edit(existing_edits, next_edit):
    """Appends a list edit, extending the last edit into a multi-op where
    possible (new.js:747)."""
    if not existing_edits:
        existing_edits.append(next_edit)
        return
    last = existing_edits[-1]
    if (
        last["action"] == "insert"
        and next_edit["action"] == "insert"
        and last["index"] == next_edit["index"] - 1
        and last["value"].get("type") == "value"
        and next_edit["value"].get("type") == "value"
        and last["elemId"] == last["opId"]
        and next_edit["elemId"] == next_edit["opId"]
        and _op_id_delta(last["elemId"], next_edit["elemId"], 1)
        and last["value"].get("datatype") == next_edit["value"].get("datatype")
        and type(last["value"].get("value")) is type(next_edit["value"].get("value"))
    ):
        last["action"] = "multi-insert"
        if next_edit["value"].get("datatype") is not None:
            last["datatype"] = next_edit["value"]["datatype"]
        last["values"] = [last["value"]["value"], next_edit["value"]["value"]]
        del last["value"]
        del last["opId"]
    elif (
        last["action"] == "multi-insert"
        and next_edit["action"] == "insert"
        and last["index"] + len(last["values"]) == next_edit["index"]
        and next_edit["value"].get("type") == "value"
        and next_edit["elemId"] == next_edit["opId"]
        and _op_id_delta(last["elemId"], next_edit["elemId"], len(last["values"]))
        and last.get("datatype") == next_edit["value"].get("datatype")
        and type(last["values"][0]) is type(next_edit["value"].get("value"))
    ):
        last["values"].append(next_edit["value"]["value"])
    elif (
        last["action"] == "remove"
        and next_edit["action"] == "remove"
        and last["index"] == next_edit["index"]
    ):
        last["count"] += next_edit["count"]
    else:
        existing_edits.append(next_edit)


def append_update(edits, index, elem_id, op_id, value, first_update):
    """Appends an UpdateEdit; conflicting values are consecutive edits with the
    same index (new.js:798)."""
    insert = False
    if first_update:
        while not insert and edits:
            last = edits[-1]
            if last["action"] in ("insert", "update") and last["index"] == index:
                edits.pop()
                insert = last["action"] == "insert"
            elif last["action"] == "multi-insert" and last["index"] + len(last["values"]) - 1 == index:
                last["values"].pop()
                insert = True
            else:
                break
    if insert:
        append_edit(edits, {"action": "insert", "index": index, "elemId": elem_id, "opId": op_id, "value": value})
    else:
        append_edit(edits, {"action": "update", "index": index, "opId": op_id, "value": value})


def convert_insert_to_update(edits, index, elem_id):
    """Rewrites a trailing insert-plus-updates suffix at `index` into updates
    (new.js:838)."""
    updates = []
    while edits:
        last = edits[-1]
        if last["action"] == "insert":
            if last["index"] != index:
                raise ValueError("last edit has unexpected index")  # amlint: disable=AM401 — internal edit-stream invariant, not a data fault
            updates.insert(0, edits.pop())
            break
        elif last["action"] == "update":
            if last["index"] != index:
                raise ValueError("last edit has unexpected index")  # amlint: disable=AM401 — internal edit-stream invariant, not a data fault
            updates.insert(0, edits.pop())
        else:
            raise ValueError("last edit has unexpected action")  # amlint: disable=AM401 — internal edit-stream invariant, not a data fault
    first_update = True
    for update in updates:
        append_update(edits, index, elem_id, update["opId"], update["value"], first_update)
        first_update = False


class _DocState:
    """Working state during applyChanges; committed to the OpSet only on
    success (mirrors docState in new.js:1805)."""

    __slots__ = ("max_op", "change_index_by_hash", "actor_ids", "heads", "clock", "ops", "object_meta")

    def __init__(self, opset):
        self.max_op = opset.max_op
        self.change_index_by_hash = opset.change_index_by_hash
        self.actor_ids = opset.actor_ids
        self.heads = opset.heads
        self.clock = opset.clock
        self.ops = list(opset.ops)
        self.object_meta = dict(opset.object_meta)


class _ChangeState:
    """Pseudo-iterator over the operations of a sequence of changes
    (mirrors changeState in new.js:678)."""

    __slots__ = (
        "changes", "change_index", "rows", "row_index", "op_ctr",
        "actor_table", "actor_index", "done", "next_op", "object_ids",
    )

    def __init__(self, changes, object_ids):
        self.changes = changes
        self.change_index = -1
        self.rows = None
        self.row_index = 0
        self.op_ctr = 0
        self.actor_table = None
        self.actor_index = None
        self.done = False
        self.next_op = None
        self.object_ids = object_ids


def _read_op_rows(columns, column_spec, actor_table=None):
    """Decodes column buffers into flat op rows (lists). ACTOR_ID values are
    translated through actor_table when given; group columns become lists.

    Port of readOperation (new.js:570) applied across the whole column set.
    """
    decoders = make_decoders(columns, column_spec)
    # Validate that the standard columns appear at the expected positions
    for i, (name, column_id) in enumerate(column_spec):
        if i < len(decoders) and decoders[i]["columnId"] != column_id:
            # Unknown column present before a standard one; unsupported for now
            raise DecodeError("unexpected columnId")
    if len(decoders) != len(column_spec):
        raise DecodeError("unexpected columnId")

    ds = [d["decoder"] for d in decoders]
    action_d = ds[ACTION]
    rows = []
    while not action_d.done:
        row = [None] * 16
        row[OBJ_ACTOR] = ds[OBJ_ACTOR].read_value()
        row[OBJ_CTR] = ds[OBJ_CTR].read_value()
        row[KEY_ACTOR] = ds[KEY_ACTOR].read_value()
        row[KEY_CTR] = ds[KEY_CTR].read_value()
        row[KEY_STR] = ds[KEY_STR].read_value()
        row[ID_ACTOR] = ds[ID_ACTOR].read_value()
        row[ID_CTR] = ds[ID_CTR].read_value()
        row[INSERT] = ds[INSERT].read_value()
        row[ACTION] = ds[ACTION].read_value()
        val_len = ds[VAL_LEN].read_value()
        row[VAL_LEN] = val_len if val_len is not None else 0
        row[VAL_RAW] = ds[VAL_RAW].read_raw_bytes((row[VAL_LEN] or 0) >> 4)
        row[CHLD_ACTOR] = ds[CHLD_ACTOR].read_value()
        row[CHLD_CTR] = ds[CHLD_CTR].read_value()
        card = ds[13].read_value() or 0
        row[13] = card
        row[14] = [ds[14].read_value() for _ in range(card)]
        row[15] = [ds[15].read_value() for _ in range(card)]
        if actor_table is not None:
            for idx in (OBJ_ACTOR, KEY_ACTOR, ID_ACTOR, CHLD_ACTOR):
                if row[idx] is not None:
                    row[idx] = actor_table[row[idx]]
            row[14] = [actor_table[a] if a is not None else None for a in row[14]]
        rows.append(row)
    return rows


def _get_actor_table(actor_ids, change):
    """Returns (actor_ids, actor_table) translating change actor indexes to doc
    actor indexes (new.js:1434)."""
    if change["actorIds"][0] not in actor_ids:
        if change["seq"] != 1:
            raise CausalityError(f"Seq {change['seq']} is the first change for actor {change['actorIds'][0]}")
        actor_ids = actor_ids + [change["actorIds"][0]]
    actor_table = []
    for actor_id in change["actorIds"]:
        try:
            actor_table.append(actor_ids.index(actor_id))
        except ValueError:
            raise CausalityError(f"actorId {actor_id} is not known to document") from None
    return actor_ids, actor_table


def _read_next_change_op(doc_state, change_state):
    """Advances change_state.next_op (port of readNextChangeOp, new.js:678)."""
    while change_state.change_index < len(change_state.changes) - 1 and (
        change_state.rows is None or change_state.row_index >= len(change_state.rows)
    ):
        change_state.change_index += 1
        change = change_state.changes[change_state.change_index]
        actor_ids, actor_table = _get_actor_table(doc_state.actor_ids, change)
        doc_state.actor_ids = actor_ids
        change_state.actor_table = actor_table
        change_state.actor_index = doc_state.actor_ids.index(change["actorIds"][0])
        columns = [(c["columnId"], c["buffer"]) for c in change["columns"]]
        change_state.rows = _read_op_rows(columns, CHANGE_COLUMNS, actor_table)
        change_state.row_index = 0
        change_state.op_ctr = change["startOp"]
        if not change_state.rows:
            change["maxOp"] = change["startOp"] - 1

    if change_state.rows is None or change_state.row_index >= len(change_state.rows):
        change_state.done = True
        change_state.next_op = None
        return

    op = list(change_state.rows[change_state.row_index])
    change_state.row_index += 1
    op[ID_ACTOR] = change_state.actor_index
    op[ID_CTR] = change_state.op_ctr
    change_state.changes[change_state.change_index]["maxOp"] = change_state.op_ctr
    if change_state.op_ctr > doc_state.max_op:
        doc_state.max_op = change_state.op_ctr
    change_state.op_ctr += 1
    change_state.next_op = op

    if (op[OBJ_CTR] is None) != (op[OBJ_ACTOR] is None):
        raise DecodeError(f"Mismatched object reference: ({op[OBJ_CTR]}, {op[OBJ_ACTOR]})")
    if (
        (op[KEY_CTR] is None and op[KEY_ACTOR] is not None)
        or (op[KEY_CTR] == 0 and op[KEY_ACTOR] is not None)
        or (op[KEY_CTR] is not None and op[KEY_CTR] > 0 and op[KEY_ACTOR] is None)
    ):
        raise DecodeError(f"Mismatched operation key: ({op[KEY_CTR]}, {op[KEY_ACTOR]})")


def _seek_to_op(doc_state, ops):
    """Finds the position at which an operation run should be applied; returns
    (skip_count, visible_count). Port of seekWithinBlock (new.js:50) over the
    flat op list (single conceptual block; no Bloom filters needed)."""
    rows = doc_state.ops
    actor_ids = doc_state.actor_ids
    n = len(rows)
    obj_actor, obj_ctr = ops["objActor"], ops["objCtr"]
    key_actor, key_ctr, key_str = ops["keyActor"], ops["keyCtr"], ops["keyStr"]
    id_actor, id_ctr, insert = ops["idActor"], ops["idCtr"], ops["insert"]

    skip_count = 0
    visible_count = 0
    elem_visible = False
    pos = 0  # aligned cursor for id/insert/succ/obj reads in the list phase
    next_obj_actor = None
    next_obj_ctr = None

    def actor_of(idx):
        return None if idx is None else actor_ids[idx]

    # Seek to the beginning of the object being updated
    if obj_ctr is not None:
        while pos < n:
            row = rows[pos]
            pos += 1
            next_obj_ctr = row[OBJ_CTR]
            next_obj_actor = actor_of(row[OBJ_ACTOR])
            if (
                next_obj_ctr is None
                or next_obj_actor is None
                or next_obj_ctr < obj_ctr
                or (next_obj_ctr == obj_ctr and next_obj_actor < obj_actor)
            ):
                skip_count += 1
            else:
                break
    if next_obj_ctr != obj_ctr or next_obj_actor != obj_actor:
        return skip_count, visible_count

    # Seek to the appropriate key (if string key is used). NB: mirrors the
    # reference's cursor layout where the obj cursor runs one op ahead of the
    # key cursor for non-root objects (new.js:77-92); any under-seek is
    # corrected by the merge loop.
    if key_str is not None:
        key_pos = skip_count
        target_key = utf16_key(key_str)
        while key_pos < n:
            if pos < n:
                row = rows[pos]
                next_obj_actor = actor_of(row[OBJ_ACTOR])
                next_obj_ctr = row[OBJ_CTR]
            else:
                next_obj_actor = None
                next_obj_ctr = None
            next_key_str = rows[key_pos][KEY_STR]
            pos += 1
            key_pos += 1
            if (
                next_key_str is not None
                and utf16_key(next_key_str) < target_key
                and next_obj_ctr == obj_ctr
                and next_obj_actor == obj_actor
            ):
                skip_count += 1
            else:
                break
        return skip_count, visible_count

    # List operation: read fields of row at skip_count (the first op of the
    # object), aligned with the obj cursor (new.js:94-101)
    pos = skip_count
    if pos >= n:
        return skip_count, visible_count
    row = rows[pos]
    pos += 1
    next_id_ctr = row[ID_CTR]
    next_id_actor = actor_of(row[ID_ACTOR])
    next_insert = row[INSERT]
    next_succ_num = row[SUCC_NUM]

    if insert:
        if key_ctr is not None and key_ctr > 0 and key_actor is not None:
            # Seek to the reference element of the insertion
            skip_count += 1
            while pos <= n and (next_id_ctr != key_ctr or next_id_actor != key_actor):
                if next_insert:
                    elem_visible = False
                if next_succ_num == 0 and not elem_visible:
                    visible_count += 1
                    elem_visible = True
                if pos >= n:
                    next_id_ctr = None
                    next_id_actor = None
                    next_obj_ctr = None
                    next_obj_actor = None
                    next_insert = None
                    next_succ_num = None
                    break
                row = rows[pos]
                pos += 1
                next_id_ctr = row[ID_CTR]
                next_id_actor = actor_of(row[ID_ACTOR])
                next_obj_ctr = row[OBJ_CTR]
                next_obj_actor = actor_of(row[OBJ_ACTOR])
                next_insert = row[INSERT]
                next_succ_num = row[SUCC_NUM]
                if next_obj_ctr == obj_ctr and next_obj_actor == obj_actor:
                    skip_count += 1
                else:
                    break
            if (
                next_obj_ctr != obj_ctr
                or next_obj_actor != obj_actor
                or next_id_ctr != key_ctr
                or next_id_actor != key_actor
                or not next_insert
            ):
                raise CausalityError(f"Reference element not found: {key_ctr}@{key_actor}")
            if next_insert:
                elem_visible = False
            if next_succ_num == 0 and not elem_visible:
                visible_count += 1
                elem_visible = True
            # Set up the next values to the operation following the reference element
            if pos >= n:
                return skip_count, visible_count
            row = rows[pos]
            pos += 1
            next_id_ctr = row[ID_CTR]
            next_id_actor = actor_of(row[ID_ACTOR])
            next_obj_ctr = row[OBJ_CTR]
            next_obj_actor = actor_of(row[OBJ_ACTOR])
            next_insert = row[INSERT]
            next_succ_num = row[SUCC_NUM]

        # Skip over any list elements with greater ID than the new one, and any
        # non-insertions (RGA convergence rule, new.js:144-163)
        while (
            (not next_insert or next_id_ctr > id_ctr or (next_id_ctr == id_ctr and next_id_actor > id_actor))
            and next_obj_ctr == obj_ctr
            and next_obj_actor == obj_actor
        ):
            skip_count += 1
            if next_insert:
                elem_visible = False
            if next_succ_num == 0 and not elem_visible:
                visible_count += 1
                elem_visible = True
            if pos < n:
                row = rows[pos]
                pos += 1
                next_id_ctr = row[ID_CTR]
                next_id_actor = actor_of(row[ID_ACTOR])
                next_obj_ctr = row[OBJ_CTR]
                next_obj_actor = actor_of(row[OBJ_ACTOR])
                next_insert = row[INSERT]
                next_succ_num = row[SUCC_NUM]
            else:
                break

    elif key_ctr is not None and key_ctr > 0 and key_actor is not None:
        # Updating an existing list element: seek to just before the
        # reference element's insertion op
        while (
            (not next_insert or next_id_ctr != key_ctr or next_id_actor != key_actor)
            and next_obj_ctr == obj_ctr
            and next_obj_actor == obj_actor
        ):
            skip_count += 1
            if next_insert:
                elem_visible = False
            if next_succ_num == 0 and not elem_visible:
                visible_count += 1
                elem_visible = True
            if pos < n:
                row = rows[pos]
                pos += 1
                next_id_ctr = row[ID_CTR]
                next_id_actor = actor_of(row[ID_ACTOR])
                next_obj_ctr = row[OBJ_CTR]
                next_obj_actor = actor_of(row[OBJ_ACTOR])
                next_insert = row[INSERT]
                next_succ_num = row[SUCC_NUM]
            else:
                break
        if (
            next_obj_ctr != obj_ctr
            or next_obj_actor != obj_actor
            or next_id_ctr != key_ctr
            or next_id_actor != key_actor
            or not next_insert
        ):
            raise CausalityError(f"Reference element not found: {key_ctr}@{key_actor}")

    return skip_count, visible_count


def _update_patch_property(patches, object_id, op, doc_state, prop_state, list_index,
                           old_succ_num, is_whole_doc):
    """Port of updatePatchProperty (new.js:884). `op` is a doc-format row."""
    actor_ids = doc_state.actor_ids
    action = op[ACTION]
    type_ = OBJECT_TYPE.get(ACTIONS[action]) if action < len(ACTIONS) else None
    op_id = f"{op[ID_CTR]}@{actor_ids[op[ID_ACTOR]]}"
    if op[INSERT]:
        elem_id_actor, elem_id_ctr = op[ID_ACTOR], op[ID_CTR]
    else:
        elem_id_actor, elem_id_ctr = op[KEY_ACTOR], op[KEY_CTR]
    if op[KEY_STR] is not None:
        elem_id = op[KEY_STR]
    else:
        elem_id = f"{elem_id_ctr}@{actor_ids[elem_id_actor]}"

    # Record new parent-child relationships for make* operations
    if action % 2 == 0 and op_id not in doc_state.object_meta:
        doc_state.object_meta[op_id] = {
            "parentObj": object_id, "parentKey": elem_id, "opId": op_id, "type": type_, "children": {},
        }
        _deep_copy_update(
            doc_state.object_meta,
            [object_id, "children", elem_id, op_id],
            {"objectId": op_id, "type": type_, "props": {}},
        )

    first_op = elem_id not in prop_state
    if first_op:
        prop_state[elem_id] = {"visibleOps": [], "hasChild": False}
    state = prop_state[elem_id]

    is_overwritten = old_succ_num is not None and op[SUCC_NUM] > 0

    if not is_overwritten:
        state["visibleOps"].append(op)
        state["hasChild"] = state["hasChild"] or (action % 2) == 0

    prev_children = doc_state.object_meta[object_id]["children"].get(elem_id)
    if state["hasChild"] or (prev_children and len(prev_children) > 0):
        values = {}
        for visible in state["visibleOps"]:
            vis_op_id = f"{visible[ID_CTR]}@{actor_ids[visible[ID_ACTOR]]}"
            vis_action = visible[ACTION]
            if vis_action < len(ACTIONS) and ACTIONS[vis_action] == "set":
                values[vis_op_id] = dict(
                    {"type": "value"}, **decode_value(visible[VAL_LEN], visible[VAL_RAW])
                )
            elif vis_action % 2 == 0:
                obj_type = OBJECT_TYPE.get(ACTIONS[vis_action]) if vis_action < len(ACTIONS) else None
                values[vis_op_id] = _empty_object_patch(vis_op_id, obj_type)
        _deep_copy_update(doc_state.object_meta, [object_id, "children", elem_id], values)

    patch_key = None
    patch_value = None

    is_set = action < len(ACTIONS) and ACTIONS[action] == "set"
    is_inc = action < len(ACTIONS) and ACTIONS[action] == "inc"

    if is_overwritten and is_set and (op[VAL_LEN] & 0x0F) == ValueType.COUNTER:
        # Initial set operation creating a counter: collect successor ops
        if "counterStates" not in state:
            state["counterStates"] = {}
        counter_state = {
            "opId": op_id,
            "value": decode_value(op[VAL_LEN], op[VAL_RAW])["value"],
            "succs": {},
        }
        for i in range(op[SUCC_NUM]):
            succ_op = f"{op[SUCC_CTR][i]}@{actor_ids[op[SUCC_ACTOR][i]]}"
            state["counterStates"][succ_op] = counter_state
            counter_state["succs"][succ_op] = True

    elif is_inc:
        if "counterStates" not in state or op_id not in state["counterStates"]:
            raise CausalityError(f"increment operation {op_id} for unknown counter")
        counter_state = state["counterStates"][op_id]
        counter_state["value"] += decode_value(op[VAL_LEN], op[VAL_RAW])["value"]
        del counter_state["succs"][op_id]
        if not counter_state["succs"]:
            patch_key = counter_state["opId"]
            patch_value = {"type": "value", "datatype": "counter", "value": counter_state["value"]}

    elif not is_overwritten:
        if is_set:
            patch_key = op_id
            patch_value = dict({"type": "value"}, **decode_value(op[VAL_LEN], op[VAL_RAW]))
        elif action % 2 == 0:
            if op_id not in patches:
                patches[op_id] = _empty_object_patch(op_id, type_)
            patch_key = op_id
            patch_value = patches[op_id]

    if object_id not in patches:
        patches[object_id] = _empty_object_patch(object_id, doc_state.object_meta[object_id]["type"])
    patch = patches[object_id]

    if op[KEY_STR] is None:
        # List or text object
        if old_succ_num == 0 and not is_whole_doc and state.get("action") == "insert":
            state["action"] = "update"
            convert_insert_to_update(patch["edits"], list_index, elem_id)

        if patch_value is not None:
            if not state.get("action") and (old_succ_num is None or is_whole_doc):
                state["action"] = "insert"
                append_edit(
                    patch["edits"],
                    {"action": "insert", "index": list_index, "elemId": elem_id,
                     "opId": patch_key, "value": patch_value},
                )
            elif state.get("action") == "remove":
                last_edit = patch["edits"][-1]
                if last_edit["action"] != "remove":
                    raise ValueError("last edit has unexpected type")  # amlint: disable=AM401 — internal edit-stream invariant, not a data fault
                if last_edit["count"] > 1:
                    last_edit["count"] -= 1
                else:
                    patch["edits"].pop()
                state["action"] = "update"
                append_update(patch["edits"], list_index, elem_id, patch_key, patch_value, True)
            else:
                append_update(
                    patch["edits"], list_index, elem_id, patch_key, patch_value, not state.get("action")
                )
                if not state.get("action"):
                    state["action"] = "update"

        elif old_succ_num == 0 and not state.get("action"):
            state["action"] = "remove"
            append_edit(patch["edits"], {"action": "remove", "index": list_index, "count": 1})

    elif patch_value is not None or not is_whole_doc:
        # Map or table object
        if first_op or op[KEY_STR] not in patch["props"]:
            patch["props"][op[KEY_STR]] = {}
        if patch_value is not None:
            patch["props"][op[KEY_STR]][patch_key] = patch_value


def _merge_doc_change_ops(patches, out_rows, change_state, doc_state, list_index, doc_cursor):
    """Two-pointer merge of doc ops and change ops for one run
    (port of mergeDocChangeOps, new.js:1052).

    `doc_cursor` is the index into doc_state.ops of the first unconsumed doc
    op. Returns the number of doc ops consumed. Merged output is appended to
    out_rows.
    """
    rows = doc_state.ops
    actor_ids = doc_state.actor_ids
    n = len(rows)

    first_op = change_state.next_op
    insert = first_op[INSERT]
    obj_actor, obj_ctr = first_op[OBJ_ACTOR], first_op[OBJ_CTR]
    object_id = "_root" if obj_actor is None else f"{obj_ctr}@{actor_ids[obj_actor]}"
    id_actor_index = change_state.actor_index
    id_actor = actor_ids[id_actor_index]

    found_list_elem = False
    elem_visible = False
    prop_state = {}
    change_state.object_ids.add(object_id)

    doc_op = rows[doc_cursor] if doc_cursor < n else None
    doc_ops_consumed = 0 if doc_op is None else 1
    doc_op_old_succ_num = 0 if doc_op is None else doc_op[SUCC_NUM]
    next_doc = doc_cursor + 1

    change_ops = []
    pred_seen = []
    last_change_key = None
    change_op = None

    def read_next_doc_op():
        nonlocal doc_op, next_doc, doc_ops_consumed, doc_op_old_succ_num
        if next_doc < n:
            doc_op = rows[next_doc]
            next_doc += 1
            doc_ops_consumed += 1
            doc_op_old_succ_num = doc_op[SUCC_NUM]
        else:
            doc_op = None

    while True:
        if not change_ops:
            found_list_elem = False
            next_op = change_state.next_op
            while (
                not change_state.done
                and next_op[ID_ACTOR] == id_actor_index
                and next_op[INSERT] == insert
                and next_op[OBJ_ACTOR] == first_op[OBJ_ACTOR]
                and next_op[OBJ_CTR] == first_op[OBJ_CTR]
            ):
                last_op = change_ops[-1] if change_ops else None
                is_overwrite = False
                for i in range(next_op[PRED_NUM]):
                    for prev_op in change_ops:
                        if (
                            next_op[PRED_ACTOR][i] == prev_op[ID_ACTOR]
                            and next_op[PRED_CTR][i] == prev_op[ID_CTR]
                        ):
                            is_overwrite = True

                if next_op is first_op:
                    pass  # first change op is always used
                elif (
                    insert
                    and last_op is not None
                    and next_op[KEY_STR] is None
                    and next_op[KEY_ACTOR] == last_op[ID_ACTOR]
                    and next_op[KEY_CTR] == last_op[ID_CTR]
                ):
                    pass  # consecutive insertions
                elif (
                    not insert
                    and last_op is not None
                    and next_op[KEY_STR] is not None
                    and next_op[KEY_STR] == last_op[KEY_STR]
                    and not is_overwrite
                ):
                    pass  # several updates to the same key
                elif (
                    not insert
                    and last_op is not None
                    and next_op[KEY_STR] is None
                    and last_op[KEY_STR] is None
                    and next_op[KEY_ACTOR] == last_op[KEY_ACTOR]
                    and next_op[KEY_CTR] == last_op[KEY_CTR]
                    and not is_overwrite
                ):
                    pass  # several updates to the same list element
                elif (
                    not insert
                    and last_op is None
                    and next_op[KEY_STR] is None
                    and doc_op is not None
                    and doc_op[INSERT]
                    and doc_op[KEY_STR] is None
                    and doc_op[ID_ACTOR] == next_op[KEY_ACTOR]
                    and doc_op[ID_CTR] == next_op[KEY_CTR]
                ):
                    pass  # updating consecutive list elements
                elif (
                    not insert
                    and last_op is None
                    and next_op[KEY_STR] is not None
                    and last_change_key is not None
                    and utf16_key(last_change_key) < utf16_key(next_op[KEY_STR])
                ):
                    pass  # several keys in ascending order
                else:
                    break

                last_change_key = next_op[KEY_STR]
                change_ops.append(next_op)
                pred_seen.append([False] * next_op[PRED_NUM])
                _read_next_change_op(doc_state, change_state)
                next_op = change_state.next_op

        if change_ops:
            change_op = change_ops[0]
        in_correct_object = (
            doc_op is not None
            and doc_op[OBJ_ACTOR] == change_op[OBJ_ACTOR]
            and doc_op[OBJ_CTR] == change_op[OBJ_CTR]
        )
        key_matches = (
            doc_op is not None
            and doc_op[KEY_STR] is not None
            and doc_op[KEY_STR] == change_op[KEY_STR]
        )
        list_elem_matches = (
            doc_op is not None
            and doc_op[KEY_STR] is None
            and change_op[KEY_STR] is None
            and (
                (not doc_op[INSERT]
                 and doc_op[KEY_ACTOR] == change_op[KEY_ACTOR]
                 and doc_op[KEY_CTR] == change_op[KEY_CTR])
                or (doc_op[INSERT]
                    and doc_op[ID_ACTOR] == change_op[KEY_ACTOR]
                    and doc_op[ID_CTR] == change_op[KEY_CTR])
            )
        )

        if not change_ops and not (in_correct_object and (key_matches or list_elem_matches)):
            break

        take_doc_op = False
        take_change_ops = 0

        if insert or not in_correct_object or (
            doc_op[KEY_STR] is None and change_op[KEY_STR] is not None
        ) or (
            doc_op[KEY_STR] is not None
            and change_op[KEY_STR] is not None
            and utf16_key(change_op[KEY_STR]) < utf16_key(doc_op[KEY_STR])
        ):
            take_change_ops = len(change_ops)
            if not in_correct_object and not found_list_elem and change_op[KEY_STR] is None and not change_op[INSERT]:
                raise CausalityError(
                    "could not find list element with ID: "
                    f"{change_op[KEY_CTR]}@{actor_ids[change_op[KEY_ACTOR]]}"
                )

        elif key_matches or list_elem_matches or found_list_elem:
            # Update the doc op's succ with any change ops whose pred matches
            for op_index, op in enumerate(change_ops):
                for i in range(op[PRED_NUM]):
                    if op[PRED_ACTOR][i] == doc_op[ID_ACTOR] and op[PRED_CTR][i] == doc_op[ID_CTR]:
                        # Copy-on-write so rows shared with the committed
                        # state are never mutated in place
                        doc_op = list(doc_op)
                        doc_op[SUCC_ACTOR] = list(doc_op[SUCC_ACTOR])
                        doc_op[SUCC_CTR] = list(doc_op[SUCC_CTR])
                        j = 0
                        while j < doc_op[SUCC_NUM] and (
                            doc_op[SUCC_CTR][j] < op[ID_CTR]
                            or (doc_op[SUCC_CTR][j] == op[ID_CTR]
                                and actor_ids[doc_op[SUCC_ACTOR][j]] < id_actor)
                        ):
                            j += 1
                        doc_op[SUCC_CTR].insert(j, op[ID_CTR])
                        doc_op[SUCC_ACTOR].insert(j, id_actor_index)
                        doc_op[SUCC_NUM] += 1
                        pred_seen[op_index][i] = True
                        break

            if list_elem_matches:
                found_list_elem = True

            if found_list_elem and not list_elem_matches:
                take_change_ops = len(change_ops)
            elif not change_ops or doc_op[ID_CTR] < change_op[ID_CTR] or (
                doc_op[ID_CTR] == change_op[ID_CTR]
                and actor_ids[doc_op[ID_ACTOR]] < id_actor
            ):
                take_doc_op = True
                _update_patch_property(
                    patches, object_id, doc_op, doc_state, prop_state, list_index,
                    doc_op_old_succ_num, False,
                )
                # Deletion ops are represented only by succ entries; remove
                # fully-seen del ops from the pending change ops
                for i in range(len(change_ops) - 1, -1, -1):
                    deleted = all(pred_seen[i])
                    op_action = change_ops[i][ACTION]
                    if op_action < len(ACTIONS) and ACTIONS[op_action] == "del" and deleted:
                        change_ops.pop(i)
                        pred_seen.pop(i)
            elif doc_op[ID_CTR] == change_op[ID_CTR] and actor_ids[doc_op[ID_ACTOR]] == id_actor:
                raise CausalityError(f"duplicate operation ID: {change_op[ID_CTR]}@{id_actor}")
            else:
                take_change_ops = 1
        else:
            take_doc_op = True

        if take_doc_op:
            out_rows.append(doc_op)
            if doc_op[INSERT] and elem_visible:
                elem_visible = False
                list_index += 1
            if doc_op[SUCC_NUM] == 0:
                elem_visible = True
            read_next_doc_op()

        if take_change_ops > 0:
            for i in range(take_change_ops):
                op = change_ops[i]
                for j in range(op[PRED_NUM]):
                    if not pred_seen[i][j]:
                        raise CausalityError(
                            "no matching operation for pred: "
                            f"{op[PRED_CTR][j]}@{actor_ids[op[PRED_ACTOR][j]]}"
                        )
                new_row = op[:13] + [0, [], []]
                out_rows.append(new_row)
                _update_patch_property(
                    patches, object_id, new_row, doc_state, prop_state, list_index, None, False
                )
                if op[INSERT]:
                    elem_visible = False
                    list_index += 1
                else:
                    elem_visible = True
            del change_ops[:take_change_ops]
            del pred_seen[:take_change_ops]

    if doc_op is not None:
        out_rows.append(doc_op)
    return doc_ops_consumed


def _apply_ops(patches, change_state, doc_state):
    """Applies one run of change ops: seek, merge, splice (port of applyOps,
    new.js:1304)."""
    op = change_state.next_op
    actor_ids = doc_state.actor_ids
    ops_info = {
        "objActor": None if op[OBJ_ACTOR] is None else actor_ids[op[OBJ_ACTOR]],
        "objCtr": op[OBJ_CTR],
        "keyActor": None if op[KEY_ACTOR] is None else actor_ids[op[KEY_ACTOR]],
        "keyCtr": op[KEY_CTR],
        "keyStr": op[KEY_STR],
        "idActor": actor_ids[op[ID_ACTOR]],
        "idCtr": op[ID_CTR],
        "insert": op[INSERT],
    }
    skip_count, visible_count = _seek_to_op(doc_state, ops_info)
    out_rows = []
    consumed = _merge_doc_change_ops(
        patches, out_rows, change_state, doc_state, visible_count, skip_count
    )
    doc_state.ops[skip_count : skip_count + consumed] = out_rows


def _setup_patches(patches, object_ids, doc_state):
    """Links child-object patches into their parents up to the root
    (port of setupPatches, new.js:1461)."""
    for object_id in object_ids:
        meta = doc_state.object_meta[object_id]
        child_meta = None
        patch_exists = False
        while True:
            has_children = (
                child_meta is not None
                and len(meta["children"].get(child_meta["parentKey"], {})) > 0
            )
            if object_id not in patches:
                patches[object_id] = _empty_object_patch(object_id, meta["type"])

            if child_meta is not None and has_children:
                if meta["type"] in ("list", "text"):
                    for edit in patches[object_id]["edits"]:
                        if edit.get("opId") and edit["opId"] in meta["children"][child_meta["parentKey"]]:
                            patch_exists = True
                    if not patch_exists:
                        obj = parse_op_id(object_id)
                        elem = parse_op_id(child_meta["parentKey"])
                        seek_pos = {
                            "objActor": obj.actor_id,
                            "objCtr": obj.counter,
                            "keyActor": elem.actor_id,
                            "keyCtr": elem.counter,
                            "keyStr": None,
                            "insert": False,
                            "idActor": None,
                            "idCtr": None,
                        }
                        _skip, visible_count = _seek_to_op(doc_state, seek_pos)
                        for op_id, value in meta["children"][child_meta["parentKey"]].items():
                            patch_value = value
                            if value.get("objectId"):
                                if value["objectId"] not in patches:
                                    patches[value["objectId"]] = _empty_object_patch(
                                        value["objectId"], value["type"]
                                    )
                                patch_value = patches[value["objectId"]]
                            edit = {"action": "update", "index": visible_count, "opId": op_id, "value": patch_value}
                            append_edit(patches[object_id]["edits"], edit)
                else:
                    if child_meta["parentKey"] not in patches[object_id]["props"]:
                        patches[object_id]["props"][child_meta["parentKey"]] = {}
                    values = patches[object_id]["props"][child_meta["parentKey"]]
                    for op_id, value in meta["children"][child_meta["parentKey"]].items():
                        if op_id in values:
                            patch_exists = True
                        elif value.get("objectId"):
                            if value["objectId"] not in patches:
                                patches[value["objectId"]] = _empty_object_patch(
                                    value["objectId"], value["type"]
                                )
                            values[op_id] = patches[value["objectId"]]
                        else:
                            values[op_id] = value

            if patch_exists or not meta["parentObj"] or (child_meta is not None and not has_children):
                break
            child_meta = meta
            object_id = meta["parentObj"]
            meta = doc_state.object_meta[object_id]
    return patches


def _apply_change_batch(patches, decoded_changes, doc_state, object_ids, throw_exceptions):
    """Causal gate + application loop (port of the applyChanges function,
    new.js:1550). Returns (applied, enqueued)."""
    heads = set(doc_state.heads)
    change_hashes = set()
    clock = dict(doc_state.clock)
    applied, enqueued = [], []

    for change in decoded_changes:
        if change["hash"] in doc_state.change_index_by_hash or change["hash"] in change_hashes:
            continue
        expected_seq = clock.get(change["actor"], 0) + 1
        causally_ready = True
        for dep in change["deps"]:
            dep_index = doc_state.change_index_by_hash.get(dep)
            if (dep_index is None or dep_index == -1) and dep not in change_hashes:
                causally_ready = False
        if not causally_ready:
            enqueued.append(change)
        elif change["seq"] < expected_seq:
            if throw_exceptions:
                raise CausalityError(
                    f"Reuse of sequence number {change['seq']} for actor {change['actor']}"
                )
            return [], decoded_changes
        elif change["seq"] > expected_seq:
            raise CausalityError(f"Skipped sequence number {expected_seq} for actor {change['actor']}")
        else:
            clock[change["actor"]] = change["seq"]
            change_hashes.add(change["hash"])
            for dep in change["deps"]:
                heads.discard(dep)
            heads.add(change["hash"])
            applied.append(change)

    if applied:
        change_state = _ChangeState(applied, object_ids)
        _read_next_change_op(doc_state, change_state)
        while not change_state.done:
            _apply_ops(patches, change_state, doc_state)
        doc_state.heads = sorted(heads)
        doc_state.clock = clock
    return applied, enqueued


def _document_patch(doc_state):
    """Scans all ops and generates the init patch for the whole document
    (port of documentPatch, new.js:1604)."""
    prop_state = {}
    patches = {"_root": {"objectId": "_root", "type": "map", "props": {}}}
    last_obj_actor = None
    last_obj_ctr = None
    object_id = "_root"
    elem_visible = False
    list_index = 0

    for doc_op in doc_state.ops:
        if doc_op[OBJ_ACTOR] != last_obj_actor or doc_op[OBJ_CTR] != last_obj_ctr:
            object_id = f"{doc_op[OBJ_CTR]}@{doc_state.actor_ids[doc_op[OBJ_ACTOR]]}"
            last_obj_actor = doc_op[OBJ_ACTOR]
            last_obj_ctr = doc_op[OBJ_CTR]
            prop_state = {}
            list_index = 0
            elem_visible = False

        if doc_op[INSERT] and elem_visible:
            elem_visible = False
            list_index += 1
        if doc_op[SUCC_NUM] == 0:
            elem_visible = True
        if doc_op[ID_CTR] > doc_state.max_op:
            doc_state.max_op = doc_op[ID_CTR]
        for i in range(doc_op[SUCC_NUM]):
            if doc_op[SUCC_CTR][i] > doc_state.max_op:
                doc_state.max_op = doc_op[SUCC_CTR][i]

        _update_patch_property(
            patches, object_id, doc_op, doc_state, prop_state, list_index,
            doc_op[SUCC_NUM], True,
        )
    return patches["_root"]


class OpSet:
    """Backend document state (port of BackendDoc, new.js:1694)."""

    def __init__(self, buffer=None):
        self.max_op = 0
        self.have_hash_graph = False
        self.changes = []  # binary changes (bytes), in application order
        self.change_index_by_hash = {}
        self.dependencies_by_hash = {}
        self.dependents_by_hash = {}
        self.hashes_by_actor = {}
        self.actor_ids = []
        self.heads = []
        self.clock = {}
        self.queue = []
        self.object_meta = {
            "_root": {"parentObj": None, "parentKey": None, "opId": None, "type": "map", "children": {}}
        }
        self.ops = []  # flat doc op rows
        self.change_meta = []  # per-change metadata for the document format
        self.binary_doc = None
        self.init_patch = None
        self.extra_bytes = None

        if buffer is not None:
            doc = decode_document_header(buffer)
            self.binary_doc = bytes(buffer)
            self.actor_ids = doc["actorIds"]
            self.heads = doc["heads"]
            self.extra_bytes = doc["extraBytes"]
            clock, head_actors, change_meta = self._read_document_changes(doc)
            self.clock = clock
            self.change_meta = change_meta
            self.changes = [None] * len(change_meta)

            if len(doc["heads"]) == 1 and len(head_actors) == 1:
                self.hashes_by_actor[head_actors[0]] = [None] * clock[head_actors[0]]
                self.hashes_by_actor[head_actors[0]][clock[head_actors[0]] - 1] = doc["heads"][0]

            if len(doc["heads"]) == len(doc["headsIndexes"]):
                for head, index in zip(doc["heads"], doc["headsIndexes"]):
                    self.change_index_by_hash[head] = index
            elif len(doc["heads"]) == 1:
                self.change_index_by_hash[doc["heads"][0]] = len(change_meta) - 1
            else:
                for head in doc["heads"]:
                    self.change_index_by_hash[head] = -1

            self.ops = _read_op_rows(doc["opsColumns"], DOC_OPS_COLUMNS)
            doc_state = _DocState(self)
            doc_state.object_meta = self.object_meta
            doc_state.max_op = 0
            self.init_patch = _document_patch(doc_state)
            self.max_op = doc_state.max_op
        else:
            self.have_hash_graph = True

    @staticmethod
    def _read_document_changes(doc):
        """Reads the change-metadata columns of a loaded document
        (port of readDocumentChanges, new.js:1645)."""
        rows = decode_columns(doc["changesColumns"], doc["actorIds"], DOCUMENT_COLUMNS)
        clock = {}
        head_indexes = set()
        change_meta = []
        for i, row in enumerate(rows):
            actor_id = row["actor"]
            seq = row["seq"]
            if seq != 1 and seq != clock.get(actor_id, 0) + 1:
                raise CausalityError(f"Expected seq {clock.get(actor_id, 0) + 1}, got {seq} for actor {actor_id}")
            clock[actor_id] = seq
            head_indexes.add(i)
            deps_indexes = [d["depsIndex"] for d in row["depsNum"]]
            for dep in deps_indexes:
                head_indexes.discard(dep)
            change_meta.append(
                {
                    "actor": actor_id,
                    "seq": seq,
                    "maxOp": row["maxOp"],
                    "time": row["time"],
                    "message": row["message"],
                    "depsIndexes": deps_indexes,
                    "extraBytes": row.get("extraLen") or b"",
                }
            )
        head_actors = sorted(change_meta[i]["actor"] for i in head_indexes)
        return clock, head_actors, change_meta

    def clone(self):
        copy = OpSet()
        copy.max_op = self.max_op
        copy.have_hash_graph = self.have_hash_graph
        copy.changes = list(self.changes)
        copy.change_index_by_hash = dict(self.change_index_by_hash)
        copy.dependencies_by_hash = dict(self.dependencies_by_hash)
        copy.dependents_by_hash = {k: list(v) for k, v in self.dependents_by_hash.items()}
        copy.hashes_by_actor = {k: list(v) for k, v in self.hashes_by_actor.items()}
        copy.actor_ids = self.actor_ids
        copy.heads = self.heads
        copy.clock = self.clock
        copy.ops = self.ops
        copy.object_meta = self.object_meta
        copy.queue = self.queue
        copy.change_meta = list(self.change_meta)
        copy.binary_doc = self.binary_doc
        copy.init_patch = self.init_patch
        copy.extra_bytes = self.extra_bytes
        return copy

    def apply_changes(self, change_buffers, is_local=False):
        """Parses binary changes and applies them; returns a patch
        (port of BackendDoc.applyChanges, new.js:1796)."""
        decoded_changes = []
        for buffer in change_buffers:
            decoded = decode_change_columns(buffer)
            decoded["buffer"] = bytes(buffer)
            decoded_changes.append(decoded)

        patches = {"_root": {"objectId": "_root", "type": "map", "props": {}}}
        doc_state = _DocState(self)
        # Work on a copy of the hash index so a delivery that raises midway
        # (seq reuse in a later gate batch, a corrupt change) cannot leave
        # phantom hashes behind: the committed index is only swapped in at
        # the commit point below (error-path atomicity for the sync layer
        # and the farm's per-doc quarantine).
        doc_state.change_index_by_hash = dict(self.change_index_by_hash)

        queue = decoded_changes if not self.queue else decoded_changes + self.queue
        all_applied = []
        object_ids = set()

        while True:
            applied, enqueued = _apply_change_batch(
                patches, queue, doc_state, object_ids, self.have_hash_graph
            )
            queue = enqueued
            for i, change in enumerate(applied):
                doc_state.change_index_by_hash[change["hash"]] = (
                    len(self.changes) + len(all_applied) + i
                )
            if applied:
                all_applied.extend(applied)
            if not queue:
                break
            if not applied:
                if self.have_hash_graph:
                    break
                self.compute_hash_graph()
                doc_state.change_index_by_hash = dict(self.change_index_by_hash)
                for i, change in enumerate(all_applied):
                    doc_state.change_index_by_hash[change["hash"]] = (
                        len(self.changes) + i
                    )

        _setup_patches(patches, object_ids, doc_state)

        # Commit (only reached if no exception was raised)
        self.change_index_by_hash = doc_state.change_index_by_hash
        for change in all_applied:
            self.changes.append(change["buffer"])
            self.hashes_by_actor.setdefault(change["actor"], [])
            actor_hashes = self.hashes_by_actor[change["actor"]]
            while len(actor_hashes) < change["seq"]:
                actor_hashes.append(None)
            actor_hashes[change["seq"] - 1] = change["hash"]
            self.change_index_by_hash[change["hash"]] = len(self.changes) - 1
            self.dependencies_by_hash[change["hash"]] = change["deps"]
            self.dependents_by_hash[change["hash"]] = []
            for dep in change["deps"]:
                self.dependents_by_hash.setdefault(dep, []).append(change["hash"])
            self.change_meta.append(
                {
                    "actor": change["actor"],
                    "seq": change["seq"],
                    "maxOp": change["maxOp"],
                    "time": change["time"],
                    "message": change["message"],
                    "depsIndexes": [self.change_index_by_hash[d] for d in change["deps"]],
                    "extraBytes": change.get("extraBytes", b"") or b"",
                }
            )

        self.max_op = doc_state.max_op
        self.actor_ids = doc_state.actor_ids
        self.heads = doc_state.heads
        self.clock = doc_state.clock
        self.ops = doc_state.ops
        self.object_meta = doc_state.object_meta
        self.queue = queue
        self.binary_doc = None
        self.init_patch = None

        patch = {
            "maxOp": self.max_op,
            "clock": self.clock,
            "deps": self.heads,
            "pendingChanges": len(self.queue),
            "diffs": patches["_root"],
        }
        if is_local and len(decoded_changes) == 1:
            patch["actor"] = decoded_changes[0]["actor"]
            patch["seq"] = decoded_changes[0]["seq"]
        return patch

    def compute_hash_graph(self):
        """Reconstructs the full change history from the current document
        (port of computeHashGraph, new.js:1879)."""
        binary_doc = self.save()
        self.have_hash_graph = True
        self.changes = []
        self.change_index_by_hash = {}
        self.dependencies_by_hash = {}
        self.dependents_by_hash = {}
        self.hashes_by_actor = {}
        self.clock = {}

        for change in decode_changes([binary_doc]):
            binary_change = encode_change(change)
            self.changes.append(binary_change)
            self.change_index_by_hash[change["hash"]] = len(self.changes) - 1
            self.dependencies_by_hash[change["hash"]] = change["deps"]
            self.dependents_by_hash[change["hash"]] = []
            for dep in change["deps"]:
                self.dependents_by_hash[dep].append(change["hash"])
            if change["seq"] == 1:
                self.hashes_by_actor[change["actor"]] = []
            self.hashes_by_actor[change["actor"]].append(change["hash"])
            expected_seq = self.clock.get(change["actor"], 0) + 1
            if change["seq"] != expected_seq:
                raise CausalityError(
                    f"Expected seq {expected_seq}, got seq {change['seq']} from actor {change['actor']}"
                )
            self.clock[change["actor"]] = change["seq"]

    def get_changes(self, have_deps):
        """Returns changes to send to a replica that has `have_deps`
        (port of getChanges, new.js:1913)."""
        if not self.have_hash_graph:
            self.compute_hash_graph()
        if not have_deps:
            return list(self.changes)

        stack = []
        seen_hashes = {}
        to_return = []
        for h in have_deps:
            seen_hashes[h] = True
            successors = self.dependents_by_hash.get(h)
            if successors is None:
                raise CausalityError(f"hash not found: {h}")
            stack.extend(successors)

        while stack:
            h = stack.pop()
            seen_hashes[h] = True
            to_return.append(h)
            if not all(seen_hashes.get(dep) for dep in self.dependencies_by_hash[h]):
                break
            stack.extend(self.dependents_by_hash[h])

        if not stack and all(seen_hashes.get(head) for head in self.heads):
            return [self.changes[self.change_index_by_hash[h]] for h in to_return]

        stack = list(have_deps)
        seen_hashes = {}
        while stack:
            h = stack.pop()
            if h not in seen_hashes:
                deps = self.dependencies_by_hash.get(h)
                if deps is None:
                    raise CausalityError(f"hash not found: {h}")
                stack.extend(deps)
                seen_hashes[h] = True

        return [
            change
            for change in self.changes
            if decode_change_meta(change, True)["hash"] not in seen_hashes
        ]

    def get_changes_added(self, other):
        """Returns changes present here but not in `other`
        (port of getChangesAdded, new.js:1971)."""
        if not self.have_hash_graph:
            self.compute_hash_graph()
        stack = list(self.heads)
        seen_hashes = {}
        to_return = []
        while stack:
            h = stack.pop()
            if h not in seen_hashes and other.change_index_by_hash.get(h) is None:
                seen_hashes[h] = True
                to_return.append(h)
                stack.extend(self.dependencies_by_hash[h])
        return [self.changes[self.change_index_by_hash[h]] for h in reversed(to_return)]

    def get_change_by_hash(self, hash_):
        if not self.have_hash_graph:
            self.compute_hash_graph()
        index = self.change_index_by_hash.get(hash_)
        return self.changes[index] if index is not None and index >= 0 else None

    def get_missing_deps(self, heads=()):
        """Returns hashes of missing dependencies (port of getMissingDeps,
        new.js:2006)."""
        if not self.have_hash_graph:
            self.compute_hash_graph()
        all_deps = set(heads)
        in_queue = set()
        for change in self.queue:
            in_queue.add(change["hash"])
            for dep in change["deps"]:
                all_deps.add(dep)
        missing = [
            h for h in all_deps if self.change_index_by_hash.get(h) is None and h not in in_queue
        ]
        return sorted(missing)

    def save(self):
        """Serialises the document into the binary document format
        (port of save, new.js:2025). Byte-identical to the reference because
        all columns are deterministic re-encodings of the maintained op and
        change-metadata sequences."""
        if self.binary_doc:
            return self.binary_doc
        self.binary_doc = encode_document_header(
            {
                "changesColumns": self._encode_change_columns(),
                "opsColumns": self._encode_ops_columns(),
                "actorIds": self.actor_ids,
                "heads": self.heads,
                "headsIndexes": [self.change_index_by_hash[h] for h in self.heads],
                "extraBytes": self.extra_bytes,
            }
        )
        return self.binary_doc

    def _encode_ops_columns(self, force_python=False):
        """Encodes the flat op rows into document op columns. Uses the native
        C++ codec library for the numeric columns when available (byte-
        identical output; see automerge_tpu/native.py)."""
        if not force_python:
            native_cols = self._encode_ops_columns_native()
            if native_cols is not None:
                return native_cols
        encoders = [encoder_by_column_id(cid) for _name, cid in DOC_OPS_COLUMNS]
        for row in self.ops:
            for i in range(13):
                if i == INSERT:
                    encoders[i].append_value(bool(row[i]))
                elif i == VAL_RAW:
                    if row[VAL_RAW]:
                        encoders[i].append_raw_bytes(row[VAL_RAW])
                elif i == VAL_LEN:
                    encoders[i].append_value(row[i])
                else:
                    encoders[i].append_value(row[i])
            encoders[SUCC_NUM].append_value(row[SUCC_NUM])
            for a in row[SUCC_ACTOR]:
                encoders[SUCC_ACTOR].append_value(a)
            for c in row[SUCC_CTR]:
                encoders[SUCC_CTR].append_value(c)
        return [
            (cid, enc.buffer) for (_name, cid), enc in zip(DOC_OPS_COLUMNS, encoders)
        ]

    def _encode_ops_columns_native(self):
        """Bulk column encode through the native codec library. Returns None
        when the library is unavailable (pure-Python fallback is used)."""
        try:
            from . import native
        except ImportError:
            return None
        if not native.available():
            return None
        import numpy as np

        ops = self.ops
        sent = native.NULL_SENTINEL

        def column(idx, transform=None):
            return np.array(
                [sent if row[idx] is None else (transform(row[idx]) if transform else row[idx])
                 for row in ops],
                np.int64,
            )

        out = []
        for name, cid in DOC_OPS_COLUMNS:
            if name == "keyStr":
                enc = encoder_by_column_id(cid)
                for row in ops:
                    enc.append_value(row[KEY_STR])
                out.append((cid, enc.buffer))
            elif name == "valRaw":
                out.append((cid, b"".join(row[VAL_RAW] or b"" for row in ops)))
            elif name == "insert":
                out.append((cid, native.bool_encode(
                    np.array([bool(row[INSERT]) for row in ops], np.uint8))))
            elif name == "keyCtr":
                out.append((cid, native.delta_encode(column(KEY_CTR))))
            elif name == "idCtr":
                out.append((cid, native.delta_encode(column(ID_CTR))))
            elif name == "chldCtr":
                out.append((cid, native.delta_encode(column(CHLD_CTR))))
            elif name == "succCtr":
                flat = [c for row in ops for c in row[SUCC_CTR]]
                out.append((cid, native.delta_encode(np.array(flat, np.int64))))
            elif name == "succActor":
                flat = [a for row in ops for a in row[SUCC_ACTOR]]
                out.append((cid, native.rle_encode(np.array(flat, np.int64))))
            elif name == "objActor":
                out.append((cid, native.rle_encode(column(OBJ_ACTOR))))
            elif name == "objCtr":
                out.append((cid, native.rle_encode(column(OBJ_CTR))))
            elif name == "keyActor":
                out.append((cid, native.rle_encode(column(KEY_ACTOR))))
            elif name == "idActor":
                out.append((cid, native.rle_encode(column(ID_ACTOR))))
            elif name == "action":
                out.append((cid, native.rle_encode(column(ACTION))))
            elif name == "valLen":
                out.append((cid, native.rle_encode(column(VAL_LEN))))
            elif name == "chldActor":
                out.append((cid, native.rle_encode(column(CHLD_ACTOR))))
            elif name == "succNum":
                out.append((cid, native.rle_encode(column(SUCC_NUM))))
            else:
                return None
        return out

    def _encode_change_columns(self):
        """Encodes change metadata into document change columns
        (port of appendChange, new.js:1680)."""
        encoders = [encoder_by_column_id(cid) for _name, cid in DOCUMENT_COLUMNS]
        actor_index = {a: i for i, a in enumerate(self.actor_ids)}
        for meta in self.change_meta:
            encoders[0].append_value(actor_index[meta["actor"]])
            encoders[1].append_value(meta["seq"])
            encoders[2].append_value(meta["maxOp"])
            encoders[3].append_value(meta["time"])
            encoders[4].append_value(meta["message"] if meta["message"] is not None else "")
            encoders[5].append_value(len(meta["depsIndexes"]))
            for dep in meta["depsIndexes"]:
                encoders[6].append_value(dep)
            extra = meta["extraBytes"] or b""
            encoders[7].append_value(len(extra) << 4 | ValueType.BYTES)
            if extra:
                encoders[8].append_raw_bytes(extra)
        return [
            (cid, enc.buffer) for (_name, cid), enc in zip(DOCUMENT_COLUMNS, encoders)
        ]

    def get_patch(self):
        """Returns a patch that reconstructs the current document state
        (port of getPatch, new.js:2052)."""
        if self.init_patch is not None:
            diffs = self.init_patch
        else:
            object_meta = {
                "_root": {"parentObj": None, "parentKey": None, "opId": None, "type": "map", "children": {}}
            }
            doc_state = _DocState(self)
            doc_state.object_meta = object_meta
            doc_state.max_op = 0
            diffs = _document_patch(doc_state)
        return {
            "maxOp": self.max_op,
            "clock": self.clock,
            "deps": self.heads,
            "pendingChanges": len(self.queue),
            "diffs": diffs,
        }
