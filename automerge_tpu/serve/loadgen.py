"""Simulated-time load harness: 10^4–10^6 clients against one AmServer.

The serving claim to test is not "a session works" (PR 5 proved that) but
"a *fleet* of sessions stays dense through the batcher and the service
survives hostile traffic". This harness builds that fleet cheaply: every
client is a reference-backend replica plus a supervised ``SyncSession``,
wired to the server over per-client chaos links (``testing/chaos.py``),
and the whole system runs on one ``ManualClock`` — a million clients'
worth of retransmission timeouts, batching windows and backoff cost zero
real seconds of sleeping. Determinism is total: one seed fixes the edit
schedule, the chaos schedule and every session's jitter.

Workload shape: each client issues ``edits_per_client`` changes of
``ops_per_edit`` key-set ops at seeded times spread over ``spread``
simulated seconds, against a document shared with the other clients
assigned to it (``clients / docs`` co-editors per doc). A ``poison``
fraction of the docs gets hostile clients whose outgoing change buffers
are corrupted in flight — the farm's per-doc isolation quarantines those
docs and the front door's admission control must shed them while every
clean doc's clients still converge.

Figures of merit (reported by ``run()`` and ``bench.py --serve``):

- **sync latency** (p50/p95/p99, simulated ms): first transmission of a
  payload frame → its ack, which prices the batching window plus the
  dispatch on exactly the path a client feels;
- **e2e ops/s**: committed ops per *host* second — what the serving
  stack actually costs;
- **batch occupancy**: docs carrying changes per farm dispatch (the
  density the batcher exists to create);
- **shed/backpressure counts** from the ``serve.*`` amtrace metrics.

Convergence criterion: every *surviving* client (its doc neither
poisoned nor quarantined) holds exactly the server's heads for its doc.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field

from .. import backend as Backend
from ..errors import AutomergeError, SyncProtocolError
from ..obs.export import SnapshotWriter, request_breakdown
from ..obs.flight import get_flight
from ..obs.metrics import enabled_metrics, get_metrics
from ..obs.scope import get_amscope
from ..obs.slo import SLOEngine, default_serve_slos, verdicts_ok
from ..sync import decode_sync_message, encode_sync_message
from ..sync_session import (
    BackendDriver,
    SessionConfig,
    SyncSession,
    decode_frame,
    encode_frame,
)
from ..testing.chaos import ChaosConfig, ChaosNetwork, ManualClock
from ..testing.faults import bit_flipped, make_change, set_op
from .batcher import BatcherConfig
from .server import AmServer

_METRICS = get_metrics()
_M_LATENCY = _METRICS.histogram(
    "serve.sync.latency_ms",
    "simulated ms from a payload frame's first transmission to its ack "
    "(prices the batching window + dispatch as the client feels it)",
)
_M_SHED_ADMISSION = _METRICS.counter(
    "serve.loadgen.frames_shed",
    "client frames the front door refused (admission/backpressure); the "
    "session retransmission path retried them",
)
_M_REJECTED_DOWN = _METRICS.counter(
    "serve.loadgen.frames_rejected",
    "server frames a client session rejected (chaos corruption)",
)
_M_CONVERGED_RATIO = _METRICS.gauge(
    "serve.clients.converged_ratio",
    "converged fraction of the surviving fleet (the convergence SLO's "
    "input gauge; surviving = doc neither poisoned nor quarantined)",
)

_SERVER = "server"


@dataclass
class LoadConfig:
    """Harness knobs. Times are simulated seconds."""

    clients: int = 10_000
    docs: int = 1024
    edits_per_client: int = 2
    ops_per_edit: int = 4
    key_space: int = 32          # per-doc key universe (forces co-editor merges)
    spread: float = 2.0          # edit times are spread over [0, spread)
    chaos: float = 0.0           # per-link drop/dup/reorder probability
    poison: float = 0.0          # fraction of docs with hostile clients
    tenants: int = 4             # clients round-robin across this many tenants
    max_time: float = 900.0      # simulated-seconds budget
    seed: int = 0
    tick: float = 0.01           # clock advance while traffic is moving
    batcher: BatcherConfig = field(default_factory=BatcherConfig)
    session: SessionConfig = field(default_factory=SessionConfig)
    # observability stack for the run: "metrics" (the PR 7 baseline —
    # metrics registry only), "full" (metrics + amscope request tracing +
    # flight recorder), or "off" (nothing enabled: the library-user hot
    # path, used by the bench overhead gate)
    observability: str = "metrics"
    flight_dir: str | None = None       # auto-dump dir for "full" runs
    snapshot_path: str | None = None    # JSONL telemetry snapshots (--watch)
    snapshot_interval: float = 0.5      # simulated seconds between snapshots
    # SLO knobs (active whenever the metrics registry is on). The latency
    # budget is simulated ms against serve.sync.latency_ms and rounds DOWN
    # to a log2 bucket bound (1000 -> 536.87ms effective): generous enough
    # for the batching window + one dispatch, breached by retransmission
    # storms.
    slo_budget_ms: float = 1000.0
    slo_latency_target: float = 0.99


class _Client:
    """One simulated editor: a reference-backend replica + its session."""

    __slots__ = ("index", "actor", "doc", "driver", "session", "seq",
                 "max_op", "poisoned", "edits_left", "inflight_since",
                 "inflight_seq")

    def __init__(self, index, actor, doc, driver, session, poisoned):
        self.index = index
        self.actor = actor
        self.doc = doc
        self.driver = driver
        self.session = session
        self.poisoned = poisoned
        self.seq = 0
        self.max_op = 0
        self.edits_left = 0
        self.inflight_since = None   # first-send time of the unacked payload
        self.inflight_seq = None


class LoadGen:
    """Builds the fleet and runs the event loop. ``run()`` returns the
    report dict; ``self.server``/``self.farm``/``self.clients`` stay
    inspectable afterwards (tests assert on them)."""

    def __init__(self, farm, config: LoadConfig | None = None):
        self.config = config or LoadConfig()
        self.farm = farm
        cfg = self.config
        self.clock = ManualClock()
        self.rng = random.Random(cfg.seed)
        self.net = ChaosNetwork(
            random.Random(cfg.seed + 1), self.clock,
            ChaosConfig.lossy(cfg.chaos),
        )
        self.server = AmServer(
            farm, clock=self.clock, rng=random.Random(cfg.seed + 2),
            config=cfg.batcher, session_config=cfg.session,
        )
        n_poison = int(round(cfg.poison * cfg.docs))
        stride = max(cfg.docs // n_poison, 1) if n_poison else 1
        self.poison_docs = {i * stride for i in range(n_poison)}
        self.clients: list[_Client] = []
        self._build_clients()
        self._schedule = self._build_schedule()
        self._next_event = 0
        self._busy_up: set[int] = set()
        self._busy_down: set[int] = set()
        self._active: set[int] = set()
        self.shed_frames = 0
        self.rejected_down = 0
        self._snapshots = None  # SnapshotWriter, armed by run()
        self._slo = None        # SLOEngine, armed by run()

    # -------------------------------------------------------------- #
    # fleet construction

    def _build_clients(self) -> None:
        cfg = self.config
        for i in range(cfg.clients):
            doc = i % cfg.docs
            client = _Client(
                index=i,
                actor=f"{i:08x}",
                doc=doc,
                driver=BackendDriver(Backend.init()),
                session=None,
                poisoned=doc in self.poison_docs,
            )
            client.session = SyncSession(
                client.driver, clock=self.clock,
                rng=random.Random(cfg.seed * 7919 + i),
                config=cfg.session,
            )
            client.edits_left = cfg.edits_per_client
            self.clients.append(client)
            self.server.connect(i, doc, tenant=f"t{i % cfg.tenants}")

    def _build_schedule(self) -> list[tuple[float, int]]:
        """(time, client index) edit events, sorted. One client's edits
        stay ordered so its seq numbers commit in order."""
        cfg = self.config
        events = []
        for client in self.clients:
            times = sorted(
                self.rng.uniform(0.0, cfg.spread)
                for _ in range(cfg.edits_per_client)
            )
            events.extend((t, client.index) for t in times)
        events.sort()
        return events

    # -------------------------------------------------------------- #
    # workload

    def _edit(self, client: _Client) -> None:
        cfg = self.config
        client.seq += 1
        start = client.max_op + 1
        ops = []
        for k in range(cfg.ops_per_edit):
            key = f"k{self.rng.randrange(cfg.key_space)}"
            ops.append(set_op(key, client.index * 1000 + client.seq))
        buf = make_change(
            client.actor, client.seq, start,
            Backend.get_heads(client.driver.backend), ops,
        )
        client.max_op = start + len(ops) - 1
        client.driver.backend, _ = Backend.apply_changes(
            client.driver.backend, [buf]
        )
        client.edits_left -= 1
        self._active.add(client.index)

    def _corrupt_payload(self, client: _Client, frame: bytes) -> bytes:
        """The hostile-client transform: keeps the envelope and message
        structurally valid but damages every change buffer inside, so the
        farm's per-doc isolation (not the protocol layer) takes the hit."""
        parsed = decode_frame(frame)
        if parsed["payload"] is None:
            return frame
        msg = decode_sync_message(parsed["payload"])
        if not msg["changes"]:
            return frame
        msg["changes"] = [bytes(bit_flipped(c)) for c in msg["changes"]]
        return encode_frame(
            parsed["epoch"], parsed["seq"], parsed["ack"],
            encode_sync_message(msg),
        )

    # -------------------------------------------------------------- #
    # event loop

    def _poll_clients(self) -> bool:
        moved = False
        for i in sorted(self._active):
            client = self.clients[i]
            frame = client.session.poll()
            if frame is None:
                if client.session.pending is None:
                    self._active.discard(i)
                continue
            moved = True
            if client.poisoned:
                frame = self._corrupt_payload(client, frame)
            self.net.link(i, _SERVER).send(frame)
            self._busy_up.add(i)
            pending = client.session.pending
            if pending is not None and pending["seq"] != client.inflight_seq:
                client.inflight_seq = pending["seq"]
                client.inflight_since = self.clock()
        return moved

    def _deliver_up(self) -> bool:
        moved = False
        for i in sorted(self._busy_up):
            link = self.net.link(i, _SERVER)
            for frame in link.deliver():
                moved = True
                try:
                    self.server.receive(i, frame)
                except AutomergeError:
                    self.shed_frames += 1
                    _M_SHED_ADMISSION.inc()
            if link.in_flight == 0:
                self._busy_up.discard(i)
        return moved

    def _pump_server(self) -> bool:
        moved = False
        self.server.tick()
        for i, frame in self.server.pump():
            moved = True
            self.net.link(_SERVER, i).send(frame)
            self._busy_down.add(i)
        return moved

    def _deliver_down(self) -> bool:
        moved = False
        now = self.clock()
        for i in sorted(self._busy_down):
            link = self.net.link(_SERVER, i)
            client = self.clients[i]
            for frame in link.deliver():
                moved = True
                try:
                    client.session.handle(frame)
                except SyncProtocolError:
                    self.rejected_down += 1
                    _M_REJECTED_DOWN.inc()
                self._active.add(i)
                if client.inflight_seq is not None and (
                    client.session.pending is None
                ):
                    if not client.session.quarantined:
                        _M_LATENCY.observe(
                            max(now - client.inflight_since, 1e-6) * 1000.0
                        )
                    client.inflight_seq = None
                    client.inflight_since = None
            if link.in_flight == 0:
                self._busy_down.discard(i)
        return moved

    def _issue_due_edits(self) -> bool:
        now = self.clock()
        issued = False
        while (
            self._next_event < len(self._schedule)
            and self._schedule[self._next_event][0] <= now
        ):
            _, i = self._schedule[self._next_event]
            self._next_event += 1
            self._edit(self.clients[i])
            issued = True
        return issued

    def _surviving(self) -> list[_Client]:
        dead = self.poison_docs | set(self.farm.quarantine)
        return [c for c in self.clients if c.doc not in dead]

    def _unconverged(self, candidates=None) -> list[_Client]:
        out = []
        for client in candidates if candidates is not None else self._surviving():
            if client.driver.heads() != self.farm.get_heads(client.doc):
                out.append(client)
        return out

    def _next_wakeup(self) -> float | None:
        """Earliest future event: a scheduled edit, the batcher window,
        a retransmission deadline (client or server), or a delayed frame
        arriving on a busy link."""
        times = []
        if self._next_event < len(self._schedule):
            times.append(self._schedule[self._next_event][0])
        deadline = self.server.next_deadline()
        if deadline is not None:
            times.append(deadline)
        for i in self._active:
            pending = self.clients[i].session.pending
            if pending is not None:
                times.append(pending["deadline"])
        for i in self._busy_up:
            at = self.net.link(i, _SERVER).next_arrival()
            if at is not None:
                times.append(at)
        for i in self._busy_down:
            at = self.net.link(_SERVER, i).next_arrival()
            if at is not None:
                times.append(at)
        return min(times, default=None)

    def run(self) -> dict:
        """Drives the fleet to convergence (or the simulated-time budget)
        and returns the report. ``config.observability`` picks the stack:
        "metrics" enables the registry (the historical behaviour), "full"
        adds amscope request tracing (phase breakdowns, exemplars, the
        tenant table) and the flight recorder (auto-dumping to
        ``flight_dir`` on quarantine/watchdog events), "off" enables
        nothing — the disabled-hot-path shape the bench overhead gate
        measures. Whenever the registry is on, an ``SLOEngine`` over
        ``default_serve_slos`` samples multi-window burn rates on the
        simulated clock and the report carries its verdicts under
        ``"slo"`` (``bench.py --serve`` gates on them)."""
        import contextlib

        cfg = self.config
        # the registry is process-wide: zero it so the report reflects
        # exactly this run (the same convention as bench.py's workloads)
        _METRICS.reset()
        scope, flight = get_amscope(), get_flight()
        stack = contextlib.ExitStack()
        if cfg.observability == "full":
            scope.reset()
            flight.clear()
            was_clock, was_dir = flight.clock, flight.dump_dir
            flight.clock = self.clock  # simulated-time timeline
            stack.enter_context(enabled_metrics())
            scope.enabled = True
            stack.callback(lambda: setattr(scope, "enabled", False))
            flight.enabled = True
            if cfg.flight_dir is not None:
                flight.dump_dir = cfg.flight_dir

            def _restore_flight():
                flight.enabled = False
                flight.dump_dir = was_dir
                flight.clock = was_clock

            stack.callback(_restore_flight)
        elif cfg.observability == "metrics":
            stack.enter_context(enabled_metrics())
        elif cfg.observability != "off":
            raise ValueError(  # amlint: disable=AM401 — API-usage validation
                f"unknown observability mode: {cfg.observability!r}"
            )
        self._slo = (
            SLOEngine(
                default_serve_slos(
                    budget_ms=cfg.slo_budget_ms,
                    latency_target=cfg.slo_latency_target,
                    latency_metric="serve.sync.latency_ms",
                ),
                clock=self.clock,
            )
            if cfg.observability != "off" else None
        )
        self._snapshots = (
            SnapshotWriter(cfg.snapshot_path, cfg.snapshot_interval,
                           clock=self.clock, slo_engine=self._slo)
            if cfg.snapshot_path else None
        )
        slo_verdicts = None
        with stack:
            converged = self._run_loop()
            surviving = self._surviving()
            unconverged = self._unconverged(surviving)
            if self._slo is not None:
                denom = len(surviving) or 1
                _M_CONVERGED_RATIO.set(
                    round((len(surviving) - len(unconverged)) / denom, 6)
                )
                slo_verdicts = self._slo.export()
            if self._snapshots is not None:
                self._snapshots.write(self.clock())
        metrics = _METRICS.as_dict()
        occupancy = metrics.get("serve.batch.occupancy", {})
        dispatches = occupancy.get("count", 0)
        latency = metrics.get("serve.sync.latency_ms", {})
        committed = metrics.get("serve.batch.changes", {}).get("value", 0)
        extras = {}
        if slo_verdicts is not None:
            extras["slo"] = {
                "verdicts": slo_verdicts,
                "ok": verdicts_ok(slo_verdicts),
            }
        if cfg.observability == "full":
            extras["breakdown"] = request_breakdown(metrics)
            extras["tenants"] = scope.tenant_stats()
            extras["dispatch_spans"] = len(scope.dispatches)
            extras["flight_events"] = len(flight)
            extras["flight_dumps"] = list(flight.dump_paths)
        return {
            **extras,
            "clients": cfg.clients,
            "docs": cfg.docs,
            "edits": cfg.clients * cfg.edits_per_client,
            "ops": cfg.clients * cfg.edits_per_client * cfg.ops_per_edit,
            "converged": converged and not unconverged,
            "surviving_clients": len(surviving),
            "unconverged_clients": len(unconverged),
            "poisoned_docs": len(self.poison_docs),
            "quarantined_docs": len(self.farm.quarantine),
            "simulated_s": round(self.clock.now(), 3),
            "dispatches": dispatches,
            "occupancy_mean": round(
                occupancy.get("sum", 0.0) / dispatches, 2
            ) if dispatches else 0.0,
            "changes_committed": committed,
            "latency_ms": {
                "p50": latency.get("p50"),
                "p95": latency.get("p95"),
                "p99": latency.get("p99"),
                "samples": latency.get("count", 0),
            },
            "admission": {
                "accepted": metrics.get(
                    "serve.admission.accepted", {}).get("value", 0),
                "rejected_quarantine": metrics.get(
                    "serve.admission.rejected_quarantine", {}).get("value", 0),
                "rejected_backpressure": metrics.get(
                    "serve.admission.rejected_backpressure", {}).get("value", 0),
                "shed_mid_window": metrics.get(
                    "serve.flush.shed_quarantined", {}).get("value", 0),
            },
            "frames_shed": self.shed_frames,
            "frames_rejected_by_clients": self.rejected_down,
        }

    def _run_loop(self) -> bool:
        cfg = self.config
        idle_checks = 0
        while self.clock.now() < cfg.max_time:
            if self._slo is not None:
                self._slo.sample(self.clock())
            if self._snapshots is not None:
                self._snapshots.maybe_write(self.clock())
            moved = self._issue_due_edits()
            moved |= self._poll_clients()
            moved |= self._deliver_up()
            moved |= self._pump_server()
            moved |= self._deliver_down()
            if moved:
                idle_checks = 0
                self.clock.advance(cfg.tick)
                continue
            wake = self._next_wakeup()
            if wake is not None:
                self.clock.advance(max(wake - self.clock.now(), cfg.tick))
                continue
            # fully quiet: either converged, or a stalled pair needs a
            # kick (re-activate unconverged channels so generate runs)
            unconverged = self._unconverged()
            if not unconverged and self._next_event >= len(self._schedule):
                return True
            idle_checks += 1
            if idle_checks > 50:
                return False  # persistent stall; report unconverged
            for client in unconverged:
                self._active.add(client.index)
                self.server.wake(client.index)
            self.clock.advance(cfg.session.timeout)
        return False
