"""amserve: the asynchronous serving front door for the merge farm.

Everything below this package is library-shaped — callers hold a
``TpuDocFarm`` and drive batched calls themselves. This package is the
service layer a fleet of concurrent editors would actually hit, built with
the same continuous-batching discipline that keeps TPU LLM serving dense
(PAPERS.md: Ragged Paged Attention): collect requests across clients,
dispatch one dense device batch, fan the results back out.

Three parts:

- **Session multiplexer** (``serve/server.py``): ``AmServer`` owns one
  supervised ``SyncSession`` (PR 5) per client channel, created through
  ``SyncFarm.make_session``/``restore_session`` so connect/resume/restart
  ride the existing epoch machinery. The core is sans-io and runs
  entirely on an injectable clock — tests and the load harness drive it
  in simulated time (``ManualClock``) — with a thin asyncio adapter for
  real transports.
- **Dynamic batching scheduler** (``serve/batcher.py``):
  ``DynamicBatcher`` accumulates incoming payload frames per document
  across clients until ≤T seconds elapse or N documents are dirty, then
  issues ONE batched farm dispatch (``receive_messages`` →
  ``apply_changes(isolation="doc")``) and fans patches and sync replies
  back per session. Admission control (bounded per-tenant queues →
  ``BackpressureError``), quarantine-aware shedding (docs in the PR 3
  quarantine set are rejected at admission — ``AdmissionRejectedError`` —
  and excluded from any flush they were queued into), and a flush policy
  that records batch occupancy so density is measurable.
- **Load harness** (``serve/loadgen.py`` + ``bench.py --serve``): drives
  10^4–10^6 simulated clients over the chaos transport in simulated time
  and reports p50/p95/p99 sync latency, e2e ops/s, batch occupancy, and
  shed/backpressure counts from amtrace.

See README "Serving" for the architecture sketch and the ``serve.*``
metric catalog.
"""
from __future__ import annotations

from .batcher import BatcherConfig, DynamicBatcher, FlushReport
from .loadgen import LoadConfig, LoadGen
from .server import AmServer, ClientChannel

__all__ = [
    "AmServer",
    "BatcherConfig",
    "ClientChannel",
    "DynamicBatcher",
    "FlushReport",
    "LoadConfig",
    "LoadGen",
]
