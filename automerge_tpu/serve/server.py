"""Session multiplexer: one supervised sync channel per client, one farm.

``AmServer`` is the front door a fleet of editors connects to. Each client
channel owns a PR 5 ``SyncSession`` created through the batched farm's
``SyncFarm.make_session`` (or ``restore_session`` for resume-after-
restart), so every reliability property of the supervised protocol —
seq/ack framing, retransmission with backoff, duplicate idempotency,
epoch-based peer-restart detection, the convergence watchdog — holds per
channel with zero new wire format. Incoming payload frames do NOT apply
individually: they run through the ``DynamicBatcher``, which turns a
window of frames from many clients into one batched farm dispatch and
fans the patches and owed replies back out.

The core is sans-io and clock-injected: ``receive`` ingests a frame,
``tick`` flushes the batcher when its window is due, ``pump`` drains
every frame the sessions owe (acks, replies, retransmissions). A test, a
chaos harness or the load generator calls those three methods against a
``ManualClock`` and the whole service runs in simulated time (amlint
AM402/AM403 keep wall clocks and blocking calls out of this module). The
``serve_forever`` adapter binds the same core to asyncio streams with
length-prefixed frames for real transports.

Connect/resume/restart ride the existing epoch machinery:

- ``connect`` creates a fresh server-side session (new epoch). A client
  that restarts and reconnects keeps talking to the *same* server
  session, whose peer-restart detection sees the client's new epoch and
  re-handshakes cleanly.
- ``resume`` rebuilds a channel from a ``save_session`` blob after a
  *server* restart; clients observe the same epoch and continue without
  a restart exchange.
"""
from __future__ import annotations

import random
from dataclasses import replace as _dc_replace

from ..errors import (
    AdmissionRejectedError,
    AutomergeError,
    BackpressureError,
)
from ..obs.metrics import get_metrics
from ..obs.scope import get_amscope
from ..sync_session import SessionConfig, _default_clock
from ..tpu.sync_farm import SyncFarm
from .batcher import BatcherConfig, DynamicBatcher, FlushReport

_METRICS = get_metrics()
_AMSCOPE = get_amscope()
_M_CONNECTS = _METRICS.counter(
    "serve.sessions.connected", "client channels opened (connect)"
)
_M_RESUMES = _METRICS.counter(
    "serve.sessions.resumed", "client channels rebuilt from persisted state"
)
_M_DISCONNECTS = _METRICS.counter(
    "serve.sessions.disconnected", "client channels closed"
)
_M_ACTIVE = _METRICS.gauge(
    "serve.sessions.active", "client channels currently connected"
)
_M_FRAMES_IN = _METRICS.counter(
    "serve.frames.received", "frames ingested from client transports"
)
_M_FRAMES_OUT = _METRICS.counter(
    "serve.frames.sent", "frames produced for client transports"
)


class ClientChannel:
    """One client's server-side state: its supervised session, target doc,
    tenant (the admission-control dimension), outbound frame queue and the
    committed request scopes awaiting their ack-send mark."""

    __slots__ = ("client_id", "tenant", "doc", "session", "outbox",
                 "pending_scopes")

    def __init__(self, client_id, tenant, doc, session):
        self.client_id = client_id
        self.tenant = tenant
        self.doc = doc
        self.session = session
        self.outbox: list[bytes] = []
        # amscope: scopes committed by a flush whose ack has not left yet
        # (the ack rides the channel's next outbound frame); empty and
        # untouched when request tracing is off
        self.pending_scopes: list = []


class AmServer:
    """The serving core. Drive it with three calls (all clock-injected):

    - ``receive(client_id, frame)`` — ingest one frame from a client's
      transport. Admission control runs here; rejections raise
      (``AdmissionRejectedError``/``BackpressureError``) and the frame is
      dropped unacked, which is the backpressure signal — the client's
      session retransmits after its backoff.
    - ``tick()`` — flush the batcher if its window is due; returns the
      ``FlushReport`` (or None). Call it from the event loop's timer.
    - ``pump()`` — collect every (client_id, frame) the sessions owe:
      acks and replies for channels the last flush touched, plus
      retransmissions whose deadlines passed. Send them, then call again
      until it returns nothing.
    """

    def __init__(self, farm, *, clock=None, rng=None,
                 config: BatcherConfig | None = None,
                 session_config: SessionConfig | None = None):
        self.farm = farm
        self.sync = SyncFarm(farm)
        self.clock = clock if clock is not None else _default_clock
        self.rng = rng if rng is not None else random.Random()
        self.session_config = session_config or SessionConfig()
        self.batcher = DynamicBatcher(self.sync, clock=self.clock,
                                      config=config)
        self.channels: dict[object, ClientChannel] = {}
        self._doc_channels: dict[int, set] = {}   # doc -> client ids
        # channels that may owe frames: polled by pump() until they go
        # quiet (poll() returns None with nothing in flight)
        self._active: set = set()

    # -------------------------------------------------------------- #
    # connect / resume / restart

    def connect(self, client_id, doc: int, tenant: str = "default",
                v2: bool | None = None) -> ClientChannel:
        """Opens (or returns) the channel for ``client_id``. Reconnects
        keep the existing server-side session: a restarted client arrives
        with a new epoch and the session's peer-restart detection
        re-handshakes; a merely-reconnected client continues mid-stream.

        ``v2`` overrides the server's default ``session_config.enable_v2``
        for this channel (the per-client opt-in a ``HELLO ... v2`` token
        carries); None inherits the server default. Enabling it only
        *advertises* — the session still speaks byte-for-byte v1 to a
        peer that never negotiates."""
        channel = self.channels.get(client_id)
        if channel is not None:
            self._active.add(client_id)
            return channel
        config = self.session_config
        if v2 is not None and v2 != config.enable_v2:
            config = _dc_replace(config, enable_v2=v2)
        session = self.sync.make_session(
            doc, clock=self.clock,
            rng=random.Random(self.rng.getrandbits(64)),
            config=config,
        )
        return self._install(client_id, tenant, doc, session, _M_CONNECTS)

    def resume(self, client_id, doc: int, blob: bytes,
               tenant: str = "default") -> ClientChannel:
        """Rebuilds a channel from a ``save_session`` blob (server
        restart): same epoch and seq/ack watermarks, so the client
        continues without a restart exchange."""
        self.channels.pop(client_id, None)
        session = self.sync.restore_session(
            doc, blob, clock=self.clock,
            rng=random.Random(self.rng.getrandbits(64)),
            config=self.session_config,
        )
        return self._install(client_id, tenant, doc, session, _M_RESUMES)

    def _install(self, client_id, tenant, doc, session, counter
                 ) -> ClientChannel:
        channel = ClientChannel(client_id, tenant, doc, session)
        self.channels[client_id] = channel
        self._doc_channels.setdefault(doc, set()).add(client_id)
        self._active.add(client_id)
        counter.inc()
        _M_ACTIVE.set(len(self.channels))
        return channel

    def save_session(self, client_id) -> bytes:
        """Durable snapshot of one channel (feed to ``resume``)."""
        return self.channels[client_id].session.save()

    def disconnect(self, client_id) -> None:
        channel = self.channels.pop(client_id, None)
        if channel is None:
            return
        self._doc_channels.get(channel.doc, set()).discard(client_id)
        self._active.discard(client_id)
        _M_DISCONNECTS.inc()
        _M_ACTIVE.set(len(self.channels))

    # -------------------------------------------------------------- #
    # the three-call event loop

    def receive(self, client_id, frame: bytes) -> None:
        """Ingests one frame. Raises ``KeyError`` for unknown clients and
        the admission errors (``AdmissionRejectedError`` /
        ``BackpressureError``) when the batcher refuses the frame — the
        caller drops it and the client's retransmission is the retry.

        Request tracing attaches here: when amscope is enabled, the frame
        gets a trace context (trace id, tenant, doc, client, bytes) that
        rides the batching window into the batched dispatch; admission
        rejections are counted against the tenant before re-raising."""
        channel = self.channels[client_id]
        _M_FRAMES_IN.inc()
        scope = (
            _AMSCOPE.attach(channel.tenant, channel.doc, client_id,
                            t=self.clock(), nbytes=len(frame))
            if _AMSCOPE.enabled else None
        )
        try:
            self.batcher.submit(channel, frame, scope)
        except AdmissionRejectedError:
            if scope is not None:
                _AMSCOPE.drop(scope, "shed")
            raise
        except BackpressureError:
            if scope is not None:
                _AMSCOPE.drop(scope, "backpressure")
            raise
        self._active.add(client_id)

    def wake(self, client_id) -> None:
        """Marks a channel as possibly owing frames so the next ``pump``
        polls it (harness hook: e.g. forcing a generate on an unconverged
        pair after a quiet period)."""
        if client_id in self.channels:
            self._active.add(client_id)

    def tick(self) -> FlushReport | None:
        """Flushes the batcher when its window is due. After a flush,
        every channel of every touched doc is woken so ``pump`` generates
        the fan-out (acks to the committers, fresh sync messages carrying
        the new changes to the doc's other clients)."""
        if not self.batcher.due():
            return None
        report = self.batcher.flush()
        for doc in report.touched_docs:
            self._active.update(self._doc_channels.get(doc, ()))
        for channel, _patch in report.committed:
            self._active.add(channel.client_id)
        return report

    def pump(self) -> list[tuple[object, bytes]]:
        """One sweep over the channels that may owe frames. Returns
        (client_id, frame) pairs for the transport; channels that produce
        nothing and have nothing in flight go quiet until a frame, a
        flush or a reconnect wakes them. Channels with an unacked payload
        stay awake so their retransmission deadlines are observed.

        Generation is batched: channels whose envelope layer says "the
        channel is clear, generate" are collected and served by ONE
        ``SyncFarm.generate_messages`` call — every Bloom filter build and
        query for the sweep runs as a single device program, the sending-
        side twin of the batcher's single receive dispatch."""
        from ..sync_session import NEEDS_GENERATE

        out: list[tuple[object, bytes]] = []
        need_generate: list[ClientChannel] = []
        for client_id in sorted(self._active, key=repr):
            channel = self.channels.get(client_id)
            if channel is None:
                self._active.discard(client_id)
                continue
            ready = channel.session.poll_begin()
            if ready is NEEDS_GENERATE:
                need_generate.append(channel)
            elif ready is not None:
                out.append((client_id, ready))
                _M_FRAMES_OUT.inc()
                self._mark_sent(channel)
            elif channel.session.pending is None:
                # quiet and nothing awaiting ack: sleep until woken
                self._active.discard(client_id)
        if need_generate:
            generate_t0 = self.clock()
            results = self.sync.generate_messages(
                [(c.doc, c.session.state) for c in need_generate],
                protocols=[
                    "v2" if c.session.v2_active else "v1"
                    for c in need_generate
                ],
            )
            if _AMSCOPE.enabled:
                _AMSCOPE.observe_phase(
                    "generate", self.clock() - generate_t0
                )
            for channel, (state, payload) in zip(need_generate, results):
                frame = channel.session.poll_commit(state, payload)
                if frame is not None:
                    out.append((channel.client_id, frame))
                    _M_FRAMES_OUT.inc()
                    self._mark_sent(channel)
                elif channel.session.pending is None:
                    self._active.discard(channel.client_id)
        return out

    def _mark_sent(self, channel: ClientChannel) -> None:
        """Finishes the channel's committed request scopes: the outbound
        frame just queued carries their ack, which ends the request's
        journey (receive -> window -> dispatch -> commit -> ack-send).
        One truthiness test when request tracing is off."""
        if channel.pending_scopes:
            now = self.clock()
            for scope in channel.pending_scopes:
                scope.mark("sent", now)
                _AMSCOPE.finish(scope)
            channel.pending_scopes.clear()

    def next_deadline(self) -> float | None:
        """The earliest future instant the core needs a ``tick``/``pump``
        call (batcher window expiry or a session retransmission deadline);
        None when fully idle. Harnesses jump simulated time here."""
        deadlines = []
        window = self.batcher.next_deadline()
        if window is not None:
            deadlines.append(window)
        for client_id in self._active:
            channel = self.channels.get(client_id)
            if channel is not None and channel.session.pending is not None:
                deadlines.append(channel.session.pending["deadline"])
        return min(deadlines, default=None)

    # -------------------------------------------------------------- #
    # asyncio adapter (real transports; the core above stays sans-io)

    async def serve_forever(self, host: str = "127.0.0.1", port: int = 0,
                            *, telemetry_port: int | None = None,
                            snapshot_path: str | None = None,
                            snapshot_interval: float = 5.0,
                            slo_engine=None):
        """Binds the core to asyncio streams: 4-byte big-endian length-
        prefixed frames, one connection per client. The first frame of a
        connection is a text hello ``b"HELLO <client_id> <doc> <tenant>"``;
        everything after is session frames. Runs until cancelled. Returns
        the listening server object (``server.sockets[0].getsockname()``
        for the bound port). A fifth hello token ``v2`` opts the channel
        into sync v2 negotiation (``HELLO <client_id> <doc> <tenant> v2``);
        old clients' four-token hello keeps the pure-v1 channel.

        Live telemetry (obs/export.py): ``telemetry_port`` mounts the
        pull-based text exposition (metrics + tenant table with
        exemplars) on a side-car HTTP listener that never enters the
        serving data path; ``snapshot_path`` appends a JSONL telemetry
        snapshot every ``snapshot_interval`` seconds from the flusher
        task — the file ``python -m automerge_tpu.obs --watch`` renders.
        ``slo_engine`` (an ``obs.slo.SLOEngine``) is evaluated from the
        flusher on this server's clock — the wall-clock leg of the SLO
        plane: its ``slo.*`` gauges ride the exposition page and every
        snapshot line carries the verdicts."""
        import asyncio

        from ..obs.export import SnapshotWriter, serve_exposition

        writer_snapshots = (
            SnapshotWriter(snapshot_path, snapshot_interval,
                           clock=self.clock, slo_engine=slo_engine)
            if snapshot_path else None
        )
        telemetry = (
            await serve_exposition(host, telemetry_port)
            if telemetry_port is not None else None
        )

        writers: dict[object, asyncio.StreamWriter] = {}

        async def _send_all() -> None:
            for client_id, frame in self.pump():
                writer = writers.get(client_id)
                if writer is None:
                    continue
                writer.write(len(frame).to_bytes(4, "big") + frame)
            for writer in writers.values():
                await writer.drain()

        slo_last = None

        async def _flusher() -> None:
            nonlocal slo_last
            while True:
                await asyncio.sleep(self.batcher.config.flush_interval / 2)
                self.tick()
                await _send_all()
                if writer_snapshots is not None:
                    writer_snapshots.maybe_write()
                elif slo_engine is not None:
                    # no snapshot file to drive the export — evaluate at
                    # ~1Hz so the exposition page's slo.* gauges stay live
                    now = self.clock()
                    if slo_last is None or now - slo_last >= 1.0:
                        slo_last = now
                        slo_engine.export(now=now)

        async def _handle(reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
            client_id = None
            try:
                hello = await _read_frame(reader)
                parts = hello.decode("utf-8").split()
                if (
                    len(parts) not in (4, 5)
                    or parts[0] != "HELLO"
                    or (len(parts) == 5 and parts[4] != "v2")
                ):
                    writer.close()
                    return
                client_id, doc, tenant = parts[1], int(parts[2]), parts[3]
                self.connect(client_id, doc, tenant,
                             v2=True if len(parts) == 5 else None)
                writers[client_id] = writer
                while True:
                    frame = await _read_frame(reader)
                    try:
                        self.receive(client_id, frame)
                    except AutomergeError:
                        pass  # shed/backpressure: drop; client retransmits
                    await _send_all()
            except (asyncio.IncompleteReadError, ConnectionError):
                pass
            finally:
                if client_id is not None:
                    writers.pop(client_id, None)
                writer.close()

        async def _read_frame(reader: asyncio.StreamReader) -> bytes:
            header = await reader.readexactly(4)
            return await reader.readexactly(int.from_bytes(header, "big"))

        server = await asyncio.start_server(_handle, host, port)
        flusher = asyncio.ensure_future(_flusher())
        try:
            async with server:
                await server.serve_forever()
        finally:
            flusher.cancel()
            if telemetry is not None:
                telemetry.close()
        return server
