"""Dynamic batching scheduler: many client sync frames, one farm dispatch.

The farm's device kernel merges billions of ops per second, but only when
fed dense batches — a request-per-dispatch front door would leave it >99%
idle. ``DynamicBatcher`` is the continuous-batching layer between the
session multiplexer (serve/server.py) and the farm: payload frames from
many clients accumulate per document until the flush policy fires (≤T
seconds elapse in the window, or N documents are dirty), then ONE batched
inner receive (``SyncFarm.receive_messages``, which routes every staged
channel's changes through a single ``TpuDocFarm.apply_changes(
isolation="doc")``) commits them all, and the patches and owed sync
replies fan back out per session.

The envelope/apply split rides ``SyncSession.begin``/``commit``: at flush,
every staged frame's envelope is processed first (acks, dedup, epoch
handling), the surviving payloads are validated and dispatched as one
batch, and only successfully applied payloads are committed — so a
rejected payload is never acked and the client's retransmission retries
cleanly, exactly as in the unbatched path.

Admission control happens at ``submit`` time, before anything is queued:

- **quarantine-aware shedding** — a document in the farm's quarantine set
  (PR 3) is rejected with ``AdmissionRejectedError``; queueing its
  traffic would only grow a batch the farm will shed anyway. A doc that
  quarantines *mid-window* (poisoned by an earlier flush) is excluded
  from the flush it was queued into: its entries are dropped unacked, so
  the client retries after ``release_quarantine``.
- **per-tenant backpressure** — each tenant has a bounded pending-entry
  budget; past it, ``submit`` raises ``BackpressureError`` without
  enqueueing. The budget is returned when the window drains, so
  backpressure releases after a flush.

The batcher is farm-implementation-agnostic: fronting a process-worker
``MeshFarm`` (PR 12, ``mesh_backend="process"``) changes nothing above,
under either of the mesh's transports (PR 19, ``mesh_transport=``):
with the shared-memory data plane the flush's patch columns stay parked
in each worker's mapped result ring until this layer's reply fan-out
actually indexes them — the JSON-ified patch a session receives is
unpickled straight out of the shared segment, with no controller-side
copy in between, and a flush whose report only reads ``outcomes`` never
touches the patch bytes at all. A worker crash mid-flush surfaces
exactly like any mid-window poisoning:
the dispatch quarantines the crashed shard's in-flight docs under
``WorkerCrashError``, the flush report's ``quarantined_docs`` diff picks
them up, their entries are never acked, and clients retry after
``release_quarantine`` (the respawned worker re-hydrates from the
controller's delivery log first). The per-submit quarantine check stays
cheap because the process controller answers ``farm.quarantine`` from
its local mirror — zero worker round trips on the admission path (pinned
by tests/test_mesh_workers.py).


Everything is driven by the injected clock (``clock()`` in simulated or
real seconds) — no wall-clock reads, no sleeps, no blocking calls (amlint
AM402/AM403): the event loop or harness decides when ``flush`` runs.
"""
# amlint: error-taxonomy
from __future__ import annotations

from dataclasses import dataclass, field

from ..errors import (
    AdmissionRejectedError,
    BackpressureError,
    SyncFrameError,
    SyncProtocolError,
)
from ..obs.flight import get_flight
from ..obs.metrics import get_metrics
from ..obs.scope import dispatch_context, get_amscope
from ..sync import decode_sync_message
from ..sync_v2 import MESSAGE_TYPE_SYNC_V2, decode_sync_message_v2

_AMSCOPE = get_amscope()
_FLIGHT = get_flight()

_METRICS = get_metrics()
_M_ADMITTED = _METRICS.counter(
    "serve.admission.accepted", "frames admitted into the batching window"
)
_M_ADM_QUARANTINE = _METRICS.counter(
    "serve.admission.rejected_quarantine",
    "frames rejected at admission because the target doc is farm-quarantined",
)
_M_ADM_BACKPRESSURE = _METRICS.counter(
    "serve.admission.rejected_backpressure",
    "frames rejected at admission because the tenant's pending budget is full",
)
_M_QUEUE_DEPTH = _METRICS.gauge(
    "serve.queue.depth", "entries currently waiting in the batching window"
)
_M_DISPATCHES = _METRICS.counter(
    "serve.batch.dispatches", "flushes that issued a batched farm dispatch"
)
_M_OCCUPANCY = _METRICS.histogram(
    "serve.batch.occupancy",
    "documents carrying changes per batched farm dispatch",
)
_M_CHANGES = _METRICS.counter(
    "serve.batch.changes", "changes routed through batched dispatches"
)
_M_WINDOWS = _METRICS.counter(
    "serve.flush.windows", "non-empty batching windows flushed"
)
_M_SHED_QUARANTINED = _METRICS.counter(
    "serve.flush.shed_quarantined",
    "queued entries dropped at flush because their doc quarantined mid-window",
)
_M_REJECTED = _METRICS.counter(
    "serve.flush.frames_rejected",
    "queued frames rejected at flush (corrupt envelope or invalid payload; "
    "not acked, so the client retransmits)",
)
_M_DEFERRED = _METRICS.counter(
    "serve.flush.deferred",
    "entries pushed to the next window (their channel already staged a "
    "payload in this flush)",
)

# per-shard flush accounting when the farm is a MeshFarm (it exposes
# shard_of); registered lazily per shard id, full-literal-prefix names so
# the README catalog's <s> placeholder row matches
_SHARD_FLUSH_DOCS: dict[int, object] = {}


def _shard_flush_docs(s: int):
    c = _SHARD_FLUSH_DOCS.get(s)
    if c is None:
        c = _METRICS.counter(
            f"serve.flush.shard.{s}.docs",
            f"flushed change-carrying docs routed to mesh shard {s}",
        )
        _SHARD_FLUSH_DOCS[s] = c
    return c


@dataclass
class BatcherConfig:
    """Flush-policy knobs. Times are in the injected clock's units
    (seconds under the default monotonic clock and under ``ManualClock``).

    - ``flush_interval`` (T): a window flushes at most this long after its
      first entry arrived.
    - ``max_docs`` (N): a window flushes as soon as this many distinct
      documents are dirty, however young it is.
    - ``max_pending_per_tenant``: admission budget — entries a tenant may
      have waiting in the window before ``submit`` raises
      ``BackpressureError``.
    """

    flush_interval: float = 0.05
    max_docs: int = 64
    max_pending_per_tenant: int = 256


@dataclass
class FlushReport:
    """What one flush did: the fan-out inputs plus density accounting."""

    committed: list = field(default_factory=list)   # (channel, patch) pairs
    touched_docs: set = field(default_factory=set)  # docs whose heads may have moved
    changes_by_doc: dict = field(default_factory=dict)  # doc -> change buffers dispatched
    docs_dispatched: int = 0       # distinct docs carrying changes in the dispatch
    changes_applied: int = 0       # change buffers routed through the dispatch
    envelope_only: int = 0         # frames consumed by begin() (acks/dups/shed)
    shed_quarantined: int = 0      # entries dropped: doc quarantined mid-window
    rejected: int = 0              # frames rejected (corrupt/invalid; unacked)
    deferred: int = 0              # entries pushed to the next window
    quarantined_docs: set = field(default_factory=set)  # newly quarantined by this flush
    outcomes: object = None        # FarmApplyResult of the dispatch, or None

    @property
    def dispatched(self) -> bool:
        return self.docs_dispatched > 0


class DynamicBatcher:
    """Accumulates (channel, frame) entries and flushes them as one
    batched farm dispatch. See the module docstring for the policy; the
    owner (``AmServer`` or a harness) calls ``submit`` on arrival and
    ``flush`` whenever ``due()`` says the window fired."""

    def __init__(self, sync_farm, *, clock, config: BatcherConfig | None = None):
        self.sync = sync_farm
        self.farm = sync_farm.farm
        self.clock = clock
        self.config = config or BatcherConfig()
        self._entries: list = []          # (channel, frame_bytes), arrival order
        self._pending_by_tenant: dict[str, int] = {}
        self._dirty_docs: set[int] = set()
        self._window_start: float | None = None

    # -------------------------------------------------------------- #
    # admission

    def submit(self, channel, frame: bytes, scope=None) -> None:
        """Admits one frame into the current window, or rejects it without
        queueing: ``AdmissionRejectedError`` when the channel's doc is
        farm-quarantined (shed; nothing the batch could do would commit),
        ``BackpressureError`` when the tenant's pending budget is full.
        Rejected frames are simply not acked — the session layer's
        retransmission is the retry loop. ``scope`` is the frame's amscope
        trace context (None when request tracing is off); it rides the
        window entry so the flush can price the queue wait and link the
        request into the dispatch span."""
        if channel.doc in self.farm.quarantine:
            _M_ADM_QUARANTINE.inc()
            raise AdmissionRejectedError(
                f"document {channel.doc} is quarantined; traffic shed at "
                "admission (release_quarantine to restore)"
            )
        tenant = channel.tenant
        if (
            self._pending_by_tenant.get(tenant, 0)
            >= self.config.max_pending_per_tenant
        ):
            _M_ADM_BACKPRESSURE.inc()
            raise BackpressureError(
                f"tenant {tenant!r} has "
                f"{self._pending_by_tenant[tenant]} entries pending (budget "
                f"{self.config.max_pending_per_tenant}); back off and retry "
                "after the window drains"
            )
        if self._window_start is None:
            self._window_start = self.clock()
        self._entries.append((channel, frame, scope))
        self._pending_by_tenant[tenant] = (
            self._pending_by_tenant.get(tenant, 0) + 1
        )
        self._dirty_docs.add(channel.doc)
        _M_ADMITTED.inc()
        _M_QUEUE_DEPTH.set(len(self._entries))

    @property
    def pending(self) -> int:
        return len(self._entries)

    def pending_for(self, tenant: str) -> int:
        return self._pending_by_tenant.get(tenant, 0)

    def due(self, now: float | None = None) -> bool:
        """True when the window should flush: N distinct docs are dirty,
        or T has elapsed since the window opened. An empty window is never
        due — empty ticks dispatch nothing."""
        if not self._entries:
            return False
        if len(self._dirty_docs) >= self.config.max_docs:
            return True
        now = self.clock() if now is None else now
        return now - self._window_start >= self.config.flush_interval

    def next_deadline(self) -> float | None:
        """When the open window will become due by timer (None when the
        window is empty) — harnesses jump simulated time here."""
        if self._window_start is None:
            return None
        return self._window_start + self.config.flush_interval

    # -------------------------------------------------------------- #
    # the dispatch point

    def flush(self) -> FlushReport:
        """Drains the window: envelope-processes every queued frame,
        validates the payloads, dispatches all surviving channels' changes
        as ONE batched inner receive, commits and fans out. Entries whose
        doc quarantined mid-window are shed unacked; a channel with more
        than one queued payload keeps its extras for the next window
        (stop-and-wait means they are retransmissions or pipelined frames
        that must see the committed state first)."""
        report = FlushReport()
        if not self._entries:
            return report
        flush_reason = (
            "count" if len(self._dirty_docs) >= self.config.max_docs
            else "timer"
        )
        entries, self._entries = self._entries, []
        self._dirty_docs = set()
        self._window_start = None
        _M_WINDOWS.inc()
        now = self.clock()

        quarantined_before = set(self.farm.quarantine)
        staged = []      # (channel, pre, msg, scope) pending batched receive
        staged_docs = set()
        deferred = []
        for channel, frame, scope in entries:
            if channel.doc in quarantined_before:
                # quarantined mid-window: excluded from the flush it was
                # queued into; dropped unacked so the client retries later
                report.shed_quarantined += 1
                _M_SHED_QUARANTINED.inc()
                self._consume(channel)
                if scope is not None:
                    _AMSCOPE.drop(scope, "shed")
                continue
            try:
                pre = channel.session.begin(frame)
            except SyncFrameError:
                report.rejected += 1
                _M_REJECTED.inc()
                self._consume(channel)
                if scope is not None:
                    _AMSCOPE.drop(scope, "rejected")
                continue
            if pre is None:
                report.envelope_only += 1
                self._consume(channel)
                if scope is not None:
                    _AMSCOPE.finish(scope, outcome="envelope")
                continue
            if channel.doc in staged_docs:
                # one payload per DOC per dispatch: a second channel of
                # the same doc would force receive_messages off the
                # batched path (per-channel applies, one device dispatch
                # each — exactly the sparsity this layer exists to kill).
                # The frame waits one window (begin's envelope effects
                # are idempotent for an uncommitted payload; its seq is
                # still unacked, so re-processing it is the normal path).
                deferred.append((channel, frame, scope))
                continue
            payload = pre["payload"]
            is_v2 = bool(payload) and payload[0] == MESSAGE_TYPE_SYNC_V2
            try:
                msg = (
                    decode_sync_message_v2(payload) if is_v2
                    else decode_sync_message(payload)
                )
            except (SyncProtocolError, ValueError, TypeError, IndexError):
                if is_v2 and getattr(channel.session, "v2_local", False):
                    # the v2 fallback contract (sync_session): a poisoned
                    # v2 frame is ACKED with state unchanged — withholding
                    # the ack would retransmit the same frame until
                    # quarantine — and the session latches its downgrade
                    # to v1. Route this rare path through the unbatched
                    # receive, which carries exactly those semantics.
                    patch = channel.session.handle(frame)
                    report.committed.append((channel, patch))
                    report.touched_docs.add(channel.doc)
                    self._consume(channel)
                    if scope is not None:
                        _AMSCOPE.finish(scope, outcome="fallback")
                    continue
                # invalid inner payload: not committed, therefore not
                # acked — the peer's intact retransmission retries
                report.rejected += 1
                _M_REJECTED.inc()
                self._consume(channel)
                if scope is not None:
                    _AMSCOPE.drop(scope, "rejected")
                continue
            staged.append((channel, pre, msg, scope))
            staged_docs.add(channel.doc)
            self._consume(channel)

        if deferred:
            # re-open the window with the deferred entries (their tenant
            # budget is still held — they were admitted, not dropped)
            report.deferred = len(deferred)
            _M_DEFERRED.inc(len(deferred))
            self._entries = deferred
            self._dirty_docs = {c.doc for c, _, _ in deferred}
            self._window_start = now

        if _FLIGHT.enabled:
            _FLIGHT.record(
                "batcher.flush", t=now, reason=flush_reason,
                entries=len(entries), staged=len(staged),
                docs=len(staged_docs), deferred=report.deferred,
                shed=report.shed_quarantined, rejected=report.rejected,
            )

        if staged:
            triples = [
                (channel.doc, channel.session.state, pre["payload"])
                for channel, pre, _, _ in staged
            ]
            # ONE batched inner receive: every channel's changes route
            # through a single farm.apply_changes(isolation="doc"). When
            # request tracing is on, ONE DispatchSpan links every staged
            # request trace and captures the farm's per-phase breakdown
            # (the honest attribution for batched execution); the ambient
            # dispatch context lets the farm's latency histograms stamp
            # this span's id as their bucket exemplar.
            span = None
            if _AMSCOPE.enabled:
                span = _AMSCOPE.begin_dispatch(
                    [s.trace_id for _, _, _, s in staged if s is not None],
                    now,
                )
                for _, _, _, scope in staged:
                    if scope is not None:
                        scope.mark("flush", now)
                        scope.dispatch_id = span.dispatch_id
                from ..profiling import PhaseProfile, use_profile

                prof = PhaseProfile()
                with dispatch_context(span), use_profile(prof):
                    results = self.sync.receive_messages(triples)
            else:
                results = self.sync.receive_messages(triples)
            committed_at = self.clock()
            report.outcomes = self.sync.last_apply
            change_docs = {
                channel.doc
                for (channel, _, msg, _) in staged
                if msg["changes"]
            }
            report.changes_by_doc = {
                channel.doc: list(msg["changes"])
                for (channel, _, msg, _) in staged
                if msg["changes"]
            }
            report.docs_dispatched = len(change_docs)
            report.changes_applied = sum(
                len(msg["changes"]) for _, _, msg, _ in staged
            )
            if change_docs:
                _M_DISPATCHES.inc()
                _M_OCCUPANCY.observe(len(change_docs))
                _M_CHANGES.inc(report.changes_applied)
                shard_of = getattr(self.farm, "shard_of", None)
                if shard_of is not None and _METRICS.enabled:
                    # mesh-backed serving: label the flush's doc fan-out
                    # by owning shard (the sub-dispatch concurrency lives
                    # inside MeshFarm.apply_changes)
                    for doc in change_docs:
                        _shard_flush_docs(shard_of(doc)).inc()
            if span is not None:
                phases = {
                    path: entry["total_s"]
                    for path, entry in prof.as_dict().items()
                    if "/" not in path  # farm phases open at the root
                }
                _AMSCOPE.end_dispatch(
                    span, committed_at, phases=phases,
                    docs=len(change_docs), changes=report.changes_applied,
                )
            for (channel, pre, msg, scope), (state, patch) in zip(
                staged, results
            ):
                patch = channel.session.commit(pre, state, patch)
                report.committed.append((channel, patch))
                report.touched_docs.add(channel.doc)
                if scope is not None:
                    scope.mark("committed", committed_at)
                    scope.changes = len(msg["changes"])
                    scope.phases = span.phases if span is not None else None
                    # the ack rides the next outbound frame; the server's
                    # pump marks "sent" and finishes the scope
                    channel.pending_scopes.append(scope)

        report.quarantined_docs = (
            set(self.farm.quarantine) - quarantined_before
        )
        _M_QUEUE_DEPTH.set(len(self._entries))
        return report

    def _consume(self, channel) -> None:
        tenant = channel.tenant
        left = self._pending_by_tenant.get(tenant, 0) - 1
        if left > 0:
            self._pending_by_tenant[tenant] = left
        else:
            self._pending_by_tenant.pop(tenant, None)
