"""Backend API: a stateless function facade over the OpSet engine.

Port of /root/reference/backend/backend.js. Wraps the engine state in a
`BackendHandle` with move-semantics (old handles are frozen after use,
backend/util.js:1-10) so stale states cannot be mutated accidentally.

This module is the swappable-backend contract: any engine implementing these
functions (the pure-Python OpSet here, or the TPU batched engine in
automerge_tpu.tpu) can serve the same frontend.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from .columnar import encode_change
from .obs.metrics import get_metrics
from .obs.spans import get_trace
from .opset import OpSet

_M_CHANGES_APPLIED = get_metrics().counter(
    "backend.changes.applied", "changes applied through the backend facade"
)


class BackendHandle:
    __slots__ = ("state", "heads", "frozen")

    def __init__(self, state, heads):
        self.state = state
        self.heads = heads
        self.frozen = False


def _backend_state(backend: BackendHandle) -> OpSet:
    if backend.frozen:
        raise ValueError(
            "Attempting to use an outdated Automerge document that has already been updated. "
            "Please use the latest document state, or call Automerge.clone() if you really "
            "need to use this old document state."
        )
    return backend.state


def init() -> BackendHandle:
    return BackendHandle(OpSet(), [])


def clone(backend: BackendHandle) -> BackendHandle:
    return BackendHandle(_backend_state(backend).clone(), backend.heads)


def free(backend: BackendHandle) -> None:
    backend.state = None
    backend.frozen = True


def apply_changes(backend: BackendHandle, changes):
    state = _backend_state(backend)
    with get_trace().span("backend.apply_changes"):
        patch = state.apply_changes(changes)
    _M_CHANGES_APPLIED.inc(len(changes))
    backend.frozen = True
    return BackendHandle(state, state.heads), patch


def _hash_by_actor(state: OpSet, actor_id: str, index: int) -> str:
    hashes = state.hashes_by_actor.get(actor_id)
    if hashes and index < len(hashes) and hashes[index]:
        return hashes[index]
    if not state.have_hash_graph:
        state.compute_hash_graph()
        hashes = state.hashes_by_actor.get(actor_id)
        if hashes and index < len(hashes) and hashes[index]:
            return hashes[index]
    raise ValueError(f"Unknown change: actorId = {actor_id}, seq = {index + 1}")


def apply_local_change(backend: BackendHandle, change):
    """Applies a change request from the local frontend; returns
    (new_backend, patch, binary_change). Adds the local actor's previous
    change hash to deps (backend.js:54-91)."""
    state = _backend_state(backend)
    if change["seq"] <= state.clock.get(change["actor"], 0):
        raise ValueError("Change request has already been applied")

    if change["seq"] > 1:
        last_hash = _hash_by_actor(state, change["actor"], change["seq"] - 2)
        if not last_hash:
            raise ValueError(f"Cannot find hash of localChange before seq={change['seq']}")
        deps = {last_hash: True}
        for h in change["deps"]:
            deps[h] = True
        change = dict(change, deps=sorted(deps.keys()))

    binary_change = encode_change(change)
    with get_trace().span("backend.apply_local_change"):
        patch = state.apply_changes([binary_change], is_local=True)
    _M_CHANGES_APPLIED.inc()
    backend.frozen = True

    # On the outgoing patch, omit the last local change hash
    last_hash = _hash_by_actor(state, change["actor"], change["seq"] - 1)
    patch["deps"] = [head for head in patch["deps"] if head != last_hash]
    return BackendHandle(state, state.heads), patch, binary_change


def save(backend: BackendHandle) -> bytes:
    with get_trace().span("backend.save"):
        return _backend_state(backend).save()


def load(data) -> BackendHandle:
    with get_trace().span("backend.load"):
        state = OpSet(data)
    return BackendHandle(state, state.heads)


def load_changes(backend: BackendHandle, changes) -> BackendHandle:
    """Applies changes without building a patch (faster for bulk loads)."""
    state = _backend_state(backend)
    state.apply_changes(changes)
    backend.frozen = True
    return BackendHandle(state, state.heads)


def get_patch(backend: BackendHandle):
    with get_trace().span("backend.get_patch"):
        return _backend_state(backend).get_patch()


def get_heads(backend: BackendHandle):
    return backend.heads


def get_all_changes(backend: BackendHandle):
    return get_changes(backend, [])


def get_changes(backend: BackendHandle, have_deps):
    if not isinstance(have_deps, list):
        raise TypeError("Pass a list of hashes to get_changes()")
    return _backend_state(backend).get_changes(have_deps)


def get_changes_added(backend1: BackendHandle, backend2: BackendHandle):
    return _backend_state(backend2).get_changes_added(_backend_state(backend1))


def get_change_by_hash(backend: BackendHandle, hash_):
    return _backend_state(backend).get_change_by_hash(hash_)


def get_missing_deps(backend: BackendHandle, heads=()):
    return _backend_state(backend).get_missing_deps(heads)
