"""ctypes bindings for the native C++ columnar codecs (native/codecs.cpp).

The native library accelerates the host-side transcoding between the
variable-length column formats and dense numpy arrays (the input/output of
the TPU engine). Falls back to the pure-Python codecs when the library has
not been built; `available()` reports which path is active.

Build with: make -C native   (or python -m automerge_tpu.native --build)
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

NULL_SENTINEL = -(2**62)

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "native", "libamcodecs.so")
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.am_rle_decode.restype = ctypes.c_int64
    lib.am_rle_decode.argtypes = [u8p, ctypes.c_size_t, ctypes.c_int,
                                  ctypes.c_int64, i64p, ctypes.c_size_t]
    lib.am_rle_encode.restype = ctypes.c_int64
    lib.am_rle_encode.argtypes = [i64p, ctypes.c_size_t, ctypes.c_int,
                                  ctypes.c_int64, u8p, ctypes.c_size_t]
    lib.am_delta_decode.restype = ctypes.c_int64
    lib.am_delta_decode.argtypes = [u8p, ctypes.c_size_t, ctypes.c_int64,
                                    i64p, ctypes.c_size_t]
    lib.am_delta_encode.restype = ctypes.c_int64
    lib.am_delta_encode.argtypes = [i64p, ctypes.c_size_t, ctypes.c_int64,
                                    u8p, ctypes.c_size_t]
    lib.am_bool_decode.restype = ctypes.c_int64
    lib.am_bool_decode.argtypes = [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
    lib.am_bool_encode.restype = ctypes.c_int64
    lib.am_bool_encode.argtypes = [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
    if hasattr(lib, "am_strrle_decode"):
        lib.am_strrle_decode.restype = ctypes.c_int64
        lib.am_strrle_decode.argtypes = [u8p, ctypes.c_size_t, u8p,
                                         ctypes.c_size_t, i64p, ctypes.c_size_t]
    _lib = lib
    return lib


def build(verbose=False):
    """Compiles the native library with g++."""
    native_dir = os.path.dirname(_LIB_PATH)
    result = subprocess.run(["make", "-C", native_dir],
                            capture_output=not verbose, text=True)
    if result.returncode != 0:
        raise RuntimeError(f"native build failed: {result.stderr}")
    global _lib
    _lib = None
    return _load() is not None


def available() -> bool:
    return _load() is not None


def _check(rc, what):
    if rc < 0:
        raise ValueError(f"native {what} failed with code {rc}")
    return rc


def _as_u8p(buf):
    return ctypes.cast(ctypes.c_char_p(bytes(buf)), ctypes.POINTER(ctypes.c_uint8))


def rle_decode(buf: bytes, signed: bool = False, max_count: int = None) -> np.ndarray:
    """Decodes an RLE column into an int64 array (nulls = NULL_SENTINEL)."""
    lib = _load()
    cap = max_count if max_count is not None else max(16, len(buf) * 64)
    out = np.empty(cap, np.int64)
    rc = lib.am_rle_decode(
        _as_u8p(buf), len(buf), 1 if signed else 0, NULL_SENTINEL,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap,
    )
    return out[:_check(rc, "rle_decode")]


def rle_encode(values: np.ndarray, signed: bool = False) -> bytes:
    lib = _load()
    values = np.ascontiguousarray(values, np.int64)
    cap = max(16, values.size * 10)
    out = np.empty(cap, np.uint8)
    rc = lib.am_rle_encode(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), values.size,
        1 if signed else 0, NULL_SENTINEL,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
    )
    return out[:_check(rc, "rle_encode")].tobytes()


def delta_decode(buf: bytes, max_count: int = None) -> np.ndarray:
    lib = _load()
    cap = max_count if max_count is not None else max(16, len(buf) * 64)
    out = np.empty(cap, np.int64)
    rc = lib.am_delta_decode(
        _as_u8p(buf), len(buf), NULL_SENTINEL,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap,
    )
    return out[:_check(rc, "delta_decode")]


def delta_encode(values: np.ndarray) -> bytes:
    lib = _load()
    values = np.ascontiguousarray(values, np.int64)
    cap = max(16, values.size * 10)
    out = np.empty(cap, np.uint8)
    rc = lib.am_delta_encode(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), values.size,
        NULL_SENTINEL,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
    )
    return out[:_check(rc, "delta_encode")].tobytes()


def bool_decode(buf: bytes, max_count: int = None) -> np.ndarray:
    lib = _load()
    cap = max_count if max_count is not None else max(16, len(buf) * 4096)
    out = np.empty(cap, np.uint8)
    rc = lib.am_bool_decode(
        _as_u8p(buf), len(buf),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
    )
    return out[:_check(rc, "bool_decode")].astype(bool)


def strrle_decode(buf: bytes, max_count: int = None):
    """Decodes a string-RLE column; returns (blob bytes, offsets int64[n,2])
    where a row's string is blob[start:end], or (-1, -1) for null."""
    lib = _load()
    if not hasattr(lib, "am_strrle_decode"):
        raise AttributeError("native library predates am_strrle_decode; rebuild")
    cap = max_count if max_count is not None else max(16, len(buf) * 64)
    blob_cap = max(64, len(buf) * 64)
    blob = np.empty(blob_cap, np.uint8)
    offs = np.empty(cap * 2, np.int64)
    rc = lib.am_strrle_decode(
        _as_u8p(buf), len(buf),
        blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), blob_cap,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap,
    )
    n = _check(rc, "strrle_decode")
    return blob.tobytes(), offs[: 2 * n].reshape(n, 2)


def bool_encode(values: np.ndarray) -> bytes:
    lib = _load()
    values = np.ascontiguousarray(values, np.uint8)
    cap = max(16, values.size * 10 + 16)
    out = np.empty(cap, np.uint8)
    rc = lib.am_bool_encode(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), values.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
    )
    return out[:_check(rc, "bool_encode")].tobytes()


if __name__ == "__main__":
    import sys

    if "--build" in sys.argv:
        ok = build(verbose=True)
        print("native codecs built" if ok else "build failed")
