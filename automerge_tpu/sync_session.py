"""Session supervision for the sync protocol: reliable delivery over lossy
transports.

The reference Bloom-filter protocol (automerge_tpu/sync.py, backend/sync.js)
is specified over a reliable, in-order, exactly-once transport. This module
supplies that transport contract on top of an unreliable one: each
``SyncSession`` supervises one peer channel, wrapping
``generate_sync_message``/``receive_sync_message`` in a compact outer
envelope — the inner payload stays the reference wire format, byte for
byte — that adds:

- **sequence numbers + acks** (stop-and-wait): duplicate and stale frames
  are idempotent no-ops, counted on ``sync.session.dup_dropped``;
- **timeout + bounded retransmission** with exponential backoff and full
  jitter, driven by an *injectable* clock and RNG (amlint AM402 bans
  ``time.time``/``random.*`` from the sync data plane);
- **channel quarantine** after the retry budget is exhausted — the channel
  is shed, mirroring the doc farm's quarantine lifecycle (PR 3), while the
  documents stay live;
- **peer-restart detection**: every session carries a random ``epoch``; a
  peer that comes back with a new epoch gets a clean re-handshake (seq
  tracking reset, our beliefs about the peer dropped) instead of a
  permanent heads mismatch;
- **a convergence watchdog**: no head/sharedHeads progress across K
  supervised rounds while payload frames still flow escalates — first a
  Bloom-filter rebuild (clear ``sentHashes``/``lastSentHeads``, resending
  anything wrongly withheld, e.g. after a pathological Bloom
  false-positive loop), then a full reset exchange (``sharedHeads = []``
  and the peer's filter treated as empty, so everything is offered
  explicitly).

Sessions persist through the existing ``encode_sync_state`` path:
``save()`` appends a versioned extension block (epoch/seq/ack watermarks)
that pre-extension decoders skip, and ``restore()`` resumes a channel
mid-sync after a process restart.

Frame layout (outer framing only; ``FRAME_TYPE`` is disjoint from the
``MESSAGE_TYPE_SYNC``/``PEER_STATE_TYPE`` record space)::

    byte  FRAME_TYPE (0x44)
    4B    checksum = sha256(body)[:4]     (rejects in-flight corruption)
    body: uint32 epoch | uint53 seq | uint53 ack | byte flags
          [prefixed payload when flags & FLAG_PAYLOAD]

``seq`` is 0 on ack-only frames (they carry no payload and are never
retransmitted); payload frames use a monotonic per-session sequence.

**Protocol negotiation (sync v2).** ``FLAG_V2`` in the flags byte
advertises that the sender speaks the range-based reconciliation
protocol (automerge_tpu/sync_v2.py). Pre-v2 decoders only test
``flags & FLAG_PAYLOAD``, so the bit is invisible to them — a v2 session
talking to a v1 peer produces byte-for-byte the v1 exchange. A session
switches to v2 generation only once BOTH sides have shown the flag
(``v2_active``); inbound payloads dispatch on their leading type byte,
so mixed-protocol transition windows are safe. If a v2 exchange errors,
the session latches ``v2_fallback``: the failed inbound frame is acked
(NOT withheld — a withheld ack would retransmit the same poisoned frame
until quarantine), the flag is dropped from outgoing frames so the peer
downgrades too, and the v1 machinery — Bloom filters, watchdog
escalation ladder and all — takes over. Never a stalled channel.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from hashlib import sha256

from . import backend as Backend
from .codecs import Decoder, Encoder
from .errors import (
    ChannelQuarantinedError,
    RetryExhaustedError,
    SyncFrameError,
    SyncProtocolError,
)
from .obs.flight import get_flight
from .obs.metrics import get_metrics
from .sync import (
    decode_sync_message,
    decode_sync_state,
    encode_sync_state,
    generate_sync_message,
    init_sync_state,
    receive_sync_message,
)
from .sync_v2 import (
    MESSAGE_TYPE_SYNC_V2,
    decode_sync_message_v2,
    generate_sync_message_v2,
    index_for_backend,
    receive_sync_message_v2,
)
from .testing.faults import fire as _fault_point

FRAME_TYPE = 0x44
FLAG_PAYLOAD = 0x01
FLAG_V2 = 0x02  # sender speaks range-based reconciliation (sync_v2)

_CHECKSUM_SIZE = 4

#: sentinel returned by ``SyncSession.poll_begin`` when the channel is
#: clear and the caller should run the inner protocol's generate (then
#: ``poll_commit``) — distinguishable from both None and a frame
NEEDS_GENERATE = object()

_METRICS = get_metrics()
# flight-recorder hook (obs/flight.py): session events land in the ring
# for postmortems. Every call site guards with `_FLIGHT.enabled` so the
# disabled path never packs kwargs, and stamps `t` from the session's
# injected clock so simulated-time runs produce simulated timelines.
_FLIGHT = get_flight()
_M_RETRANSMITS = _METRICS.counter(
    "sync.session.retransmits", "payload frames retransmitted after a timeout"
)
_M_DUP_DROPPED = _METRICS.counter(
    "sync.session.dup_dropped",
    "duplicate/stale frames dropped idempotently (re-acked, not applied)",
)
_M_TIMEOUTS = _METRICS.counter(
    "sync.session.timeouts", "retransmission deadlines that expired unacked"
)
_M_BACKOFF_MS = _METRICS.histogram(
    "sync.session.backoff_ms",
    "full-jitter backoff delays (ms) applied before retransmissions",
)
_M_PEER_RESTARTS = _METRICS.counter(
    "sync.session.peer_restarts",
    "epoch changes observed from the peer (clean re-handshakes triggered)",
)
_M_FRAMES_REJECTED = _METRICS.counter(
    "sync.session.frames_rejected",
    "frames dropped as malformed/corrupt (SyncFrameError; state untouched)",
)
_M_SHED = _METRICS.counter(
    "sync.session.shed",
    "frames shed unprocessed because the channel is quarantined",
)
_M_ADVERTS_SUPPRESSED = _METRICS.counter(
    "sync.session.adverts_suppressed",
    "regenerated payloads withheld because the peer already acked the "
    "identical bytes (poll-driven callers would otherwise chatter forever)",
)
_M_WD_STALLS = _METRICS.counter(
    "sync.watchdog.stalls",
    "stalled-pair detections (no head progress while messages flowed)",
)
_M_WD_ESCALATIONS = _METRICS.counter(
    "sync.watchdog.escalations",
    "watchdog escalations (Bloom rebuild, then full reset exchange)",
)
_M_WD_RESETS = _METRICS.counter(
    "sync.watchdog.resets",
    "full reset exchanges forced after a Bloom rebuild failed to unstall",
)
_M_CHQ_ENTERED = _METRICS.counter(
    "sync.channel.quarantine.entered",
    "channels quarantined after the retransmission budget was exhausted",
)
_M_CHQ_RELEASED = _METRICS.counter(
    "sync.channel.quarantine.released", "channels returned to service"
)
_M_CHQ_ACTIVE = _METRICS.gauge(
    "sync.channel.quarantine.active", "channels currently quarantined"
)
_M_V2_NEGOTIATED = _METRICS.counter(
    "sync.v2.sessions.negotiated",
    "sessions upgraded to range-based reconciliation (both sides flagged v2)",
)
_M_V2_FALLBACKS = _METRICS.counter(
    "sync.v2.fallbacks",
    "mid-session downgrades to the Bloom protocol after a v2 exchange error",
)


def _set_active_quarantined():
    # derived from the enter/release counters rather than a module global,
    # so a registry reset() re-zeros the gauge consistently with them
    _M_CHQ_ACTIVE.set(max(0, _M_CHQ_ENTERED.value - _M_CHQ_RELEASED.value))


# ---------------------------------------------------------------------- #
# frame codec (outer framing only; payload is the reference wire format)

def encode_frame(epoch: int, seq: int, ack: int, payload: bytes | None,
                 extra_flags: int = 0) -> bytes:
    body = Encoder()
    body.append_uint32(epoch)
    body.append_uint53(seq)
    body.append_uint53(ack)
    if payload is None:
        body.append_byte(extra_flags)
    else:
        body.append_byte(FLAG_PAYLOAD | extra_flags)
        body.append_prefixed_bytes(payload)
    encoder = Encoder()
    encoder.append_byte(FRAME_TYPE)
    encoder.append_raw_bytes(sha256(body.buffer).digest()[:_CHECKSUM_SIZE])
    encoder.append_raw_bytes(body.buffer)
    return encoder.buffer


def decode_frame(data) -> dict:
    """Decodes one session frame; raises ``SyncFrameError`` on any
    malformed or corrupted input (short reads, checksum mismatch, bad
    type), never a raw decode exception, and touches no session state."""
    try:
        decoder = Decoder(data)
        frame_type = decoder.read_byte()
        if frame_type != FRAME_TYPE:
            raise SyncFrameError(f"unexpected frame type: {frame_type}")
        checksum = decoder.read_raw_bytes(_CHECKSUM_SIZE)
        body = decoder.read_raw_bytes(len(decoder.buf) - decoder.offset)
        if sha256(body).digest()[:_CHECKSUM_SIZE] != checksum:
            raise SyncFrameError("session frame checksum mismatch")
        body_decoder = Decoder(body)
        epoch = body_decoder.read_uint32()
        seq = body_decoder.read_uint53()
        ack = body_decoder.read_uint53()
        flags = body_decoder.read_byte()
        payload = (
            body_decoder.read_prefixed_bytes() if flags & FLAG_PAYLOAD else None
        )
    except SyncFrameError:
        raise
    except (ValueError, TypeError, IndexError) as exc:
        raise SyncFrameError(f"malformed session frame: {exc}") from exc
    return {
        "epoch": epoch, "seq": seq, "ack": ack, "flags": flags,
        "payload": payload,
    }


# ---------------------------------------------------------------------- #
# protocol drivers: what a session supervises

class BackendDriver:
    """Supervises a backend handle via the sequential protocol
    (automerge_tpu/sync.py). The handle is rebound on every receive, so the
    session owns the document's latest state."""

    def __init__(self, backend):
        self.backend = backend
        self._v2_index = None  # lazily built, incrementally refreshed

    def generate(self, state):
        return generate_sync_message(self.backend, state)

    def receive(self, state, payload):
        self.backend, state, patch = receive_sync_message(
            self.backend, state, payload
        )
        return state, patch

    def _index(self):
        self._v2_index = index_for_backend(self.backend, self._v2_index)
        return self._v2_index

    def generate_v2(self, state):
        return generate_sync_message_v2(self.backend, state, self._index())

    def receive_v2(self, state, payload):
        self.backend, state, patch = receive_sync_message_v2(
            self.backend, state, self._index(), payload
        )
        return state, patch

    def heads(self):
        return Backend.get_heads(self.backend)


class FarmDriver:
    """Supervises one document channel of a batched ``SyncFarm``
    (tpu/sync_farm.py). Malformed payloads raise out of ``receive`` (the
    session must withhold its ack so the peer retransmits), so the inner
    message is validated here before the farm's reject-in-place path."""

    def __init__(self, sync_farm, doc: int):
        self.sync_farm = sync_farm
        self.doc = doc

    def generate(self, state):
        ((state, msg),) = self.sync_farm.generate_messages([(self.doc, state)])
        return state, msg

    def receive(self, state, payload):
        decode_sync_message(payload)  # raises SyncProtocolError, state untouched
        ((state, patch),) = self.sync_farm.receive_messages(
            [(self.doc, state, payload)]
        )
        return state, patch

    def generate_v2(self, state):
        ((state, msg),) = self.sync_farm.generate_messages(
            [(self.doc, state)], protocols=["v2"]
        )
        return state, msg

    def receive_v2(self, state, payload):
        decode_sync_message_v2(payload)  # raises, farm state untouched
        ((state, patch),) = self.sync_farm.receive_messages(
            [(self.doc, state, payload)], protocols=["v2"]
        )
        return state, patch

    def heads(self):
        return self.sync_farm.farm.get_heads(self.doc)


# ---------------------------------------------------------------------- #

@dataclass
class SessionConfig:
    """Supervision knobs. Times are in the injected clock's units
    (seconds under the default monotonic clock)."""

    timeout: float = 1.0          # unacked-frame deadline before retransmit
    max_retries: int = 8          # retransmissions before channel quarantine
    backoff_base: float = 0.5     # first retry's backoff cap
    backoff_cap: float = 10.0     # backoff growth ceiling
    watchdog_rounds: int = 5      # K no-progress rounds before escalation
    enable_v2: bool = False       # advertise range-based reconciliation


def _default_clock():
    # the single wall-clock injection point for the sync data plane; every
    # other call site takes this (or a test clock) as a parameter
    return time.monotonic()  # amlint: disable=AM402 — the injectable-clock default


class SyncSession:
    """One supervised peer channel. Drive it with two calls:

    - ``poll()`` — the send half: returns the next frame to transmit (a
      fresh payload frame, a retransmission, or an owed ack), or None.
    - ``handle(frame)`` — the receive half: processes one incoming frame,
      returns the patch from the inner protocol (or None for acks,
      duplicates and shed frames). Corrupt frames raise ``SyncFrameError``
      (and inapplicable payloads ``SyncProtocolError``) with all session
      state untouched, so the peer's retransmission gets a clean retry.

    ``clock`` is a zero-argument callable; ``rng`` is a ``random.Random``
    instance. Both default to real time / OS entropy but are injectable so
    tests and the chaos harness are fully deterministic.
    """

    def __init__(self, driver, *, clock=None, rng=None, config=None,
                 state=None):
        self.driver = driver
        self.clock = clock if clock is not None else _default_clock
        self.rng = rng if rng is not None else random.Random()
        self.config = config or SessionConfig()
        self.state = state if state is not None else init_sync_state()
        self.epoch = self.rng.getrandbits(32) or 1  # 0 is reserved: "unknown"
        self.seq_out = 0          # last payload sequence number used
        self.last_seen = 0        # highest peer payload seq applied
        self.peer_epoch = None
        self.pending = None       # unacked outgoing payload frame, or None
        self.ack_owed = False
        self.quarantine_cause = None
        # the payload the peer last acknowledged, plus how many inbound
        # payloads had been applied when it was sent: a regenerated
        # payload byte-identical to it is suppressed (see poll_commit)
        # UNLESS the peer has sent us a payload since — without the
        # suppression, a poll-driven caller (the serving loop) chatters
        # forever once one side reaches the reference protocol's
        # reply-suppressed terminal state (receiveSyncMessage sets
        # lastSentHeads = msg.heads, so the peer's theirHeads stays stale
        # and generate keeps re-advertising); without the payload-since
        # escape, the suppression would silence the head-exchange chatter
        # the convergence watchdog counts stalled rounds on
        self._acked_payload = None
        self._acked_rx_mark = -1
        self._payloads_applied = 0
        # v2 negotiation: we advertise when the config opts in AND the
        # driver can actually run both halves of the v2 protocol; the peer
        # advertises via FLAG_V2 on its frames. v2_fallback latches a
        # mid-session downgrade (v2 exchange errored) — permanent for this
        # session incarnation, cleared only by a peer restart.
        self.v2_local = bool(
            self.config.enable_v2
            and hasattr(driver, "generate_v2")
            and hasattr(driver, "receive_v2")
        )
        self.peer_v2 = False
        self.v2_fallback = False
        self.stats = {
            "retransmits": 0, "dup_dropped": 0, "timeouts": 0,
            "backoff_ms": 0.0, "peer_restarts": 0, "shed": 0,
            "stalls": 0, "escalations": 0, "resets": 0, "suppressed": 0,
            "v2_negotiated": 0, "v2_fallbacks": 0,
        }
        self._wd_heads = None
        self._wd_shared = None
        self._wd_rounds = 0
        self._wd_stage = 0

    # -------------------------------------------------------------- #
    # protocol negotiation (sync v2)

    @property
    def v2_active(self) -> bool:
        """True when this session generates v2 messages: both sides have
        advertised the capability and no fallback has latched. Inbound
        dispatch is by payload type byte regardless, so flipping mid-flight
        is safe."""
        return self.v2_local and self.peer_v2 and not self.v2_fallback

    def _flags_out(self) -> int:
        return FLAG_V2 if (self.v2_local and not self.v2_fallback) else 0

    def _note_peer_flags(self, flags: int):
        """Tracks the peer's advertised capability from every frame. A
        frame WITHOUT the flag from a previously-v2 peer downgrades us too
        (the peer latched its own fallback); the symmetric drop is what
        terminates a one-sided fallback instead of leaving us feeding v2
        frames to a peer that now rejects them."""
        peer_v2 = bool(flags & FLAG_V2)
        if peer_v2 == self.peer_v2:
            return
        was_active = self.v2_active
        self.peer_v2 = peer_v2
        if not was_active and self.v2_active:
            _M_V2_NEGOTIATED.inc()
            self.stats["v2_negotiated"] += 1
            if _FLIGHT.enabled:
                _FLIGHT.record("v2.negotiated", t=self.clock(),
                               epoch=self.epoch, peer_epoch=self.peer_epoch)

    def _v2_fall_back(self, where: str, cause):
        """Latches the mid-session downgrade to v1: counted, flight-evented
        (record + trigger — a fallback is a postmortem-worthy anomaly), v2
        descent state dropped so the Bloom machinery starts clean."""
        if self.v2_fallback:
            return
        self.v2_fallback = True
        _M_V2_FALLBACKS.inc()
        self.stats["v2_fallbacks"] += 1
        self._acked_payload = None  # the v1 restart must regenerate freely
        if _FLIGHT.enabled:
            _FLIGHT.record("v2.fallback", t=self.clock(), epoch=self.epoch,
                           where=where, cause=str(cause))
            _FLIGHT.trigger("v2.fallback", t=self.clock(), epoch=self.epoch)
        self.state = {
            k: v for k, v in self.state.items() if not k.startswith("v2")
        }

    # -------------------------------------------------------------- #
    # send half

    def poll(self):
        """Returns the next frame to transmit, or None when idle. Call it
        whenever the transport can send: it retransmits on expired
        deadlines, generates the next protocol message when the channel is
        clear, and emits owed acks."""
        ready = self.poll_begin()
        if ready is not NEEDS_GENERATE:
            return ready
        state, payload = self._generate_dispatch(self.state)
        return self.poll_commit(state, payload)

    def _generate_dispatch(self, state):
        """Runs the negotiated protocol's generate; a v2 generate error
        falls back to v1 (counted + flight-evented) rather than killing
        the channel."""
        if self.v2_active:
            try:
                return self.driver.generate_v2(state)
            except SyncProtocolError as exc:
                self._v2_fall_back("generate", exc)
                state = self.state  # _v2_fall_back stripped the v2 keys
        return self.driver.generate(state)

    def poll_begin(self):
        """The pre-generate half of ``poll``: quarantine shed, owed acks
        while a frame is in flight, and the retransmission/timeout path.
        Returns a frame (or None) when the channel needs no generation,
        or the ``NEEDS_GENERATE`` sentinel when the caller should run the
        inner protocol's generate and finish with ``poll_commit``. The
        serving multiplexer uses this split to batch MANY sessions'
        generates into one device program (``SyncFarm.generate_messages``)
        instead of one dispatch per channel."""
        if self.quarantine_cause is not None:
            return None
        now = self.clock()
        if self.pending is not None:
            if now < self.pending["deadline"]:
                return self._ack_frame() if self.ack_owed else None
            _M_TIMEOUTS.inc()
            self.stats["timeouts"] += 1
            if self.pending["attempt"] >= self.config.max_retries:
                self._enter_quarantine(RetryExhaustedError(
                    f"no ack for frame seq={self.pending['seq']} after "
                    f"{self.pending['attempt']} retransmissions; channel "
                    "quarantined (release() to retry)"
                ))
                return None
            self.pending["attempt"] += 1
            backoff = self._backoff(self.pending["attempt"])
            self.pending["deadline"] = now + self.config.timeout + backoff
            _M_RETRANSMITS.inc()
            self.stats["retransmits"] += 1
            if _FLIGHT.enabled:
                _FLIGHT.record(
                    "session.retransmit", t=now, seq=self.pending["seq"],
                    attempt=self.pending["attempt"],
                    backoff_ms=round(backoff * 1000.0, 3),
                )
            self.ack_owed = False
            # re-frame so the retransmission carries the current ack
            return encode_frame(
                self.epoch, self.pending["seq"], self.last_seen,
                self.pending["payload"], self._flags_out(),
            )
        return NEEDS_GENERATE

    def poll_commit(self, state, payload):
        """The post-generate half of ``poll``: adopts the new sync state
        and frames the payload (fresh seq, retransmission deadline), or
        emits the owed ack when there is nothing (left) to say."""
        self.state = state
        if payload is None:
            return self._ack_frame() if self.ack_owed else None
        if (
            payload == self._acked_payload
            and self._payloads_applied == self._acked_rx_mark
        ):
            # byte-identical to a payload the peer already acknowledged,
            # and the peer has said nothing since: retransmitting carries
            # zero new information and only restarts the ack->regenerate
            # chatter loop. Stay quiet; any real event (our heads move, a
            # peer payload arrives — which re-arms this check so the
            # watchdog's stalled-round chatter still flows — a restart or
            # a watchdog reset) changes the bytes or the mark.
            self.stats["suppressed"] += 1
            _M_ADVERTS_SUPPRESSED.inc()
            return self._ack_frame() if self.ack_owed else None
        self.seq_out += 1
        self.pending = {
            "seq": self.seq_out,
            "payload": payload,
            "attempt": 0,
            "deadline": self.clock() + self.config.timeout,
            "rx_mark": self._payloads_applied,
        }
        self.ack_owed = False
        return encode_frame(self.epoch, self.seq_out, self.last_seen, payload,
                            self._flags_out())

    def _ack_frame(self) -> bytes:
        self.ack_owed = False
        return encode_frame(self.epoch, 0, self.last_seen, None,
                            self._flags_out())

    def _backoff(self, attempt: int) -> float:
        """Full jitter: uniform in [0, min(cap, base * 2^(attempt-1)))."""
        ceiling = min(
            self.config.backoff_cap,
            self.config.backoff_base * (2 ** (attempt - 1)),
        )
        delay = self.rng.uniform(0.0, ceiling)
        _M_BACKOFF_MS.observe(delay * 1000.0)
        self.stats["backoff_ms"] += delay * 1000.0
        return delay

    # -------------------------------------------------------------- #
    # receive half

    def handle(self, frame_bytes):
        """Processes one incoming frame; returns the inner protocol's patch
        (None for acks/duplicates/shed frames)."""
        pre = self.begin(frame_bytes)
        if pre is None:
            return None
        # apply BEFORE advancing the seq watermark: a payload the inner
        # protocol rejects (corrupt/inapplicable) must not be acked, so the
        # peer's intact retransmission gets a clean retry
        state, patch = self._receive_dispatch(self.state, pre["payload"])
        return self.commit(pre, state, patch)

    def _receive_dispatch(self, state, payload):
        """Routes an inbound payload by its leading type byte. A v2
        payload that errors latches the fallback and is ACKED with state
        unchanged: withholding the ack would make the peer retransmit the
        same poisoned frame until the retry budget quarantined the channel.
        v1 payload errors keep the withhold-ack semantics — their
        retransmission path is how transient corruption heals."""
        if self.v2_local and payload and payload[0] == MESSAGE_TYPE_SYNC_V2:
            try:
                return self.driver.receive_v2(state, payload)
            except SyncProtocolError as exc:
                self._v2_fall_back("receive", exc)
                return self.state, None
        return self.driver.receive(state, payload)

    def begin(self, frame_bytes):
        """The envelope half of ``handle``: decodes and validates the
        frame, processes its ack/epoch side effects, and drops duplicates
        — everything except applying the payload through the driver.
        Returns None when there is nothing to apply (ack-only, duplicate,
        shed), else ``{"seq", "payload"}`` to hand to the inner protocol
        and then to ``commit``. The serving batcher (serve/batcher.py)
        uses this split to stage many sessions' payloads into ONE batched
        farm dispatch; ``handle`` composes the same two halves around an
        immediate ``driver.receive``."""
        if self.quarantine_cause is not None:
            _M_SHED.inc()
            self.stats["shed"] += 1
            return None
        _fault_point("session.receive", frame=frame_bytes)
        try:
            frame = decode_frame(frame_bytes)
        except SyncFrameError:
            _M_FRAMES_REJECTED.inc()
            raise
        if frame["epoch"] != self.peer_epoch:
            if self.peer_epoch is not None:
                self._on_peer_restart()
            self.peer_epoch = frame["epoch"]
        self._note_peer_flags(frame["flags"])
        if self.pending is not None and frame["ack"] >= self.pending["seq"]:
            self._acked_payload = self.pending["payload"]
            self._acked_rx_mark = self.pending["rx_mark"]
            self.pending = None
        payload = frame["payload"]
        if payload is None:
            return None
        if frame["seq"] <= self.last_seen:
            _M_DUP_DROPPED.inc()
            self.stats["dup_dropped"] += 1
            self.ack_owed = True  # re-ack so the peer stops retransmitting
            return None
        return {"seq": frame["seq"], "payload": payload}

    def commit(self, pre, state, patch):
        """The post-apply half of ``handle``: adopts the inner protocol's
        new state, advances the seq watermark (the payload is now safe to
        ack), and runs a watchdog round. Must only be called with the
        result of a successful ``driver.receive`` of ``begin``'s payload —
        a rejected payload is NOT committed, so it is never acked and the
        peer's retransmission retries cleanly."""
        self.state = state
        self.last_seen = pre["seq"]
        self.ack_owed = True
        self._payloads_applied += 1
        self._watchdog_round()
        return patch

    def _on_peer_restart(self):
        """The peer came back with a new epoch: reset the envelope-level
        seq tracking and drop everything we believed about the peer, so
        the next exchange is a clean re-handshake (the inner protocol's
        reset paths then re-establish sharedHeads) instead of a permanent
        dup-drop/heads mismatch."""
        _M_PEER_RESTARTS.inc()
        self.stats["peer_restarts"] += 1
        if _FLIGHT.enabled:
            _FLIGHT.record("session.peer_restart", t=self.clock(),
                           epoch=self.epoch, peer_epoch=self.peer_epoch)
        self.last_seen = 0
        self.pending = None  # addressed to the old incarnation; regenerate
        self._acked_payload = None  # the new incarnation acked nothing
        self.state = {
            k: v for k, v in dict(
                self.state,
                theirHeads=None, theirHave=None, theirNeed=None,
                lastSentHeads=[], sentHashes={},
            ).items()
            if not k.startswith("v2")  # in-flight descents die with the peer
        }
        # the new incarnation re-negotiates from scratch (it may have come
        # back without v2, or healthy enough to retry after our fallback)
        self.peer_v2 = False
        self.v2_fallback = False
        self._wd_rounds = 0
        self._wd_stage = 0

    # -------------------------------------------------------------- #
    # convergence watchdog

    def _watchdog_round(self):
        """Called after every applied payload (so "messages still flow" by
        construction): escalates when heads and sharedHeads are both stuck
        for K rounds short of convergence."""
        heads = self.driver.heads()
        shared = self.state["sharedHeads"]
        their = self.state["theirHeads"]
        converged = their is not None and heads == their
        progressed = heads != self._wd_heads or shared != self._wd_shared
        self._wd_heads = heads
        self._wd_shared = shared
        if converged or progressed:
            self._wd_rounds = 0
            self._wd_stage = 0
            return
        self._wd_rounds += 1
        if self._wd_rounds < self.config.watchdog_rounds:
            return
        self._wd_rounds = 0
        _M_WD_STALLS.inc()
        self.stats["stalls"] += 1
        _M_WD_ESCALATIONS.inc()
        self.stats["escalations"] += 1
        if _FLIGHT.enabled:
            _FLIGHT.record("watchdog.stall", t=self.clock(),
                           epoch=self.epoch, stage=self._wd_stage)
        self._acked_payload = None  # escalations must retransmit freely
        if self.v2_active:
            # v2 has no Bloom state to rebuild and no probabilistic
            # failure mode to escalate past — in practice this branch
            # should be unreachable (that's the point of v2). If it ever
            # fires, drop the in-flight descent so the next generate
            # re-probes the full range from current heads.
            if _FLIGHT.enabled:
                _FLIGHT.record("watchdog.escalate", t=self.clock(),
                               epoch=self.epoch, action="v2_reprobe")
            self.state = {
                k: v for k, v in dict(
                    self.state, lastSentHeads=[], sentHashes={},
                ).items()
                if not k.startswith("v2")
            }
            return
        if self._wd_stage == 0:
            # stage 1 — rebuild the Bloom exchange: clearing sentHashes and
            # lastSentHeads makes the next generate resend its filter and
            # re-offer anything wrongly withheld (e.g. a change a stale
            # sentHashes entry or a Bloom false-positive loop suppressed)
            self._wd_stage = 1
            if _FLIGHT.enabled:
                _FLIGHT.record("watchdog.escalate", t=self.clock(),
                               epoch=self.epoch, action="bloom_rebuild")
            self.state = dict(self.state, lastSentHeads=[], sentHashes={})
        else:
            # stage 2 — full reset exchange: treat the peer's filter as
            # empty (every change Bloom-negative, so all of ours are
            # offered explicitly) and rebuild ours from scratch
            self._wd_stage = 0
            _M_WD_RESETS.inc()
            self.stats["resets"] += 1
            if _FLIGHT.enabled:
                _FLIGHT.record("watchdog.reset", t=self.clock(),
                               epoch=self.epoch)
                _FLIGHT.trigger("watchdog.reset", t=self.clock(),
                                epoch=self.epoch)
            self.state = dict(
                self.state,
                sharedHeads=[], lastSentHeads=[], sentHashes={},
                theirHave=[{"lastSync": [], "bloom": b""}],
                theirNeed=self.state["theirNeed"] or [],
            )

    # -------------------------------------------------------------- #
    # channel quarantine (mirrors the doc farm's lifecycle, PR 3)

    @property
    def quarantined(self) -> bool:
        return self.quarantine_cause is not None

    def _enter_quarantine(self, cause: SyncProtocolError):
        self.quarantine_cause = cause
        self.pending = None
        _M_CHQ_ENTERED.inc()
        _set_active_quarantined()
        if _FLIGHT.enabled:
            _FLIGHT.record("session.quarantine.enter", t=self.clock(),
                           epoch=self.epoch, cause=str(cause))
            _FLIGHT.trigger("session.quarantine", t=self.clock(),
                            epoch=self.epoch)

    def release(self):
        """Returns a quarantined channel to service with a fresh retry
        budget; the next ``poll()`` regenerates from current state. On a
        live channel it just resets the in-flight retry budget — call it
        after a known network heal so a frame that burned most of its
        budget against the partition is not quarantined by its next
        timeout."""
        if self.quarantine_cause is None:
            if self.pending is not None:
                self.pending["attempt"] = 0
            return
        self.quarantine_cause = None
        self._acked_payload = None  # post-heal recovery regenerates freely
        _M_CHQ_RELEASED.inc()
        _set_active_quarantined()
        if _FLIGHT.enabled:
            _FLIGHT.record("session.quarantine.release", t=self.clock(),
                           epoch=self.epoch)

    def check(self):
        """Raises ``ChannelQuarantinedError`` if the channel is shed (the
        explicit-error analogue of the silent shed in ``handle``)."""
        if self.quarantine_cause is not None:
            raise ChannelQuarantinedError(
                f"sync channel is quarantined ({self.quarantine_cause}); "
                "release() to retry"
            )

    # -------------------------------------------------------------- #
    # persistence (resumable sessions)

    def save(self) -> bytes:
        """Durable snapshot: the inner state's sharedHeads plus the session
        extension (epoch and seq/ack watermarks, and the watchdog's
        escalation ladder — without it a restart silently re-armed a
        stalled channel's stall counters from zero). In-flight frames are
        deliberately not persisted — after restore the peer's
        retransmissions and our regenerated frames re-fill the channel."""
        return encode_sync_state(self.state, session={
            "epoch": self.epoch,
            "seqOut": self.seq_out,
            "lastSeen": self.last_seen,
            "peerEpoch": self.peer_epoch,
            "wdRounds": self._wd_rounds,
            "wdStage": self._wd_stage,
            "wdStalls": self.stats["stalls"],
            "wdEscalations": self.stats["escalations"],
            "wdResets": self.stats["resets"],
        })

    @classmethod
    def restore(cls, blob, driver, *, clock=None, rng=None, config=None):
        """Resumes a channel from ``save()`` output. Pre-extension blobs
        (plain ``encode_sync_state``) restore too — the session then starts
        with a fresh epoch, which the peer handles as a restart. Blobs
        written before the watchdog tail existed restore with the
        escalation ladder at rest."""
        state = decode_sync_state(blob)
        session = state.pop("session", None)
        restored = cls(driver, clock=clock, rng=rng, config=config, state=state)
        if session is not None:
            restored.epoch = session["epoch"]
            restored.seq_out = session["seqOut"]
            restored.last_seen = session["lastSeen"]
            restored.peer_epoch = session["peerEpoch"]
            restored._wd_rounds = session.get("wdRounds", 0)
            restored._wd_stage = session.get("wdStage", 0)
            restored.stats["stalls"] = session.get("wdStalls", 0)
            restored.stats["escalations"] = session.get("wdEscalations", 0)
            restored.stats["resets"] = session.get("wdResets", 0)
        return restored
