"""Sync v2: range-based set reconciliation over the change-hash DAG.

The v1 protocol (automerge_tpu/sync.py) ships O(n) Bloom filters per round
and can stall on false positives — the PR 5 watchdog's rebuild/reset ladder
exists purely to break those stalls. This module implements the
deterministic alternative (range-based set reconciliation, in the style of
https://arxiv.org/abs/2212.13567): the two peers' change-hash sets are
compared range-by-range using XOR-of-hash fingerprints, mismatching ranges
split at item-count medians, and ranges below a small threshold exchange
explicit item lists. Convergence takes O(log n) round trips with **no
probabilistic failure mode** — a fingerprint mismatch is always real, an
item list is always authoritative, and nothing is ever wrongly withheld
(v2 deliberately does not consult v1's ``sentHashes``).

Layering mirrors v1:

- the wire codec (``encode_sync_message_v2``/``decode_sync_message_v2``)
  rejects malformed frames strictly into the error taxonomy
  (``SyncProtocolError``; local state untouched);
- the driver is split into a host planning phase
  (``plan_generate_v2`` — which fingerprint queries does this round
  need?), a fingerprint resolution step the caller owns (the batched farm
  resolves EVERY live channel's queries as one device reduction, see
  tpu/fingerprint.py), and a finish phase (``finish_generate_v2``);
- ``generate_sync_message_v2``/``receive_sync_message_v2`` wrap the
  phases for a single backend, the drop-in v2 twins of the v1 entry
  points.

Negotiation lives one layer up (sync_session.py): v2 only runs inside a
session whose peer advertised the capability flag, and the session falls
back to v1 mid-stream if a v2 exchange errors.

Wire format (inner payload; the session envelope is unchanged)::

    byte   MESSAGE_TYPE_SYNC_V2 (0x45)
    heads  sorted hash list          (same layout as v1)
    need   sorted hash list
    uint32 range count; per range:
        32B lo | 32B hi              (half-open [lo, hi); sorted,
                                      non-overlapping, lo < hi)
        byte mode
        mode 0 (fingerprint): uint53 count | 32B xor-of-hashes
        mode 1 (item list):   uint32 n | n x 32B (strictly ascending,
                                                  every item in [lo, hi))
    uint32 change count; per change: prefixed change bytes

Trailing bytes are ignored for forward compatibility (as in v1).
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from bisect import bisect_left, insort

from . import backend as Backend
from .codecs import Decoder, Encoder, bytes_to_hex, hex_to_bytes
from .columnar import decode_change_meta_cached
from .errors import AutomergeError, EncodeError, SyncProtocolError
from .obs.metrics import get_metrics
from .sync import HASH_SIZE, _advance_heads, _decode_hashes, _encode_hashes
from .testing.faults import fire as _fault_point

MESSAGE_TYPE_SYNC_V2 = 0x45
RANGE_FINGERPRINT = 0
RANGE_ITEMS = 1

#: ranges at or below this many local items answer a fingerprint mismatch
#: with an explicit item list instead of splitting further
ITEM_THRESHOLD = 16
#: mismatching ranges split into this many subranges at item-count medians
SPLIT_FANOUT = 4

#: the full hash space, half-open: [MIN_HASH, MAX_HASH)
MIN_HASH = "0" * 64
MAX_HASH = "f" * 64

# v2 wire/driver metrics. Change and byte volume record into the SAME
# named instruments as v1 (sync.changes.*, sync.bytes.*) so protocol
# totals accumulate in one place; the sync.v2.* family is the
# reconciliation-specific accounting.
_METRICS = get_metrics()
_M2_MSGS_GEN = _METRICS.counter(
    "sync.v2.messages.generated", "v2 reconciliation messages encoded"
)
_M2_MSGS_RECV = _METRICS.counter(
    "sync.v2.messages.received", "v2 reconciliation messages decoded"
)
_M2_REJECTED = _METRICS.counter(
    "sync.v2.messages.rejected",
    "received v2 messages rejected as malformed or inapplicable "
    "(SyncProtocolError; local state untouched)",
)
_M2_RANGES_SENT = _METRICS.counter(
    "sync.v2.ranges.sent", "ranges encoded into outgoing v2 messages"
)
_M2_RECONCILED = _METRICS.counter(
    "sync.v2.ranges.reconciled",
    "received ranges whose fingerprint matched ours (subtree fully in sync)",
)
_M2_SPLIT = _METRICS.counter(
    "sync.v2.ranges.split",
    "fingerprint mismatches answered by splitting at item-count medians",
)
_M2_ITEMS = _METRICS.counter(
    "sync.v2.items.sent", "item-list entries sent for sub-threshold ranges"
)
_M_BYTES_SENT = _METRICS.counter("sync.bytes.sent")
_M_BYTES_RECV = _METRICS.counter("sync.bytes.received")
_M_CHANGES_SENT = _METRICS.counter("sync.changes.sent")
_M_CHANGES_RECV = _METRICS.counter("sync.changes.received")


# ---------------------------------------------------------------------- #
# fingerprint index (host). The device twin — one pow2-bucketed XOR
# reduction for every live channel's ranges — is tpu/fingerprint.py.

class HashIndex:
    """Sorted change-hash set with O(1)-per-query range fingerprints.

    Hashes are 64-char lowercase hex (the reference protocol's hash
    strings); a range fingerprint over [lo, hi) is the XOR of every member
    hash, served from a lazily rebuilt prefix-XOR array. Inserts are
    incremental (``insert_many`` on every applied change); the prefix
    array rebuilds once per query burst, not per insert.
    """

    __slots__ = ("_hashes", "_members", "_prefix", "_dirty")

    def __init__(self, hashes=()):
        self._hashes: list[str] = []
        self._members: set[str] = set()
        self._prefix: list[int] = [0]
        self._dirty = False
        self.insert_many(hashes)

    def __len__(self) -> int:
        return len(self._hashes)

    def contains(self, h: str) -> bool:
        return h in self._members

    def insert(self, h: str) -> bool:
        if h in self._members:
            return False
        if len(h) != 2 * HASH_SIZE:
            raise SyncProtocolError(f"not a 256-bit hash: {h!r}")
        try:
            int(h, 16)
        except (ValueError, TypeError) as exc:
            raise SyncProtocolError(f"not a hex hash: {h!r}") from exc
        self._members.add(h)
        insort(self._hashes, h)
        self._dirty = True
        return True

    def insert_many(self, hashes) -> None:
        for h in hashes:
            self.insert(h)

    def _span(self, lo: str, hi: str) -> tuple[int, int]:
        return bisect_left(self._hashes, lo), bisect_left(self._hashes, hi)

    def count(self, lo: str, hi: str) -> int:
        i, j = self._span(lo, hi)
        return j - i

    def items(self, lo: str, hi: str) -> list[str]:
        i, j = self._span(lo, hi)
        return self._hashes[i:j]

    def fingerprint_many(self, queries) -> list[tuple[int, str]]:
        """[(lo, hi)] -> [(count, xor_hex)] in query order."""
        if self._dirty:
            acc = 0
            prefix = [0]
            for h in self._hashes:
                acc ^= int(h, 16)
                prefix.append(acc)
            self._prefix = prefix
            self._dirty = False
        out = []
        for lo, hi in queries:
            i, j = self._span(lo, hi)
            out.append((j - i, format(self._prefix[j] ^ self._prefix[i], "064x")))
        return out


def index_for_backend(backend, index: HashIndex | None = None) -> HashIndex:
    """Builds (or refreshes) a ``HashIndex`` over every change hash the
    backend holds. Refresh is a no-op when the counts already agree —
    change sets only grow, so a count match means the index is current."""
    index = index if index is not None else HashIndex()
    hashes = [
        decode_change_meta_cached(c)["hash"]
        for c in Backend.get_changes(backend, [])
    ]
    if len(hashes) != len(index):
        index.insert_many(hashes)
    return index


# ---------------------------------------------------------------------- #
# wire codec

def encode_sync_message_v2(message) -> bytes:
    encoder = Encoder()
    encoder.append_byte(MESSAGE_TYPE_SYNC_V2)
    _encode_hashes(encoder, message["heads"])
    _encode_hashes(encoder, message["need"])
    ranges = message["ranges"]
    encoder.append_uint32(len(ranges))
    prev_hi = None
    for r in ranges:
        lo, hi = r["lo"], r["hi"]
        lo_bytes, hi_bytes = hex_to_bytes(lo), hex_to_bytes(hi)
        if len(lo_bytes) != HASH_SIZE or len(hi_bytes) != HASH_SIZE:
            raise EncodeError("range bounds must be 256-bit hashes")
        if lo >= hi:
            raise EncodeError("range bounds must satisfy lo < hi")
        if prev_hi is not None and lo < prev_hi:
            raise EncodeError("ranges must be sorted and non-overlapping")
        prev_hi = hi
        encoder.append_raw_bytes(lo_bytes)
        encoder.append_raw_bytes(hi_bytes)
        mode = r["mode"]
        encoder.append_byte(mode)
        if mode == RANGE_FINGERPRINT:
            encoder.append_uint53(r["count"])
            fp = hex_to_bytes(r["fp"])
            if len(fp) != HASH_SIZE:
                raise EncodeError("range fingerprint must be 256 bits")
            encoder.append_raw_bytes(fp)
        elif mode == RANGE_ITEMS:
            items = r["items"]
            encoder.append_uint32(len(items))
            prev = None
            for h in items:
                data = hex_to_bytes(h)
                if len(data) != HASH_SIZE:
                    raise EncodeError("item hashes must be 256 bits")
                if not (lo <= h < hi):
                    raise EncodeError("item hash outside its range")
                if prev is not None and h <= prev:
                    raise EncodeError("item hashes must be strictly ascending")
                prev = h
                encoder.append_raw_bytes(data)
        else:
            raise EncodeError(f"unknown range mode: {mode}")
    encoder.append_uint32(len(message["changes"]))
    for change in message["changes"]:
        encoder.append_prefixed_bytes(change)
    return encoder.buffer


def decode_sync_message_v2(data):
    """Decodes one v2 message with strict validation: unsorted or
    overlapping ranges, inverted bounds, out-of-range or duplicate item
    hashes, unknown modes, and truncated or garbage bytes all raise
    ``SyncProtocolError`` (never a raw decode exception) without
    constructing partial state."""
    try:
        decoder = Decoder(data)
        message_type = decoder.read_byte()
        if message_type != MESSAGE_TYPE_SYNC_V2:
            raise SyncProtocolError(
                f"Unexpected v2 message type: {message_type}"
            )
        heads = _decode_hashes(decoder)
        need = _decode_hashes(decoder)
        range_count = decoder.read_uint32()
        ranges = []
        prev_hi = None
        for _ in range(range_count):
            lo = bytes_to_hex(decoder.read_raw_bytes(HASH_SIZE))
            hi = bytes_to_hex(decoder.read_raw_bytes(HASH_SIZE))
            if lo >= hi:
                raise SyncProtocolError(
                    f"inverted range bounds: {lo[:8]}.. >= {hi[:8]}.."
                )
            if prev_hi is not None and lo < prev_hi:
                raise SyncProtocolError(
                    f"overlapping ranges: {lo[:8]}.. < {prev_hi[:8]}.."
                )
            prev_hi = hi
            mode = decoder.read_byte()
            if mode == RANGE_FINGERPRINT:
                count = decoder.read_uint53()
                fp = bytes_to_hex(decoder.read_raw_bytes(HASH_SIZE))
                ranges.append(
                    {"lo": lo, "hi": hi, "mode": mode, "count": count, "fp": fp}
                )
            elif mode == RANGE_ITEMS:
                n = decoder.read_uint32()
                items = []
                prev = None
                for _ in range(n):
                    h = bytes_to_hex(decoder.read_raw_bytes(HASH_SIZE))
                    if not (lo <= h < hi):
                        raise SyncProtocolError(
                            f"item hash {h[:8]}.. outside its range"
                        )
                    if prev is not None and h <= prev:
                        raise SyncProtocolError(
                            "item hashes must be strictly ascending "
                            f"(duplicate or unsorted at {h[:8]}..)"
                        )
                    prev = h
                    items.append(h)
                ranges.append({"lo": lo, "hi": hi, "mode": mode, "items": items})
            else:
                raise SyncProtocolError(f"unknown range mode: {mode}")
        change_count = decoder.read_uint32()
        changes = [decoder.read_prefixed_bytes() for _ in range(change_count)]
    except SyncProtocolError:
        raise
    except (ValueError, TypeError, IndexError) as exc:
        raise SyncProtocolError(f"malformed v2 sync message: {exc}") from exc
    # Trailing bytes are ignored for forward compatibility (as in v1)
    return {"heads": heads, "need": need, "ranges": ranges, "changes": changes}


# ---------------------------------------------------------------------- #
# driver: plan / resolve-fingerprints / finish

def _split_ranges(items, lo, hi, fanout=SPLIT_FANOUT):
    """Subranges of [lo, hi) cut at the local items' count medians:
    [(lo_k, hi_k, count_k)] covering [lo, hi) exactly."""
    n = len(items)
    cuts = []
    for k in range(1, fanout):
        b = items[(n * k) // fanout]
        if b <= lo or b >= hi:
            continue
        if cuts and b <= cuts[-1]:
            continue
        cuts.append(b)
    bounds = [lo] + cuts + [hi]
    out = []
    for a, b in zip(bounds, bounds[1:]):
        i, j = bisect_left(items, a), bisect_left(items, b)
        out.append((a, b, j - i))
    return out


def plan_generate_v2(state, view, our_heads):
    """Host phase 1 of a v2 generate: consumes the inbound fingerprint
    ranges (stashed by the last receive) and decides whether to open a
    fresh full-range probe. Returns ``(plan, queries)`` where ``queries``
    is the ordered [(lo, hi)] fingerprint list the caller must resolve —
    via ``HashIndex.fingerprint_many`` for one document, or ONE pow2-
    bucketed batched device reduction for every live channel at once
    (tpu/fingerprint.FingerprintIndex.fingerprint_ranges) — before
    ``finish_generate_v2``. ``view`` answers host-side set questions
    (count/items) for the local hash set."""
    queries = []
    entries = []
    for r in state.get("v2Inbound") or []:
        lo, hi = r["lo"], r["hi"]
        count = view.count(lo, hi)
        entry = {"range": r, "q": len(queries)}
        queries.append((lo, hi))
        if count > ITEM_THRESHOLD:
            items = view.items(lo, hi)
            subs = []
            for a, b, _c in _split_ranges(items, lo, hi):
                subs.append({"lo": a, "hi": b, "q": len(queries)})
                queries.append((a, b))
            entry["subs"] = subs
        else:
            entry["items"] = view.items(lo, hi)
        entries.append(entry)
    their_heads = state.get("theirHeads")
    probe_key = [list(our_heads), list(their_heads or [])]
    probe = None
    if (
        not entries
        and not (state.get("v2Outbound") or [])
        and (their_heads is None or list(their_heads) != list(our_heads))
        and state.get("v2Probe") != probe_key
    ):
        # nothing in flight and the heads disagree: open (or re-open) the
        # descent with a full-range fingerprint. The probe key pins one
        # probe per observed heads pair, so an in-progress descent is
        # never duplicated while the ball is in the peer's court.
        probe = {"q": len(queries)}
        queries.append((MIN_HASH, MAX_HASH))
    return {"entries": entries, "probe": probe, "probe_key": probe_key}, queries


def finish_generate_v2(state, plan, fps, get_change, our_heads, our_need):
    """Host phase 2: assembles the outgoing message from the resolved
    fingerprints. Returns ``(new_state, message_bytes | None)`` — None
    exactly when the channel is converged and silent (v1's quiescence
    conditions, so the session layer's advert suppression composes)."""
    ranges = list(state.get("v2Outbound") or [])
    for entry in plan["entries"]:
        r = entry["range"]
        count, fp = fps[entry["q"]]
        if count == r["count"] and fp == r["fp"]:
            _M2_RECONCILED.inc()
            continue
        if "items" in entry:
            ranges.append({
                "lo": r["lo"], "hi": r["hi"],
                "mode": RANGE_ITEMS, "items": entry["items"],
            })
        else:
            _M2_SPLIT.inc()
            for sub in entry["subs"]:
                sc, sf = fps[sub["q"]]
                ranges.append({
                    "lo": sub["lo"], "hi": sub["hi"],
                    "mode": RANGE_FINGERPRINT, "count": sc, "fp": sf,
                })
    probed = False
    if plan["probe"] is not None:
        pc, pf = fps[plan["probe"]["q"]]
        ranges.append({
            "lo": MIN_HASH, "hi": MAX_HASH,
            "mode": RANGE_FINGERPRINT, "count": pc, "fp": pf,
        })
        probed = True
    # enforce the wire invariant (sorted, non-overlapping): responses to
    # disjoint peer ranges are disjoint by construction, but a carried-over
    # outbound range can collide with a fresh probe; the dropped range's
    # information is re-derived by the next descent round
    ranges.sort(key=lambda r: (r["lo"], r["hi"]))
    kept = []
    prev_hi = None
    for r in ranges:
        if prev_hi is not None and r["lo"] < prev_hi:
            continue
        kept.append(r)
        prev_hi = r["hi"]
    ranges = kept

    need = sorted(set(our_need or ()) | set(state.get("v2Need") or ()))
    send_queue = state.get("v2SendQueue") or {}
    their_need = state.get("theirNeed") or []
    changes = []
    seen = set()
    for h in list(their_need) + sorted(send_queue):
        if h in seen:
            continue
        seen.add(h)
        change = get_change(h)
        if change is not None:
            changes.append(change)

    heads_unchanged = (
        isinstance(state.get("lastSentHeads"), list)
        and list(our_heads) == list(state["lastSentHeads"])
    )
    their_heads = state.get("theirHeads")
    heads_equal = (
        isinstance(their_heads, list) and list(our_heads) == list(their_heads)
    )
    if heads_unchanged and heads_equal and not ranges and not changes and not need:
        return state, None

    message = {
        "heads": list(our_heads), "need": need,
        "ranges": ranges, "changes": changes,
    }
    encoded = encode_sync_message_v2(message)
    new_state = dict(
        state,
        lastSentHeads=list(our_heads),
        v2Inbound=[], v2Outbound=[], v2SendQueue={}, v2Need=[],
    )
    if probed:
        new_state["v2Probe"] = plan["probe_key"]
    _M2_MSGS_GEN.inc()
    _M_BYTES_SENT.inc(len(encoded))
    _M_CHANGES_SENT.inc(len(changes))
    if _METRICS.enabled:
        _M2_RANGES_SENT.inc(len(ranges))
        _M2_ITEMS.inc(sum(
            len(r["items"]) for r in ranges if r["mode"] == RANGE_ITEMS
        ))
    return new_state, encoded


def post_receive_v2(state, message, before_heads, after_heads, has_change, view):
    """Shared post-apply bookkeeping for a validated, applied v2 message:
    advances sharedHeads exactly like v1's receive, stashes received
    fingerprint ranges for the next generate's batched resolution, and
    diffs item-list ranges against the local set (ours-not-theirs queue as
    sends; theirs-not-ours become explicit needs). Pure state-in/state-out
    so the sequential and batched-farm receive paths share it."""
    shared_heads = state["sharedHeads"]
    last_sent_heads = state["lastSentHeads"]
    if message["changes"]:
        shared_heads = _advance_heads(before_heads, after_heads, shared_heads)
    if not message["changes"] and message["heads"] == before_heads:
        last_sent_heads = message["heads"]
    known = [h for h in message["heads"] if has_change(h)]
    if len(known) == len(message["heads"]):
        shared_heads = message["heads"]
    else:
        shared_heads = sorted(set(known + shared_heads))

    inbound = list(state.get("v2Inbound") or [])
    send_queue = dict(state.get("v2SendQueue") or {})
    need = list(state.get("v2Need") or [])
    for r in message["ranges"]:
        if r["mode"] == RANGE_FINGERPRINT:
            inbound.append({
                "lo": r["lo"], "hi": r["hi"],
                "count": r["count"], "fp": r["fp"],
            })
        else:
            theirs = set(r["items"])
            for h in view.items(r["lo"], r["hi"]):
                if h not in theirs:
                    send_queue[h] = True
            for h in r["items"]:
                if not view.contains(h):
                    need.append(h)
    return dict(
        state,
        sharedHeads=shared_heads,
        lastSentHeads=last_sent_heads,
        theirHeads=message["heads"],
        theirNeed=message["need"],
        theirHave=None,  # v1 belief; stale after a v2 exchange
        v2Inbound=inbound,
        v2SendQueue=send_queue,
        v2Need=need,
    )


# ---------------------------------------------------------------------- #
# single-document entry points (the v2 twins of sync.py's)

def generate_sync_message_v2(backend, sync_state, index):
    """Generates the next v2 message for a peer, or None when converged.
    Returns (sync_state, message_bytes_or_None)."""
    if backend is None:
        raise SyncProtocolError(
            "generate_sync_message_v2 called with no Automerge document"
        )
    if sync_state is None:
        raise SyncProtocolError(
            "generate_sync_message_v2 requires a sync_state"
        )
    our_heads = Backend.get_heads(backend)
    our_need = Backend.get_missing_deps(backend, sync_state.get("theirHeads") or [])
    plan, queries = plan_generate_v2(sync_state, index, our_heads)
    fps = index.fingerprint_many(queries)
    return finish_generate_v2(
        sync_state, plan, fps,
        lambda h: Backend.get_change_by_hash(backend, h),
        our_heads, our_need,
    )


def receive_sync_message_v2(backend, old_sync_state, index, binary_message):
    """Processes a received v2 message; returns (backend, sync_state,
    patch). Malformed or inapplicable messages raise ``SyncProtocolError``
    with the backend, the sync_state object AND the index all provably
    untouched (validation and change application both complete before any
    local mutation)."""
    if backend is None:
        raise SyncProtocolError(
            "receive_sync_message_v2 called with no Automerge document"
        )
    if old_sync_state is None:
        raise SyncProtocolError(
            "receive_sync_message_v2 requires a sync_state"
        )
    try:
        _fault_point("sync.receive_message_v2", message=binary_message)
        message = decode_sync_message_v2(binary_message)
    except SyncProtocolError:
        _M2_REJECTED.inc()
        raise
    before_heads = Backend.get_heads(backend)
    patch = None
    if message["changes"]:
        try:
            backend, patch = Backend.apply_changes(backend, message["changes"])
        except (AutomergeError, ValueError, KeyError, IndexError) as exc:
            # OpSet.apply_changes commits only after a clean run, so the
            # backend state is untouched here
            _M2_REJECTED.inc()
            raise SyncProtocolError(
                f"v2 sync message carried inapplicable changes: {exc}"
            ) from exc
        index.insert_many(
            decode_change_meta_cached(c)["hash"] for c in message["changes"]
        )
    _M2_MSGS_RECV.inc()
    _M_BYTES_RECV.inc(len(binary_message))
    _M_CHANGES_RECV.inc(len(message["changes"]))
    new_state = post_receive_v2(
        old_sync_state, message, before_heads, Backend.get_heads(backend),
        lambda h: Backend.get_change_by_hash(backend, h) is not None,
        index,
    )
    return backend, new_state, patch
