"""``atomic_write`` — crash-safe whole-file replacement with an fsync seam.

Every durable artifact that is replaced as a unit — the mesh workers'
black-box crash files (obs/flight.py), the store's manifests, compacted
chunks and the quarantine sidecar — goes through this one helper: write
to a pid-tagged temp name in the target directory, flush, fsync,
``os.replace`` over the destination, fsync the directory entry. A crash
at any instant leaves either the old file or the new file on disk, never
a torn mix (rename within one directory is atomic on POSIX).

The fsync seam (``fsync_file``/``fsync_dir``) is the fault-injectable
durability boundary: it fires the ``store.fsync`` failure point
(testing/faults.py) before touching the kernel, so the crash-point sweep
can abort a workload at every sync without a real power cut. amlint rule
AM601 holds the durability-plane modules to this writer — raw write
handles below are the rule's justified escape hatch.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import os


def _fire(point: str, **context) -> None:
    # Late import: obs/flight.py uses this module, and testing.faults pulls
    # in columnar/obs — binding at call time keeps the import graph acyclic.
    from ..testing.faults import fire

    fire(point, **context)


def fsync_file(fh) -> None:
    """Flushes and fsyncs an open file object (the durability boundary for
    data bytes). Fires the ``store.fsync`` failure point first."""
    _fire("store.fsync", path=getattr(fh, "name", "<fd>"))
    fh.flush()
    os.fsync(fh.fileno())


def fsync_dir(path: str) -> None:
    """Fsyncs a directory so a rename/unlink inside it is durable (the
    durability boundary for file *names*). Fires ``store.fsync``."""
    _fire("store.fsync", path=path)
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path, data, fsync: bool = True) -> None:
    """Replaces ``path`` with ``data`` (str or bytes) atomically.

    With ``fsync`` (the default) both the bytes and the directory entry
    are synced, so the replacement survives a power cut; without it the
    write is still atomic against process crashes (rename is the commit
    point) but rides the OS writeback cache."""
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    if isinstance(data, str):
        # amlint: disable=AM601 — this IS the atomic writer the rule points at
        fh = open(tmp, "w", encoding="utf-8")
    else:
        # amlint: disable=AM601 — this IS the atomic writer the rule points at
        fh = open(tmp, "wb")
    try:
        with fh:
            fh.write(data)
            if fsync:
                fsync_file(fh)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if fsync:
        fsync_dir(os.path.dirname(path) or ".")
