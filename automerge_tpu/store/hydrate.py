"""Batched cold-start hydration: ``ShardStore`` → ``TpuDocFarm``.

The naive cold start is a per-doc ``load()`` loop — one decode pass and
one farm delivery per document, paying the dispatch overhead
``num_docs`` times. ``open_farm`` instead feeds *every* recovered change
buffer through ``warm_decode_cache``'s vectorized decode path in one
shot, then replays the whole store as a single batched
``apply_changes`` delivery straight into farm pages. After replay the
rebuilt hash graph is verified against the segment footers, documents a
corrupt segment covered are quarantined with their ``StoreCorruptError``
cause, and the persisted quarantine sidecar (causes + failure counts) is
restored — quarantine state survives save/load instead of silently
resetting.

Hydration happens *before* the store is attached to the farm, so the
replay is never re-logged into the WAL it just came from.

This module keeps its device-layer imports inside the functions: the
``store`` package stays importable on hosts without jax (mesh worker
specs and the lint gate touch it), and only an actual hydration pulls in
the farm.
"""
from __future__ import annotations

from ..errors import StoreCorruptError, error_from_kind
from ..obs.flight import get_flight
from ..obs.metrics import get_metrics
from .wal import ShardStore, StoreConfig

_METRICS = get_metrics()
_M_HYDRATE_DOCS = _METRICS.counter(
    "store.hydrate.docs", "documents hydrated into farm pages by open_farm"
)
_M_HYDRATE_CHANGES = _METRICS.counter(
    "store.hydrate.changes",
    "recovered changes replayed through the batched decode path",
)
_FLIGHT = get_flight()


def quarantine_snapshot(farm) -> dict:
    """The farm quarantine state the store persists as its sidecar: active
    causes (by taxonomy kind + message) and non-zero failure counts. JSON
    keys are strings; ``hydrate_farm`` undoes the coercion on restore."""
    return {
        "quarantine": {
            str(d): {"kind": getattr(exc, "kind", "other"), "message": str(exc)}
            for d, exc in farm.quarantine.items()
        },
        "fault_counts": {
            str(d): count
            for d, count in enumerate(farm.fault_counts) if count
        },
    }


def hydrate_farm(farm, store: ShardStore):
    """Replays a recovered store into ``farm`` as one batched delivery and
    restores the persisted fault-isolation state. Returns the store's
    ``RecoveryReport``. Call before ``farm.attach_store(store)``."""
    from ..tpu.decode import warm_decode_cache

    recovered = store.recovered_commits()
    per_doc: list[list] = [[] for _ in range(farm.num_docs)]
    total = 0
    for doc, buffers in recovered.items():
        if not 0 <= doc < farm.num_docs:
            raise StoreCorruptError(
                f"store covers doc {doc} but the farm has only "
                f"{farm.num_docs} slots — refusing to drop history"
            )
        per_doc[doc] = list(buffers)
        total += len(buffers)
    if total:
        warm_decode_cache([buf for bufs in per_doc for buf in bufs])
        farm.apply_changes(per_doc)
    store.drop_recovered()

    # hash-graph verification: every change a sealed/cold footer vouches
    # for must exist in the rebuilt graph, or the doc's history is a lie
    for doc, hashes in store.footer_hashes.items():
        if doc in store.corrupt_docs or doc >= farm.num_docs:
            continue
        index = farm.change_index_by_hash[doc]
        missing = sum(1 for h in hashes if h not in index)
        if missing:
            exc = StoreCorruptError(
                f"hash-graph verification failed for doc {doc}: {missing} "
                "footer hash(es) absent after replay — repair via sync "
                "redelivery"
            )
            store.corrupt_docs[doc] = exc
            store.report.corrupt_docs[doc] = exc

    for doc, exc in store.corrupt_docs.items():
        if doc < farm.num_docs:
            farm.quarantine[doc] = exc

    snapshot = store.load_quarantine()
    if snapshot:
        for key, cause in snapshot.get("quarantine", {}).items():
            doc = int(key)
            if doc < farm.num_docs and doc not in farm.quarantine:
                farm.quarantine[doc] = error_from_kind(
                    cause.get("kind", "other"), cause.get("message", "")
                )
        for key, count in snapshot.get("fault_counts", {}).items():
            doc = int(key)
            if doc < farm.num_docs:
                farm.fault_counts[doc] = int(count)

    if _METRICS.enabled:
        _M_HYDRATE_DOCS.inc(sum(1 for bufs in per_doc if bufs))
        _M_HYDRATE_CHANGES.inc(total)
    if _FLIGHT.enabled:
        _FLIGHT.record(
            "store.hydrate", root=store.root,
            docs=sum(1 for bufs in per_doc if bufs), changes=total,
            quarantined=len(farm.quarantine),
        )
    return store.report


def open_farm(root, num_docs: int | None = None, *,
              store_config: StoreConfig | None = None,
              farm=None, farm_factory=None, **farm_kwargs):
    """Opens (and thereby recovers) the shard store at ``root``, hydrates a
    farm from it in one batched delivery, and attaches the store so every
    subsequent committed delivery is WAL-durable before its ack.

    Pass an existing ``farm``, a ``farm_factory`` callable, or ``num_docs``
    (plus ``TpuDocFarm`` kwargs) to construct one. Returns
    ``(farm, store)``; the recovery details are on ``store.report``."""
    store = ShardStore(root, store_config)
    try:
        if farm is None:
            if farm_factory is not None:
                farm = farm_factory()
            elif num_docs is None:
                raise ValueError(
                    "open_farm needs a farm, a farm_factory, or num_docs"
                )
            else:
                from ..tpu.farm import TpuDocFarm

                farm = TpuDocFarm(num_docs, **farm_kwargs)
        hydrate_farm(farm, store)
    except BaseException:
        store.close()
        raise
    farm.attach_store(store)
    return farm, store
