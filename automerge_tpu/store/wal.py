"""amstore — crash-consistent write-ahead persistence for the doc farm.

One ``ShardStore`` owns one directory and makes a single guarantee:
**acked ⇒ durable**. `TpuDocFarm.apply_changes` appends every committed
change to the active write-ahead segment and runs a group-commit fsync
barrier *before* the patches are returned, so any crash after an ack can
be replayed from disk, and any crash before one loses at most work the
caller never saw acknowledged.

Directory layout::

    MANIFEST.json       compaction state, committed via atomic_write:
                        {"generation", "cold": [...], "compacted_through"}
    wal-00000003.open   the active segment (append + group-commit fsync)
    wal-00000002.seg    sealed segments (footer frame, then atomic rename)
    cold-g0002-000.seg  compacted doc-grouped chunks (generation g)
    quarantine.json     farm quarantine sidecar (causes + failure counts)
    corrupt/            checksum-corrupt segments, moved aside for forensics

Frame format (all segment files): ``u32le length | sha256(payload) |
payload`` where payload is ``u8 record_type | body``. Commit records
(type 1) carry ``uleb(doc) uleb(n) n×(uleb(len) change-bytes)`` —
reference-format binary changes, stored verbatim so persisted chunks stay
bit-compatible with the save/load corpus. Chunk records (type 3, written
by compaction) use the same body for a document's whole committed
history. A footer (type 2, JSON) seals a segment with its record count
and per-doc change-hash lists — the recovery path verifies the rebuilt
hash graph against these.

Recovery policy (``ShardStore`` open):

- a short/torn frame at the tail of the *active* segment is the signature
  of a crash mid-append: the tail is truncated at the last whole frame
  (``StoreTornWriteError`` is recorded, not raised) and appending resumes;
- a checksum-mismatched *complete* frame, or a sealed segment without a
  valid footer, is real corruption: the whole segment moves to
  ``corrupt/`` and every document it covers is handed to the farm
  quarantine with a ``StoreCorruptError`` cause — repairable via sync
  redelivery, never fatal to the open;
- compaction is two-generation: the new cold chunk is written and
  verified (decoded back from disk, hash graph compared against the
  source footers) before ``MANIFEST.json`` atomically swaps generations
  and the sources are deleted, so a crash at any stage leaves exactly one
  generation fully live; orphans of the losing generation are swept on
  the next open.

Durability knobs (``StoreConfig``): ``group_commit=N`` fsyncs every N-th
commit barrier instead of every one — acks inside the window survive a
process crash (the bytes are flushed) but ride the OS cache against power
loss; ``segment_bytes`` bounds the active segment before rotation;
``auto_compact_segments`` triggers compaction once that many sealed
segments accumulate; ``fsync=False`` drops to flush-only for tests.

Failure points (testing/faults.py): ``store.append`` before a frame is
written, ``store.fsync`` inside the seam, ``store.rotate`` at the
footer/rename stages, ``store.compact`` at write/verify/swap/cleanup.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import dataclasses
import json
import os
import re
import struct
from hashlib import sha256

from ..columnar import decode_change_meta_cached
from ..errors import StoreCorruptError, StoreTornWriteError
from ..obs.flight import get_flight
from ..obs.metrics import get_metrics
from ..testing.faults import fire
from .atomic import atomic_write, fsync_dir, fsync_file

_METRICS = get_metrics()
_M_APPEND_RECORDS = _METRICS.counter(
    "store.append.records", "commit records appended to the write-ahead log"
)
_M_APPEND_BYTES = _METRICS.counter(
    "store.append.bytes", "framed bytes appended to the write-ahead log"
)
_M_FSYNC = _METRICS.counter(
    "store.fsyncs", "group-commit fsync barriers reaching the kernel"
)
_M_ROTATIONS = _METRICS.counter(
    "store.rotations", "active segments sealed and atomically renamed"
)
_M_SEALED = _METRICS.gauge(
    "store.segments.sealed", "sealed write-ahead segments awaiting compaction"
)
_M_COMPACTIONS = _METRICS.counter(
    "store.compactions", "WAL-to-cold compaction passes committed"
)
_M_FOLDED = _METRICS.counter(
    "store.compact.folded_records",
    "commit records folded into cold chunks by compaction",
)
_M_REC_RECORDS = _METRICS.counter(
    "store.recover.records", "commit/chunk records replayed on open"
)
_M_REC_TORN = _METRICS.counter(
    "store.recover.torn_bytes", "bytes truncated from torn segment tails on open"
)
_M_REC_CORRUPT = _METRICS.counter(
    "store.recover.corrupt_segments",
    "checksum-corrupt segments quarantined on open",
)
_FLIGHT = get_flight()

_MAGIC = b"AMST"
_HEADER = _MAGIC + bytes([1])  # magic + format version
_DIGEST_LEN = 32
_LEN_FMT = struct.Struct("<I")

_REC_COMMIT = 1
_REC_FOOTER = 2
_REC_CHUNK = 3

_WAL_RE = re.compile(r"^wal-(\d{8})\.(seg|open)$")
_COLD_RE = re.compile(r"^cold-g(\d{4})-(\d{3})\.seg$")

MANIFEST_NAME = "MANIFEST.json"
QUARANTINE_NAME = "quarantine.json"
CORRUPT_DIR = "corrupt"


@dataclasses.dataclass
class StoreConfig:
    """Durability/maintenance knobs for one shard store (see module doc)."""

    group_commit: int = 1
    segment_bytes: int = 1 << 20
    auto_compact_segments: int = 0
    fsync: bool = True


@dataclasses.dataclass
class RecoveryReport:
    """What one ``ShardStore`` open found and did (also on ``store.report``)."""

    segments: int = 0
    records: int = 0
    changes: int = 0
    torn_bytes: int = 0
    sealed_on_open: int = 0
    corrupt_segments: list = dataclasses.field(default_factory=list)
    corrupt_docs: dict = dataclasses.field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not (self.torn_bytes or self.corrupt_segments or self.corrupt_docs)


def _uleb(value: int) -> bytes:
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _read_uleb(data: bytes, pos: int) -> tuple[int, int]:
    value = shift = 0
    while True:
        if pos >= len(data):
            raise StoreCorruptError("truncated varint inside a store record")
        byte = data[pos]
        pos += 1
        value |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return value, pos
        shift += 7


def _frame(payload: bytes) -> bytes:
    return _LEN_FMT.pack(len(payload)) + sha256(payload).digest() + payload


def _record_body(rec_type: int, doc: int, buffers) -> bytes:
    body = bytearray((rec_type,))
    body += _uleb(doc)
    body += _uleb(len(buffers))
    for buf in buffers:
        raw = bytes(buf)
        body += _uleb(len(raw))
        body += raw
    return bytes(body)


def _parse_record_body(body: bytes) -> tuple[int, list[bytes]]:
    doc, pos = _read_uleb(body, 1)
    count, pos = _read_uleb(body, pos)
    buffers = []
    for _ in range(count):
        length, pos = _read_uleb(body, pos)
        if pos + length > len(body):
            raise StoreCorruptError("store record buffer overruns its frame")
        buffers.append(body[pos:pos + length])
        pos += length
    return doc, buffers


@dataclasses.dataclass
class _SegScan:
    """One segment file, parsed with the recovery policy applied lazily."""

    records: list = dataclasses.field(default_factory=list)  # (doc, [bytes])
    footer: dict | None = None
    torn_offset: int | None = None  # file offset of the first torn frame
    corrupt: bool = False
    error: str = ""
    docs: set = dataclasses.field(default_factory=set)


def _scan_segment(data: bytes) -> _SegScan:
    """Walks every frame of a segment image. Never raises: torn tails and
    checksum damage are reported on the scan so the caller can pick the
    truncate-vs-quarantine policy (active vs sealed)."""
    scan = _SegScan()
    if not data.startswith(_HEADER):
        if _HEADER.startswith(data):  # crash mid-header: a torn, empty segment
            scan.torn_offset = 0
            return scan
        scan.corrupt = True
        scan.error = "bad segment magic/version"
        return scan
    pos = len(_HEADER)
    while pos < len(data):
        head_end = pos + _LEN_FMT.size + _DIGEST_LEN
        if head_end > len(data):
            scan.torn_offset = pos
            return scan
        (length,) = _LEN_FMT.unpack_from(data, pos)
        payload_end = head_end + length
        if payload_end > len(data):
            scan.torn_offset = pos
            return scan
        digest = data[pos + _LEN_FMT.size:head_end]
        payload = data[head_end:payload_end]
        pos = payload_end
        if sha256(payload).digest() != digest:
            scan.corrupt = True
            scan.error = scan.error or "frame checksum mismatch"
            continue  # framing is self-delimiting: keep walking for coverage
        if not payload:
            scan.corrupt = True
            scan.error = scan.error or "empty frame payload"
            continue
        rec_type = payload[0]
        if rec_type == _REC_FOOTER:
            try:
                scan.footer = json.loads(payload[1:].decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                scan.corrupt = True
                scan.error = scan.error or "unparseable segment footer"
            continue
        if rec_type not in (_REC_COMMIT, _REC_CHUNK):
            scan.corrupt = True
            scan.error = scan.error or f"unknown record type {rec_type}"
            continue
        try:
            doc, buffers = _parse_record_body(payload)
        except StoreCorruptError as exc:
            scan.corrupt = True
            scan.error = scan.error or str(exc)
            continue
        scan.records.append((doc, buffers))
        scan.docs.add(doc)
    if scan.footer is not None:
        for key in scan.footer.get("docs", {}):
            try:
                scan.docs.add(int(key))
            except ValueError:
                pass
        if scan.footer.get("records") != len(scan.records):
            scan.corrupt = True
            scan.error = scan.error or "footer record count disagrees with body"
    return scan


def _footer_frame(records: int, hashes: dict[int, list[str]]) -> bytes:
    payload = bytes((_REC_FOOTER,)) + json.dumps(
        {"records": records, "docs": {str(d): h for d, h in sorted(hashes.items())}},
        sort_keys=True,
    ).encode("utf-8")
    return _frame(payload)


class ShardStore:
    """One shard's crash-consistent change store (see the module doc).

    Opening the store *is* recovery: the constructor sweeps compaction
    orphans, replays every live segment with the torn-tail/corruption
    policy, and leaves the store appendable. The replayed history is on
    ``recovered_commits()`` (per-doc ordered change buffers) for the
    hydration layer; ``corrupt_docs`` and ``report`` describe the damage.
    """

    def __init__(self, root, config: StoreConfig | None = None):
        self.root = os.fspath(root)
        self.config = config or StoreConfig()
        if self.config.group_commit < 1:
            raise ValueError("group_commit must be >= 1")
        self.report = RecoveryReport()
        self.corrupt_docs: dict[int, StoreCorruptError] = {}
        #: per-doc ordered change-hash lists from sealed/cold footers — the
        #: hydration layer verifies the rebuilt hash graph against these
        self.footer_hashes: dict[int, list[str]] = {}
        self._recovered: dict[int, list[bytes]] = {}
        self._manifest = {"generation": 0, "cold": [], "compacted_through": 0}
        self._fh = None
        self._active_seq = 0
        self._active_path = ""
        self._active_size = 0
        self._active_records = 0
        self._active_hashes: dict[int, list[str]] = {}
        self._unsynced = False
        self._since_fsync = 0
        self._q_sig: str | None = None
        self._open()

    # ------------------------------------------------------------------ #
    # naming

    def _path(self, name: str) -> str:
        return os.path.join(self.root, name)

    @staticmethod
    def _wal_name(seq: int, sealed: bool) -> str:
        return f"wal-{seq:08d}.{'seg' if sealed else 'open'}"

    def _sealed_paths(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.root):
            m = _WAL_RE.match(name)
            if m and m.group(2) == "seg":
                seq = int(m.group(1))
                if seq > self._manifest["compacted_through"]:
                    out.append((seq, self._path(name)))
        out.sort()
        return out

    # ------------------------------------------------------------------ #
    # open-time recovery

    def _open(self) -> None:
        os.makedirs(self.root, exist_ok=True)
        self._load_manifest()
        self._sweep_orphans()
        for name in self._manifest["cold"]:
            self._recover_file(self._path(name), sealed=True)
        wal_sealed, wal_open = [], []
        for name in os.listdir(self.root):
            m = _WAL_RE.match(name)
            if not m:
                continue
            seq = int(m.group(1))
            if seq <= self._manifest["compacted_through"]:
                continue
            (wal_sealed if m.group(2) == "seg" else wal_open).append((seq, name))
        for seq, name in sorted(wal_sealed):
            self._recover_file(self._path(name), sealed=True)
        survivor = None  # (seq, path, scan) of the .open segment to resume
        for seq, name in sorted(wal_open):
            result = self._recover_file(self._path(name), sealed=False)
            if result is not None:
                if survivor is not None:
                    # two live .open files cannot happen in one process
                    # lifetime; seal the older so the order stays on disk
                    self._seal_recovered(*survivor)
                survivor = (seq, self._path(name), result)
        _M_REC_RECORDS.inc(self.report.records)
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "store.recovered", root=self.root,
                segments=self.report.segments, records=self.report.records,
                docs=len(self._recovered),
                corrupt_segments=len(self.report.corrupt_segments),
            )
        if survivor is not None:
            seq, path, scan = survivor
            self._resume_active(seq, path, scan)
        else:
            top = max(
                [s for s, _ in wal_sealed + wal_open] or
                [self._manifest["compacted_through"]]
            )
            self._start_active(top + 1)
        self._q_sig = self._read_quarantine_raw()
        _M_SEALED.set(len(self._sealed_paths()))

    def _load_manifest(self) -> None:
        path = self._path(MANIFEST_NAME)
        if not os.path.exists(path):
            return
        try:
            with open(path, "rb") as fh:
                manifest = json.loads(fh.read().decode("utf-8"))
            self._manifest = {
                "generation": int(manifest["generation"]),
                "cold": list(manifest["cold"]),
                "compacted_through": int(manifest["compacted_through"]),
            }
        except (ValueError, KeyError, TypeError, UnicodeDecodeError) as exc:
            # The manifest is tiny and always atomic_write-replaced; damage
            # here means the store root itself is rotten, not one segment.
            raise StoreCorruptError(
                f"unreadable store manifest {path}: {exc}"
            ) from exc

    def _sweep_orphans(self) -> None:
        """Removes the losing generation of a crashed compaction: cold files
        the manifest does not own, folded WAL segments the manifest says are
        compacted, and stale atomic-write temps."""
        live_cold = set(self._manifest["cold"])
        for name in os.listdir(self.root):
            path = self._path(name)
            if ".tmp." in name:
                os.unlink(path)
                continue
            if _COLD_RE.match(name) and name not in live_cold:
                os.unlink(path)
                continue
            m = _WAL_RE.match(name)
            if m and int(m.group(1)) <= self._manifest["compacted_through"]:
                os.unlink(path)

    def _recover_file(self, path: str, sealed: bool):
        """Replays one segment. Returns the scan for a surviving ``.open``
        segment (so the caller can resume appending to it), else None."""
        with open(path, "rb") as fh:
            data = fh.read()
        scan = _scan_segment(data)
        self.report.segments += 1
        if sealed and not scan.corrupt and (
            scan.torn_offset is not None or scan.footer is None
        ):
            # sealing is atomic (footer + rename): a sealed segment that is
            # short or footer-less was damaged after the fact
            scan.corrupt = True
            scan.error = scan.error or "sealed segment has no valid footer"
        if scan.corrupt:
            self._quarantine_segment(path, scan)
            return None
        if scan.torn_offset is not None:
            dropped = len(data) - scan.torn_offset
            self.report.torn_bytes += dropped
            _M_REC_TORN.inc(dropped)
            if _FLIGHT.enabled:
                _FLIGHT.record(
                    "store.torn_write", seg=os.path.basename(path),
                    offset=scan.torn_offset, dropped_bytes=dropped,
                    error=str(StoreTornWriteError("torn frame at segment tail")),
                )
            os.truncate(path, scan.torn_offset)
        for doc, buffers in scan.records:
            self._recovered.setdefault(doc, []).extend(buffers)
            self.report.records += 1
            self.report.changes += len(buffers)
        if scan.footer is not None:
            for key, hashes in scan.footer.get("docs", {}).items():
                self.footer_hashes.setdefault(int(key), []).extend(hashes)
        return None if sealed else scan

    def _quarantine_segment(self, path: str, scan: _SegScan) -> None:
        corrupt_dir = self._path(CORRUPT_DIR)
        os.makedirs(corrupt_dir, exist_ok=True)
        name = os.path.basename(path)
        os.replace(path, os.path.join(corrupt_dir, name))
        self.report.corrupt_segments.append(name)
        _M_REC_CORRUPT.inc()
        for doc in sorted(scan.docs):
            exc = StoreCorruptError(
                f"segment {name} failed verification ({scan.error}); "
                f"doc {doc}'s tail is unrecoverable from this store — "
                "repair via sync redelivery"
            )
            self.corrupt_docs[doc] = exc
            self.report.corrupt_docs[doc] = exc
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "store.segment.corrupt", seg=name, error=scan.error,
                docs=sorted(scan.docs),
            )
            _FLIGHT.trigger("store.corrupt", seg=name)

    def _seal_recovered(self, seq: int, path: str, scan: _SegScan) -> None:
        """Finishes a rotation a crash interrupted: appends the footer to a
        recovered ``.open`` segment and renames it sealed."""
        hashes: dict[int, list[str]] = {}
        for doc, buffers in scan.records:
            hashes.setdefault(doc, []).extend(
                decode_change_meta_cached(buf)["hash"] for buf in buffers
            )
        # amlint: disable=AM601 — checksummed-frame append; sealing commits via rename
        with open(path, "ab") as fh:
            fh.write(_footer_frame(len(scan.records), hashes))
            if self.config.fsync:
                fsync_file(fh)
        os.replace(path, self._path(self._wal_name(seq, sealed=True)))
        if self.config.fsync:
            fsync_dir(self.root)
        for doc, doc_hashes in hashes.items():
            self.footer_hashes.setdefault(doc, []).extend(doc_hashes)
        self.report.sealed_on_open += 1

    def _resume_active(self, seq: int, path: str, scan: _SegScan) -> None:
        self._active_seq = seq
        self._active_path = path
        if scan.footer is not None:
            # the crash hit between footer-write and rename: finish it
            self._seal_recovered(seq, path, scan)
            self._start_active(seq + 1)
            return
        self._active_records = len(scan.records)
        self._active_hashes = {}
        for doc, buffers in scan.records:
            self._active_hashes.setdefault(doc, []).extend(
                decode_change_meta_cached(buf)["hash"] for buf in buffers
            )
        # amlint: disable=AM601 — the WAL's checksummed append handle itself
        self._fh = open(path, "ab")
        self._active_size = os.path.getsize(path)
        if self._active_size < len(_HEADER):
            # the torn tail ate into the header itself: start the image over
            self._fh.write(_HEADER[self._active_size:])
            self._active_size = len(_HEADER)

    def _start_active(self, seq: int) -> None:
        self._active_seq = seq
        self._active_path = self._path(self._wal_name(seq, sealed=False))
        # amlint: disable=AM601 — the WAL's checksummed append handle itself
        self._fh = open(self._active_path, "wb")
        self._fh.write(_HEADER)
        self._active_size = len(_HEADER)
        self._active_records = 0
        self._active_hashes = {}

    # ------------------------------------------------------------------ #
    # hydration hand-off

    def recovered_commits(self) -> dict[int, list[bytes]]:
        """Per-doc committed change buffers replayed on open, in commit
        order (cold generation first, then WAL segments by sequence)."""
        return self._recovered

    def drop_recovered(self) -> None:
        """Releases the replayed buffers once hydration has applied them."""
        self._recovered = {}

    # ------------------------------------------------------------------ #
    # the write path

    def append_commit(self, doc: int, buffers) -> None:
        """Appends one committed delivery for one doc (called by the farm
        before the delivery is acked; ``commit_barrier`` makes it durable)."""
        if not buffers:
            return
        fire("store.append", doc=doc)
        # hash (and thereby structurally validate) the buffers *before* the
        # write: an unencodable buffer must never reach a committed frame
        hashes = [decode_change_meta_cached(buf)["hash"] for buf in buffers]
        frame = _frame(_record_body(_REC_COMMIT, doc, buffers))
        self._fh.write(frame)
        self._active_records += 1
        self._active_size += len(frame)
        self._unsynced = True
        self._active_hashes.setdefault(doc, []).extend(hashes)
        if _METRICS.enabled:
            _M_APPEND_RECORDS.inc()
            _M_APPEND_BYTES.inc(len(frame))

    def commit_barrier(self, quarantine: dict | None = None) -> None:
        """The ack boundary: runs the group-commit fsync policy, persists a
        changed quarantine sidecar, and triggers rotation/compaction
        housekeeping. The farm calls this once per apply, just before
        returning patches."""
        if quarantine is not None:
            self.save_quarantine(quarantine)
        if self._unsynced:
            self._since_fsync += 1
            if self._since_fsync >= self.config.group_commit:
                self._sync_active()
            else:
                self._fh.flush()
        if self._active_size >= self.config.segment_bytes and self._active_records:
            self.rotate()
        limit = self.config.auto_compact_segments
        if limit and len(self._sealed_paths()) >= limit:
            self.compact()

    def _sync_active(self) -> None:
        if self.config.fsync:
            fsync_file(self._fh)
            _M_FSYNC.inc()
        else:
            self._fh.flush()
        self._unsynced = False
        self._since_fsync = 0

    def rotate(self) -> None:
        """Seals the active segment: footer, fsync, atomic rename to
        ``.seg``, then a fresh active. Crash-safe at every step — a footer
        without the rename is finished on the next open; a torn footer is
        truncated away and the segment stays active."""
        if self._fh is None or self._active_records == 0:
            return
        name = os.path.basename(self._active_path)
        fire("store.rotate", stage="footer", seg=name)
        self._fh.write(_footer_frame(self._active_records, self._active_hashes))
        if self.config.fsync:
            fsync_file(self._fh)
            _M_FSYNC.inc()
        else:
            self._fh.flush()
        self._fh.close()
        self._fh = None
        fire("store.rotate", stage="rename", seg=name)
        sealed_path = self._path(self._wal_name(self._active_seq, sealed=True))
        os.replace(self._active_path, sealed_path)
        if self.config.fsync:
            fsync_dir(self.root)
        for doc, hashes in self._active_hashes.items():
            self.footer_hashes.setdefault(doc, []).extend(hashes)
        _M_ROTATIONS.inc()
        _M_SEALED.set(len(self._sealed_paths()))
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "store.rotate", seg=os.path.basename(sealed_path),
                records=self._active_records, bytes=self._active_size,
            )
        self._unsynced = False
        self._since_fsync = 0
        self._start_active(self._active_seq + 1)

    # ------------------------------------------------------------------ #
    # compaction

    def compact(self) -> None:
        """Folds every sealed WAL segment (plus the previous cold
        generation) into one doc-grouped cold chunk, verifies the new
        generation against the source hash graph, then atomically swaps the
        manifest and deletes the sources. A crash at any stage leaves
        exactly one generation fully live."""
        sealed = self._sealed_paths()
        if not sealed:
            return
        new_gen = self._manifest["generation"] + 1
        fire("store.compact", stage="write", generation=new_gen)
        per_doc: dict[int, list[bytes]] = {}
        expected: dict[int, list[str]] = {}
        folded_records = 0
        sources = [self._path(n) for n in self._manifest["cold"]]
        sources += [path for _, path in sealed]
        for path in sources:
            with open(path, "rb") as fh:
                scan = _scan_segment(fh.read())
            if scan.corrupt or scan.torn_offset is not None or scan.footer is None:
                raise StoreCorruptError(
                    f"compaction source {os.path.basename(path)} failed "
                    f"verification ({scan.error or 'torn/footer-less'}); "
                    "compaction aborted, both generations untouched"
                )
            for doc, buffers in scan.records:
                per_doc.setdefault(doc, []).extend(buffers)
                folded_records += 1
            for key, hashes in scan.footer.get("docs", {}).items():
                expected.setdefault(int(key), []).extend(hashes)
        image = bytearray(_HEADER)
        chunk_hashes: dict[int, list[str]] = {}
        for doc in sorted(per_doc):
            image += _frame(_record_body(_REC_CHUNK, doc, per_doc[doc]))
            chunk_hashes[doc] = [
                decode_change_meta_cached(buf)["hash"] for buf in per_doc[doc]
            ]
        image += _footer_frame(len(per_doc), chunk_hashes)
        cold_name = f"cold-g{new_gen:04d}-000.seg"
        cold_path = self._path(cold_name)
        atomic_write(cold_path, bytes(image), fsync=self.config.fsync)
        fire("store.compact", stage="verify", generation=new_gen)
        self._verify_cold(cold_path, expected)
        fire("store.compact", stage="swap", generation=new_gen)
        top_seq = max(seq for seq, _ in sealed)
        manifest = {
            "generation": new_gen,
            "cold": [cold_name],
            "compacted_through": top_seq,
        }
        atomic_write(
            self._path(MANIFEST_NAME),
            json.dumps(manifest, sort_keys=True),
            fsync=self.config.fsync,
        )
        self._manifest = manifest
        fire("store.compact", stage="cleanup", generation=new_gen)
        for path in sources:
            os.unlink(path)
        if self.config.fsync:
            fsync_dir(self.root)
        _M_COMPACTIONS.inc()
        _M_FOLDED.inc(folded_records)
        _M_SEALED.set(0)
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "store.compact", generation=new_gen,
                segments=len(sources), records=folded_records,
                docs=len(per_doc), bytes=len(image),
            )

    def _verify_cold(self, path: str, expected: dict[int, list[str]]) -> None:
        """Hash-graph verification of a freshly written cold chunk, read
        back from disk: every source change hash must survive, in order,
        before the sources may be deleted."""
        with open(path, "rb") as fh:
            scan = _scan_segment(fh.read())
        actual: dict[int, list[str]] = {}
        if not (scan.corrupt or scan.torn_offset is not None or scan.footer is None):
            for doc, buffers in scan.records:
                actual.setdefault(doc, []).extend(
                    decode_change_meta_cached(buf)["hash"] for buf in buffers
                )
        if scan.corrupt or scan.torn_offset is not None or scan.footer is None \
                or actual != expected:
            os.unlink(path)
            raise StoreCorruptError(
                "compacted chunk failed hash-graph verification against its "
                "source footers; sources kept, new generation discarded"
            )

    # ------------------------------------------------------------------ #
    # quarantine sidecar

    def _read_quarantine_raw(self) -> str | None:
        path = self._path(QUARANTINE_NAME)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as fh:
            return fh.read().decode("utf-8", errors="replace")

    def save_quarantine(self, snapshot: dict) -> None:
        """Persists the farm's quarantine sidecar (active causes + failure
        counts) when it changed since the last write."""
        sig = json.dumps(snapshot, sort_keys=True)
        if sig == self._q_sig:
            return
        atomic_write(self._path(QUARANTINE_NAME), sig, fsync=self.config.fsync)
        self._q_sig = sig

    def load_quarantine(self) -> dict | None:
        """The persisted quarantine sidecar, or None if absent/unreadable
        (the sidecar is advisory: damage degrades to an empty quarantine,
        never a failed open)."""
        raw = self._read_quarantine_raw()
        if raw is None:
            return None
        try:
            snapshot = json.loads(raw)
        except ValueError:
            return None
        return snapshot if isinstance(snapshot, dict) else None

    # ------------------------------------------------------------------ #

    def close(self) -> None:
        """Final durability barrier + handle close (idempotent)."""
        if self._fh is None:
            return
        if self._unsynced:
            self._sync_active()
        self._fh.close()
        self._fh = None
