"""amstore — the crash-consistent persistence tier under the farm and mesh.

Three layers (full contract in wal.py's module doc):

- **atomic** (`store.atomic`): ``atomic_write`` tmp+rename replacement
  with a fault-injectable fsync seam — the one blessed writer for every
  durable artifact (amlint AM601 holds the durability plane to it).
- **wal** (`store.wal`): ``ShardStore`` — per-shard append-only segments
  of length+sha256-framed reference-format change chunks, group-commit
  fsync at the ack boundary, atomic rotation, torn-write truncation,
  corrupt-segment quarantine, and two-generation compaction into
  doc-grouped cold chunks with hash-graph verification.
- **hydrate** (`store.hydrate`): ``open_farm`` batched cold start —
  every recovered segment flows through ``warm_decode_cache``'s
  vectorized path into farm pages in one delivery, then the persisted
  quarantine sidecar is restored.

Importing this package never initialises jax; only an actual hydration
pulls in the device layer.
"""
from .atomic import atomic_write, fsync_dir, fsync_file
from .hydrate import hydrate_farm, open_farm, quarantine_snapshot
from .wal import (MANIFEST_NAME, QUARANTINE_NAME, RecoveryReport, ShardStore,
                  StoreConfig)

__all__ = [
    "atomic_write",
    "fsync_dir",
    "fsync_file",
    "hydrate_farm",
    "open_farm",
    "quarantine_snapshot",
    "ShardStore",
    "StoreConfig",
    "RecoveryReport",
    "MANIFEST_NAME",
    "QUARANTINE_NAME",
]
