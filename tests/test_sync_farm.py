"""SyncFarm differential suite: the batched sync driver must produce
byte-identical messages to the sequential protocol (automerge_tpu/sync.py)
and converge replica farms exactly like per-doc sync does (the simulated
two-peer pattern of the reference's test/sync_test.js)."""
import random

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu import sync as seq_sync
from automerge_tpu.columnar import decode_change_columns, encode_change
from automerge_tpu.tpu.farm import TpuDocFarm
from automerge_tpu.tpu.sync_farm import SyncFarm, filters_from_bytes
from automerge_tpu.tpu import sync_batch


def make_change(actor, seq, start_op, deps, ops):
    buf = encode_change(
        {"actor": actor, "seq": seq, "startOp": start_op, "time": 0,
         "deps": sorted(deps), "ops": ops}
    )
    return buf, decode_change_columns(buf)["hash"]


class Replica:
    """One side of the sync test: a farm of N docs plus N sequential
    backends fed identical changes, so the batched and sequential sync
    paths can be compared step by step."""

    def __init__(self, num_docs, actor):
        self.farm = TpuDocFarm(num_docs, capacity=256)
        self.sync = SyncFarm(self.farm)
        self.backends = [Backend.init() for _ in range(num_docs)]
        self.actor = actor
        self.seqs = [0] * num_docs
        self.max_op = [0] * num_docs

    def edit(self, d, rng, n_ops=2):
        """Applies a random local change to doc d on both representations."""
        self.seqs[d] += 1
        start = self.max_op[d] + 1
        ops = []
        for i in range(n_ops):
            ops.append({"action": "set", "obj": "_root",
                        "key": f"k{rng.randrange(6)}", "datatype": "uint",
                        "value": rng.randrange(1000), "pred": []})
        buf, _ = make_change(self.actor, self.seqs[d], start,
                             self.farm.get_heads(d), ops)
        self.max_op[d] = start + len(ops) - 1
        per_doc = [[] for _ in range(self.farm.num_docs)]
        per_doc[d] = [buf]
        self.farm.apply_changes(per_doc)
        self.backends[d], _ = Backend.apply_changes(self.backends[d], [buf])


def sync_farms(a, b, num_docs, max_rounds=10, check_bytes=True):
    """Runs the reference sync driver loop (sync_test.js:15-35) over every
    doc channel simultaneously, batched on each side, optionally asserting
    byte-equality against the sequential protocol each step."""
    a_states = [SyncFarm.init_state() for _ in range(num_docs)]
    b_states = [SyncFarm.init_state() for _ in range(num_docs)]
    sa_states = [seq_sync.init_sync_state() for _ in range(num_docs)]
    sb_states = [seq_sync.init_sync_state() for _ in range(num_docs)]

    for _ in range(max_rounds):
        out_a = a.sync.generate_messages(
            [(d, a_states[d]) for d in range(num_docs)]
        )
        any_msg = False
        for d in range(num_docs):
            a_states[d], msg = out_a[d]
            if check_bytes:
                sa_states[d], seq_msg = seq_sync.generate_sync_message(
                    a.backends[d], sa_states[d]
                )
                assert msg == seq_msg, f"A->B message mismatch doc {d}"
            if msg is None:
                continue
            any_msg = True
            (b_states[d], _patch), = b.sync.receive_messages(
                [(d, b_states[d], msg)]
            )
            if check_bytes:
                b.backends[d], sb_states[d], _p = seq_sync.receive_sync_message(
                    b.backends[d], sb_states[d], msg
                )
        out_b = b.sync.generate_messages(
            [(d, b_states[d]) for d in range(num_docs)]
        )
        for d in range(num_docs):
            b_states[d], msg = out_b[d]
            if check_bytes:
                sb_states[d], seq_msg = seq_sync.generate_sync_message(
                    b.backends[d], sb_states[d]
                )
                assert msg == seq_msg, f"B->A message mismatch doc {d}"
            if msg is None:
                continue
            any_msg = True
            (a_states[d], _patch), = a.sync.receive_messages(
                [(d, a_states[d], msg)]
            )
            if check_bytes:
                a.backends[d], sa_states[d], _p = seq_sync.receive_sync_message(
                    a.backends[d], sa_states[d], msg
                )
        if not any_msg:
            break
    return a_states, b_states


class TestFiltersFromBytes:
    def test_round_trip(self):
        import numpy as np

        rng = np.random.default_rng(0)
        xyz = rng.integers(0, 2**32, size=(4, 9, 3), dtype=np.uint32)
        counts = np.asarray([9, 4, 0, 1], np.int32)
        words, modulo = sync_batch.build_filters(xyz, counts, 4)
        blobs = sync_batch.filters_to_bytes(words, modulo, counts)
        w2, m2, c2 = filters_from_bytes(blobs)
        np.testing.assert_array_equal(c2, counts)
        np.testing.assert_array_equal(m2, np.asarray(modulo))
        got = sync_batch.query_filters(w2, m2, c2, xyz)
        want = sync_batch.query_filters(words, modulo, counts, xyz)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestSyncFarm:
    def test_empty_docs_reach_quiescence(self):
        a = Replica(2, "aaaaaaaa")
        b = Replica(2, "bbbbbbbb")
        sync_farms(a, b, 2)
        for d in range(2):
            assert a.farm.get_heads(d) == b.farm.get_heads(d) == []

    def test_one_sided_transfer(self):
        rng = random.Random(1)
        a = Replica(3, "aaaaaaaa")
        b = Replica(3, "bbbbbbbb")
        for d in range(3):
            for _ in range(3):
                a.edit(d, rng)
        sync_farms(a, b, 3)
        for d in range(3):
            assert a.farm.get_heads(d) == b.farm.get_heads(d)
            assert a.farm.get_patch(d) == b.farm.get_patch(d)

    def test_divergent_replicas_converge(self):
        rng = random.Random(2)
        a = Replica(4, "aaaaaaaa")
        b = Replica(4, "bbbbbbbb")
        # common history first: sync once, then diverge
        for d in range(4):
            a.edit(d, rng)
        sync_farms(a, b, 4)
        for d in range(4):
            for _ in range(rng.randrange(1, 4)):
                a.edit(d, rng)
            for _ in range(rng.randrange(1, 4)):
                b.edit(d, rng)
        sync_farms(a, b, 4)
        for d in range(4):
            assert a.farm.get_heads(d) == b.farm.get_heads(d)
            assert a.farm.get_patch(d)["diffs"] == b.farm.get_patch(d)["diffs"]

    def test_repeated_incremental_rounds(self):
        rng = random.Random(3)
        a = Replica(2, "aaaaaaaa")
        b = Replica(2, "bbbbbbbb")
        for round_ in range(4):
            for d in range(2):
                if rng.random() < 0.8:
                    a.edit(d, rng)
                if rng.random() < 0.8:
                    b.edit(d, rng)
            sync_farms(a, b, 2)
        for d in range(2):
            assert a.farm.get_heads(d) == b.farm.get_heads(d)
            assert a.farm.get_patch(d)["diffs"] == b.farm.get_patch(d)["diffs"]

    def test_batched_receive_single_call(self):
        """All docs' messages received in ONE batched receive call."""
        rng = random.Random(4)
        num_docs = 3
        a = Replica(num_docs, "aaaaaaaa")
        b = Replica(num_docs, "bbbbbbbb")
        for d in range(num_docs):
            a.edit(d, rng)
        a_states = [SyncFarm.init_state() for _ in range(num_docs)]
        b_states = [SyncFarm.init_state() for _ in range(num_docs)]
        for _ in range(10):
            out_a = a.sync.generate_messages(
                [(d, a_states[d]) for d in range(num_docs)]
            )
            batch = []
            for d in range(num_docs):
                a_states[d], msg = out_a[d]
                if msg is not None:
                    batch.append((d, b_states[d], msg))
            if not batch:
                break
            for (d, _, _), (state, _patch) in zip(
                batch, b.sync.receive_messages(batch)
            ):
                b_states[d] = state
            out_b = b.sync.generate_messages(
                [(d, b_states[d]) for d in range(num_docs)]
            )
            batch = []
            for d in range(num_docs):
                b_states[d], msg = out_b[d]
                if msg is not None:
                    batch.append((d, a_states[d], msg))
            for (d, _, _), (state, _patch) in zip(
                batch, a.sync.receive_messages(batch)
            ):
                a_states[d] = state
        for d in range(num_docs):
            assert a.farm.get_heads(d) == b.farm.get_heads(d)

class TestQuarantineShedding:
    """ISSUE 5 satellite: a doc quarantined by the farm's per-doc isolation
    (PR 3) must not be offered in generate_messages until released, counted
    on sync.messages.shed_quarantined."""

    def _quarantine_doc(self, replica, d):
        from automerge_tpu.testing import faults

        bad = faults.garbage(48)
        for _ in range(replica.farm.quarantine_threshold):
            per_doc = [[] for _ in range(replica.farm.num_docs)]
            per_doc[d] = [bad]
            replica.farm.apply_changes(per_doc)
        assert d in replica.farm.quarantine

    def test_quarantined_doc_is_shed_from_generate(self):
        from automerge_tpu.obs.metrics import enabled_metrics, get_metrics

        rng = random.Random(11)
        a = Replica(2, "aaaaaaaa")
        for d in range(2):
            a.edit(d, rng)
        self._quarantine_doc(a, 0)
        metrics = get_metrics()
        metrics.reset()
        states = [SyncFarm.init_state() for _ in range(2)]
        with enabled_metrics():
            out = a.sync.generate_messages(
                [(d, states[d]) for d in range(2)]
            )
        (state0, msg0), (state1, msg1) = out
        assert msg0 is None          # quarantined channel sheds
        assert state0 == states[0]   # and leaves its sync state untouched
        assert msg1 is not None      # healthy neighbour unaffected
        snap = metrics.as_dict()
        assert snap["sync.messages.shed_quarantined"]["value"] == 1

    def test_release_resumes_sync_on_same_channel(self):
        rng = random.Random(12)
        a = Replica(1, "aaaaaaaa")
        b = Replica(1, "bbbbbbbb")
        a.edit(0, rng)
        self._quarantine_doc(a, 0)
        state = SyncFarm.init_state()
        ((_, msg),) = a.sync.generate_messages([(0, state)])
        assert msg is None
        a.farm.release_quarantine(0)
        # the same replicas converge normally after release (check_bytes
        # is off: the quarantine deliveries only touched the farm, so the
        # differential backends are not in lockstep for doc 0's farm)
        sync_farms(a, b, 1, check_bytes=False)
        assert a.farm.get_heads(0) == b.farm.get_heads(0)


class TestFarmReceiveIdempotency:
    """ISSUE 5 satellite: double-delivery of the same sync message through
    the batched receive path is a no-op on heads and farm state."""

    def test_double_receive_is_noop(self):
        import json

        rng = random.Random(13)
        a = Replica(1, "aaaaaaaa")
        b = Replica(1, "bbbbbbbb")
        for _ in range(3):
            a.edit(0, rng)
        a_state = SyncFarm.init_state()
        b_state = SyncFarm.init_state()
        # drive one exchange until a message carries changes
        msg_with_changes = None
        for _ in range(6):
            ((a_state, msg),) = a.sync.generate_messages([(0, a_state)])
            if msg is not None and seq_sync.decode_sync_message(msg)["changes"]:
                msg_with_changes = msg
                break
            if msg is not None:
                ((b_state, _),) = b.sync.receive_messages([(0, b_state, msg)])
            ((b_state, back),) = b.sync.generate_messages([(0, b_state)])
            if back is not None:
                ((a_state, _),) = a.sync.receive_messages([(0, a_state, back)])
        assert msg_with_changes is not None
        ((b_state1, patch1),) = b.sync.receive_messages(
            [(0, b_state, msg_with_changes)]
        )
        assert patch1 is not None
        heads = b.farm.get_heads(0)
        doc_json = json.dumps(b.farm.get_patch(0), sort_keys=True)
        # identical bytes again: heads, doc state and sharedHeads stable
        ((b_state2, _patch2),) = b.sync.receive_messages(
            [(0, b_state1, msg_with_changes)]
        )
        assert b.farm.get_heads(0) == heads
        assert json.dumps(b.farm.get_patch(0), sort_keys=True) == doc_json
        assert b_state2["sharedHeads"] == b_state1["sharedHeads"]
