"""Engine tests ported from the reference backend test suite
(/root/reference/test/new_backend_test.js): exact patch JSON and exact
encoded column bytes."""
import pytest

from automerge_tpu import backend as B
from automerge_tpu.columnar import encode_change
from automerge_tpu.opset import OpSet

from helpers import check_columns, hash_of

ACTOR = "0123456789abcdef"


def apply_all(opset, *changes):
    patches = []
    for change in changes:
        patches.append(opset.apply_changes([encode_change(change)]))
    return patches


class TestRootProperties:
    def test_overwrite_root_property(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 3, "pred": []},
            {"action": "set", "obj": "_root", "key": "y", "datatype": "uint", "value": 4, "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 3, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 5, "pred": [f"1@{ACTOR}"]},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p1 == {
            "maxOp": 2, "clock": {ACTOR: 1}, "deps": [hash_of(change1)], "pendingChanges": 0,
            "diffs": {"objectId": "_root", "type": "map", "props": {
                "x": {f"1@{ACTOR}": {"type": "value", "value": 3, "datatype": "uint"}},
                "y": {f"2@{ACTOR}": {"type": "value", "value": 4, "datatype": "uint"}},
            }},
        }
        assert p2 == {
            "maxOp": 3, "clock": {ACTOR: 2}, "deps": [hash_of(change2)], "pendingChanges": 0,
            "diffs": {"objectId": "_root", "type": "map", "props": {
                "x": {f"3@{ACTOR}": {"type": "value", "value": 5, "datatype": "uint"}},
            }},
        }
        check_columns(backend, {
            "objActor": [], "objCtr": [], "keyActor": [], "keyCtr": [],
            "keyStr": [2, 1, 0x78, 0x7F, 1, 0x79],
            "idActor": [3, 0],
            "idCtr": [0x7D, 1, 2, 0x7F],
            "insert": [3],
            "action": [3, 1],
            "valLen": [3, 0x13],
            "valRaw": [3, 5, 4],
            "succNum": [0x7F, 1, 2, 0],
            "succActor": [0x7F, 0],
            "succCtr": [0x7F, 3],
        })

    def test_concurrent_conflict(self):
        actor1, actor2 = "01234567", "89abcdef"
        change1 = {"actor": actor1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        change2 = {"actor": actor2, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 2, "pred": []},
        ]}
        change3 = {"actor": actor1, "seq": 2, "startOp": 2, "time": 0,
                   "deps": [hash_of(change1), hash_of(change2)], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 3,
             "pred": [f"1@{actor1}", f"1@{actor2}"]},
        ]}
        backend = OpSet()
        p1, p2, p3 = apply_all(backend, change1, change2, change3)
        assert p2["diffs"]["props"]["x"] == {
            f"1@{actor1}": {"type": "value", "value": 1, "datatype": "uint"},
            f"1@{actor2}": {"type": "value", "value": 2, "datatype": "uint"},
        }
        assert p2["deps"] == sorted([hash_of(change1), hash_of(change2)])
        assert p3["diffs"]["props"]["x"] == {
            f"2@{actor1}": {"type": "value", "value": 3, "datatype": "uint"},
        }
        check_columns(backend, {
            "keyStr": [3, 1, 0x78],
            "idActor": [0x7D, 0, 1, 0],
            "idCtr": [0x7D, 1, 0, 1],
            "insert": [3],
            "action": [3, 1],
            "valLen": [3, 0x13],
            "valRaw": [1, 2, 3],
            "succNum": [2, 1, 0x7F, 0],
            "succActor": [2, 0],
            "succCtr": [0x7E, 2, 0],
        })

    def test_pred_does_not_exist(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
            {"action": "set", "obj": "_root", "key": "y", "datatype": "uint", "value": 2, "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 3, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 3, "pred": [f"2@{ACTOR}"]},
        ]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        with pytest.raises(ValueError, match="no matching operation for pred"):
            backend.apply_changes([encode_change(change2)])

    def test_pred_does_not_exist_other_actor(self):
        actor1, actor2 = "01234567", "89abcdef"
        change1 = {"actor": actor1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        change2 = {"actor": actor2, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "w", "datatype": "uint", "value": 2, "pred": []},
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 2, "pred": []},
        ]}
        change3 = {"actor": actor1, "seq": 2, "startOp": 2, "time": 0,
                   "deps": [hash_of(change1), hash_of(change2)], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 3,
             "pred": [f"1@{actor2}"]},
        ]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        backend.apply_changes([encode_change(change2)])
        with pytest.raises(ValueError, match="no matching operation for pred"):
            backend.apply_changes([encode_change(change3)])


class TestNestedMaps:
    def test_create_and_update_nested_maps(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeMap", "obj": "_root", "key": "map", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "key": "x", "value": "a", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "key": "y", "value": "b", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "key": "z", "value": "c", "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 5, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": f"1@{ACTOR}", "key": "y", "value": "B", "pred": [f"3@{ACTOR}"]},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p1["diffs"] == {
            "objectId": "_root", "type": "map", "props": {"map": {f"1@{ACTOR}": {
                "objectId": f"1@{ACTOR}", "type": "map", "props": {
                    "x": {f"2@{ACTOR}": {"type": "value", "value": "a"}},
                    "y": {f"3@{ACTOR}": {"type": "value", "value": "b"}},
                    "z": {f"4@{ACTOR}": {"type": "value", "value": "c"}},
                },
            }}},
        }
        assert p2["diffs"] == {
            "objectId": "_root", "type": "map", "props": {"map": {f"1@{ACTOR}": {
                "objectId": f"1@{ACTOR}", "type": "map",
                "props": {"y": {f"5@{ACTOR}": {"type": "value", "value": "B"}}},
            }}},
        }
        check_columns(backend, {
            "objActor": [0, 1, 4, 0],
            "objCtr": [0, 1, 4, 1],
            "keyStr": [0x7E, 3, 0x6D, 0x61, 0x70, 1, 0x78, 2, 1, 0x79, 0x7F, 1, 0x7A],
            "idActor": [5, 0],
            "idCtr": [3, 1, 0x7E, 2, 0x7F],
            "insert": [5],
            "action": [0x7F, 0, 4, 1],
            "valLen": [0x7F, 0, 4, 0x16],
            "valRaw": [0x61, 0x62, 0x42, 0x63],
            "succNum": [2, 0, 0x7F, 1, 2, 0],
            "succActor": [0x7F, 0],
            "succCtr": [0x7F, 5],
        })

    def test_nested_maps_several_levels_deep(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeMap", "obj": "_root", "key": "a", "pred": []},
            {"action": "makeMap", "obj": f"1@{ACTOR}", "key": "b", "pred": []},
            {"action": "makeMap", "obj": f"2@{ACTOR}", "key": "c", "pred": []},
            {"action": "set", "obj": f"3@{ACTOR}", "key": "d", "datatype": "uint", "value": 1, "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 5, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": f"3@{ACTOR}", "key": "d", "datatype": "uint", "value": 2,
             "pred": [f"4@{ACTOR}"]},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p2["diffs"] == {
            "objectId": "_root", "type": "map", "props": {"a": {f"1@{ACTOR}": {
                "objectId": f"1@{ACTOR}", "type": "map", "props": {"b": {f"2@{ACTOR}": {
                    "objectId": f"2@{ACTOR}", "type": "map", "props": {"c": {f"3@{ACTOR}": {
                        "objectId": f"3@{ACTOR}", "type": "map", "props": {"d": {f"5@{ACTOR}": {
                            "type": "value", "value": 2, "datatype": "uint",
                        }}},
                    }}},
                }}},
            }}},
        }
        check_columns(backend, {
            "objActor": [0, 1, 4, 0],
            "objCtr": [0, 1, 0x7E, 1, 2, 2, 3],
            "keyStr": [0x7D, 1, 0x61, 1, 0x62, 1, 0x63, 2, 1, 0x64],
            "idActor": [5, 0],
            "idCtr": [5, 1],
            "insert": [5],
            "action": [3, 0, 2, 1],
            "valLen": [3, 0, 2, 0x13],
            "valRaw": [1, 2],
            "succNum": [3, 0, 0x7E, 1, 0],
            "succActor": [0x7F, 0],
            "succCtr": [0x7F, 5],
        })


class TestText:
    def test_create_text_object(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeText", "obj": "_root", "key": "text", "insert": False, "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": "_head", "insert": True, "value": "a", "pred": []},
        ]}
        backend = OpSet()
        (p1,) = apply_all(backend, change1)
        assert p1["diffs"] == {
            "objectId": "_root", "type": "map", "props": {"text": {f"1@{ACTOR}": {
                "objectId": f"1@{ACTOR}", "type": "text", "edits": [
                    {"action": "insert", "index": 0, "elemId": f"2@{ACTOR}", "opId": f"2@{ACTOR}",
                     "value": {"type": "value", "value": "a"}},
                ],
            }}},
        }
        check_columns(backend, {
            "objActor": [0, 1, 0x7F, 0],
            "objCtr": [0, 1, 0x7F, 1],
            "keyActor": [],
            "keyCtr": [0, 1, 0x7F, 0],
            "keyStr": [0x7F, 4, 0x74, 0x65, 0x78, 0x74, 0, 1],
            "idActor": [2, 0],
            "idCtr": [2, 1],
            "insert": [1, 1],
            "action": [0x7E, 4, 1],
            "valLen": [0x7E, 0, 0x16],
            "valRaw": [0x61],
            "succNum": [2, 0],
            "succActor": [],
            "succCtr": [],
        })

    def test_insert_text_characters(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeText", "obj": "_root", "key": "text", "insert": False, "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": "_head", "insert": True, "value": "a", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"2@{ACTOR}", "insert": True, "value": "b", "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 4, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"3@{ACTOR}", "insert": True, "value": "c", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"4@{ACTOR}", "insert": True, "value": "d", "pred": []},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p1["diffs"]["props"]["text"][f"1@{ACTOR}"]["edits"] == [
            {"action": "multi-insert", "index": 0, "elemId": f"2@{ACTOR}", "values": ["a", "b"]},
        ]
        assert p2["diffs"]["props"]["text"][f"1@{ACTOR}"]["edits"] == [
            {"action": "multi-insert", "index": 2, "elemId": f"4@{ACTOR}", "values": ["c", "d"]},
        ]
        check_columns(backend, {
            "objActor": [0, 1, 4, 0],
            "objCtr": [0, 1, 4, 1],
            "keyActor": [0, 2, 3, 0],
            "keyCtr": [0, 1, 0x7E, 0, 2, 2, 1],
            "keyStr": [0x7F, 4, 0x74, 0x65, 0x78, 0x74, 0, 4],
            "idActor": [5, 0],
            "idCtr": [5, 1],
            "insert": [1, 4],
            "action": [0x7F, 4, 4, 1],
            "valLen": [0x7F, 0, 4, 0x16],
            "valRaw": [0x61, 0x62, 0x63, 0x64],
            "succNum": [5, 0],
            "succActor": [],
            "succCtr": [],
        })

    def test_insertion_reference_not_found(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeText", "obj": "_root", "key": "text", "insert": False, "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": "_head", "insert": True, "value": "a", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"2@{ACTOR}", "insert": True, "value": "b", "pred": []},
            {"action": "makeMap", "obj": "_root", "key": "map", "insert": False, "pred": []},
            {"action": "set", "obj": f"4@{ACTOR}", "key": "foo", "insert": False, "value": "c", "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 6, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"4@{ACTOR}", "insert": True, "value": "d", "pred": []},
        ]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        with pytest.raises(ValueError, match="Reference element not found"):
            backend.apply_changes([encode_change(change2)])

    def test_non_consecutive_insertions(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeText", "obj": "_root", "key": "text", "insert": False, "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": "_head", "insert": True, "value": "a", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"2@{ACTOR}", "insert": True, "value": "c", "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 4, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"2@{ACTOR}", "insert": True, "value": "b", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"3@{ACTOR}", "insert": True, "value": "d", "pred": []},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p2["diffs"]["props"]["text"][f"1@{ACTOR}"]["edits"] == [
            {"action": "insert", "index": 1, "elemId": f"4@{ACTOR}", "opId": f"4@{ACTOR}",
             "value": {"type": "value", "value": "b"}},
            {"action": "insert", "index": 3, "elemId": f"5@{ACTOR}", "opId": f"5@{ACTOR}",
             "value": {"type": "value", "value": "d"}},
        ]


class TestDeletion:
    def test_delete_map_key(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 2, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "del", "obj": "_root", "key": "x", "pred": [f"1@{ACTOR}"]},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p2["diffs"] == {"objectId": "_root", "type": "map", "props": {"x": {}}}

    def test_delete_list_element(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeList", "obj": "_root", "key": "list", "insert": False, "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": "_head", "insert": True, "value": "a", "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": f"2@{ACTOR}", "insert": True, "value": "b", "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 4, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "del", "obj": f"1@{ACTOR}", "elemId": f"2@{ACTOR}", "insert": False,
             "pred": [f"2@{ACTOR}"]},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p2["diffs"]["props"]["list"][f"1@{ACTOR}"]["edits"] == [
            {"action": "remove", "index": 0, "count": 1},
        ]

    def test_multi_op_deletion(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeText", "obj": "_root", "key": "text", "insert": False, "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": "_head", "insert": True,
             "values": ["a", "b", "c"], "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 5, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "del", "obj": f"1@{ACTOR}", "elemId": f"2@{ACTOR}", "insert": False,
             "multiOp": 3, "pred": [f"2@{ACTOR}"]},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p1["diffs"]["props"]["text"][f"1@{ACTOR}"]["edits"] == [
            {"action": "multi-insert", "index": 0, "elemId": f"2@{ACTOR}", "values": ["a", "b", "c"]},
        ]
        assert p2["diffs"]["props"]["text"][f"1@{ACTOR}"]["edits"] == [
            {"action": "remove", "index": 0, "count": 3},
        ]


class TestCounters:
    def test_increment_counter(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "counter", "datatype": "counter", "value": 1, "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 2, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "inc", "obj": "_root", "key": "counter", "value": 2, "pred": [f"1@{ACTOR}"]},
        ]}
        backend = OpSet()
        p1, p2 = apply_all(backend, change1, change2)
        assert p1["diffs"]["props"]["counter"] == {
            f"1@{ACTOR}": {"type": "value", "value": 1, "datatype": "counter"},
        }
        assert p2["diffs"]["props"]["counter"] == {
            f"1@{ACTOR}": {"type": "value", "datatype": "counter", "value": 3},
        }


class TestCausalOrdering:
    def test_enqueue_out_of_order_changes(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 2, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": "_root", "key": "y", "datatype": "uint", "value": 2, "pred": []},
        ]}
        backend = OpSet()
        patch = backend.apply_changes([encode_change(change2)])
        assert patch["pendingChanges"] == 1
        assert patch["diffs"] == {"objectId": "_root", "type": "map", "props": {}}
        patch = backend.apply_changes([encode_change(change1)])
        assert patch["pendingChanges"] == 0
        assert patch["diffs"]["props"] == {
            "x": {f"1@{ACTOR}": {"type": "value", "value": 1, "datatype": "uint"}},
            "y": {f"2@{ACTOR}": {"type": "value", "value": 2, "datatype": "uint"}},
        }
        assert backend.get_missing_deps() == []

    def test_missing_deps_reported(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 2, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": "_root", "key": "y", "datatype": "uint", "value": 2, "pred": []},
        ]}
        backend = OpSet()
        backend.apply_changes([encode_change(change2)])
        assert backend.get_missing_deps() == [hash_of(change1)]

    def test_duplicate_changes_ignored(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        patch = backend.apply_changes([encode_change(change1)])
        assert patch["diffs"] == {"objectId": "_root", "type": "map", "props": {}}


class TestSaveLoad:
    def _build_doc(self):
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeText", "obj": "_root", "key": "text", "insert": False, "pred": []},
            {"action": "set", "obj": f"1@{ACTOR}", "elemId": "_head", "insert": True, "value": "a", "pred": []},
            {"action": "makeMap", "obj": "_root", "key": "map", "pred": []},
            {"action": "set", "obj": f"3@{ACTOR}", "key": "x", "datatype": "uint", "value": 1, "pred": []},
            {"action": "makeList", "obj": "_root", "key": "list", "pred": []},
            {"action": "set", "obj": f"5@{ACTOR}", "elemId": "_head", "insert": True,
             "values": [1, 2, 3], "datatype": "uint", "pred": []},
        ]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        return backend

    def test_save_load_round_trip(self):
        backend = self._build_doc()
        saved = backend.save()
        loaded = OpSet(saved)
        assert loaded.get_patch() == backend.get_patch()
        assert loaded.save() == saved

    def test_load_save_reencode_identical(self):
        backend = self._build_doc()
        saved = backend.save()
        loaded = OpSet(saved)
        loaded.binary_doc = None  # force re-encoding from the op rows
        assert loaded.save() == saved

    def test_save_load_after_merge(self):
        actor1, actor2 = "01234567", "89abcdef"
        change1 = {"actor": actor1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        change2 = {"actor": actor2, "seq": 1, "startOp": 1, "time": 0, "deps": [hash_of(change1)], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 2,
             "pred": [f"1@{actor1}"]},
        ]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        backend.apply_changes([encode_change(change2)])
        loaded = OpSet(backend.save())
        assert loaded.get_patch() == backend.get_patch()
        # the full change history can be reconstructed from the document
        assert loaded.get_changes([]) == backend.get_changes([])


class TestBackendFacade:
    def test_apply_local_change(self):
        b = B.init()
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        b, patch, bin1 = B.apply_local_change(b, change1)
        assert patch["actor"] == ACTOR
        assert patch["seq"] == 1
        assert patch["deps"] == []
        change2 = {"actor": ACTOR, "seq": 2, "startOp": 2, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 2,
             "pred": [f"1@{ACTOR}"]},
        ]}
        b, patch2, bin2 = B.apply_local_change(b, change2)
        assert patch2["deps"] == []
        assert B.get_all_changes(b) == [bin1, bin2]

    def test_frozen_state_rejected(self):
        b = B.init()
        change1 = {"actor": ACTOR, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "datatype": "uint", "value": 1, "pred": []},
        ]}
        b2, _ = B.apply_changes(b, [encode_change(change1)])
        with pytest.raises(ValueError, match="outdated Automerge document"):
            B.apply_changes(b, [encode_change(change1)])
