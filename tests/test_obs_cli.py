"""Tier-1 smoke for the obs CLI contract (ISSUE 8 satellite): the
``make obs-report`` target and the new ``--flight`` / ``--watch`` modes
cannot rot.

The Makefile target is parsed to pin that it still invokes
``python -m automerge_tpu.obs``, and the exact same command shape is run
as a subprocess asserting the report contract (span tree + metrics table,
exit 0). ``--watch`` is exercised headlessly against a snapshot file
written by a real (tiny) load-harness run — the "live top-style renderer
against a running loadgen" satellite, in its CI-friendly one-frame form.
"""
import json
import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run_cli(args, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "automerge_tpu.obs", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )


def test_makefile_obs_report_target_still_runs_the_cli():
    """The contract `make obs-report` wires into: the target must invoke
    `python -m automerge_tpu.obs` (the report CLI), so the smoke below
    exercises exactly what the Make target runs."""
    makefile = (REPO / "Makefile").read_text(encoding="utf-8")
    target = re.search(r"^obs-report:\n(\t.+\n?)+", makefile, re.M)
    assert target, "Makefile lost its obs-report target"
    assert "-m automerge_tpu.obs" in target.group(0)


def test_obs_report_subprocess_contract():
    """The `make obs-report` command shape succeeds and prints the span
    tree with percentiles plus the metrics table."""
    proc = _run_cli(["--docs", "2", "--rounds", "1", "--ops", "4"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "== spans ==" in proc.stdout
    assert "== metrics ==" in proc.stdout
    assert "p50" in proc.stdout and "p99" in proc.stdout
    assert "engine.device.dispatches" in proc.stdout


def test_flight_render_needs_no_workload(tmp_path):
    """--flight renders a dump in-process without touching jax or the
    canned workload."""
    from automerge_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(clock=lambda: 0.5)
    rec.enabled = True
    rec.record("watchdog.reset", epoch=7)
    rec.record("flight.trigger", reason="watchdog.reset")
    dump = tmp_path / "dump.jsonl"
    dump.write_text(rec.to_jsonl(), encoding="utf-8")

    from automerge_tpu.obs.__main__ import main

    assert main(["--flight", str(dump)]) == 0


@pytest.fixture(scope="module")
def snapshot_file(tmp_path_factory):
    """A telemetry snapshot file produced by a real tiny load-harness run
    (simulated time; the --watch data source)."""
    from automerge_tpu.serve.loadgen import LoadConfig, LoadGen
    from automerge_tpu.tpu.farm import TpuDocFarm

    path = tmp_path_factory.mktemp("watch") / "snaps.jsonl"
    farm = TpuDocFarm(4, capacity=64)
    gen = LoadGen(farm, LoadConfig(
        clients=12, docs=4, edits_per_client=1, ops_per_edit=2,
        spread=0.3, observability="full", snapshot_path=str(path),
        snapshot_interval=0.2,
    ))
    report = gen.run()
    assert report["converged"]
    return {"path": path, "report": report}


def test_watch_renders_latest_snapshot_headlessly(snapshot_file, capsys):
    snapshot_file = snapshot_file["path"]
    """The --watch satellite, exercised headlessly: one frame with the
    tenant table, the phase shares and the flight tail, exit 0."""
    from automerge_tpu.obs.__main__ import main

    assert main(["--watch", str(snapshot_file)]) == 0
    out = capsys.readouterr().out
    assert "phase shares" in out
    assert "queue_wait" in out and "readback" in out and "ack" in out
    assert "tenants" in out
    assert "t0" in out  # a tenant row
    assert "flight tail" in out


def test_loadgen_report_and_snapshots_carry_slo_verdicts(snapshot_file,
                                                         capsys):
    """ISSUE 13: an observability!="off" load-harness run evaluates the
    serve SLO set on the simulated clock — the report carries the verdict
    block, every snapshot line embeds the verdicts as of its tick, and
    the --watch view renders the SLO panel."""
    report = snapshot_file["report"]
    assert report["slo"]["ok"] is True
    names = {v["objective"] for v in report["slo"]["verdicts"]}
    assert names == {
        "serve_latency", "serve_availability", "serve_convergence",
    }
    lines = [
        json.loads(ln)
        for ln in snapshot_file["path"].read_text().splitlines()
    ]
    assert lines and all("slo" in rec for rec in lines)
    from automerge_tpu.obs.__main__ import main

    assert main(["--watch", str(snapshot_file["path"])]) == 0
    out = capsys.readouterr().out
    assert "-- SLOs --" in out
    assert "serve_latency" in out and "serve_convergence" in out


def test_watch_renders_mesh_shard_table(tmp_path, capsys):
    """Shard-labelled mesh metrics in a snapshot pivot into the per-shard
    --watch section via ``obs.export.shard_table`` (one row per shard,
    histograms collapsed to count @ total ms)."""
    from automerge_tpu.obs.__main__ import main

    record = {
        "t": 1.0,
        "metrics": {
            "mesh.shard.0.docs": {"type": "counter", "value": 96},
            "mesh.shard.1.docs": {"type": "counter", "value": 160},
            "mesh.shard.0.dispatch_ms": {
                "type": "histogram", "count": 2, "sum": 12.5, "p99": 8.0,
            },
            "serve.flush.shard.1.docs": {"type": "counter", "value": 7},
            "mesh.shards": {"type": "gauge", "value": 2},  # unlabelled: not a row
        },
        "tenants": {},
        "flight_tail": [],
    }
    path = tmp_path / "snaps.jsonl"
    path.write_text(json.dumps(record) + "\n", encoding="utf-8")
    assert main(["--watch", str(path)]) == 0
    out = capsys.readouterr().out
    assert "-- shards --" in out
    assert "dispatch_ms" in out and "docs" in out
    assert "flush.docs" in out  # serve family must not shadow mesh docs
    assert "96" in out and "160" in out
    assert "2 @ 12.5ms" in out  # the histogram cell
    rows = [ln for ln in out.splitlines() if ln.strip().startswith(("0 ", "1 "))]
    assert len(rows) == 2


def test_watch_snapshot_lines_are_self_contained(snapshot_file):
    lines = [
        json.loads(line)
        for line in snapshot_file["path"].read_text(
            encoding="utf-8").splitlines()
        if line.strip()
    ]
    assert len(lines) >= 2  # periodic + final
    last = lines[-1]
    assert "metrics" in last and "tenants" in last and "flight_tail" in last
    assert last["breakdown"]["requests"] > 0


def test_watch_missing_file_exits_nonzero(capsys):
    from automerge_tpu.obs.__main__ import main

    assert main(["--watch", "/nonexistent/snaps.jsonl"]) == 1
