"""Flight-recorder suite (automerge_tpu/obs/flight.py + fault-path
integration).

Covers the ISSUE 8 contract:
- the ring is bounded and causally ordered (global seq survives wraps);
- auto-dump: entering farm quarantine, a device fault, channel
  quarantine and a watchdog reset each snapshot the ring to JSONL;
- a chaos+poison loadgen run auto-dumps a timeline containing the
  quarantine events that occurred (the acceptance-criteria shape);
- the ``--flight`` CLI renders a dump as a causally-ordered timeline.

Plus the ISSUE 13 mesh telemetry channel:
- ``ship()``/``absorb()`` move a worker recorder's unshipped tail into
  the controller ring with origin tags and fresh controller seqs;
- black-box recovery dedups against live-shipped events;
- merged multi-process dumps order deterministically by
  ``(seq, epoch, shard, wseq)`` while untagged single-process dumps keep
  the exact pre-mesh shape (byte-identical timeline, no shard column);
- the disabled path stays one counter compare — no ring access.
"""
import json
import os
import random

import pytest

from automerge_tpu.obs.flight import (
    BLACKBOX_TAIL,
    FlightRecorder,
    enabled_flight,
    get_flight,
    load_jsonl,
    read_blackbox,
    render_timeline,
    write_blackbox,
)
from automerge_tpu.serve.loadgen import LoadConfig, LoadGen
from automerge_tpu.testing.faults import bit_flipped
from automerge_tpu.tpu.farm import TpuDocFarm


def _stream(rounds, ops, actor="aaaaaaaa", seed=0):
    from automerge_tpu.obs.__main__ import _change_stream

    return _change_stream(actor, rounds, ops, seed=seed)


# ---------------------------------------------------------------------- #
# ring mechanics

def test_ring_is_bounded_and_causally_ordered():
    rec = FlightRecorder(capacity=8, clock=lambda: 0.0)
    rec.enabled = True
    for i in range(20):
        rec.record("batcher.flush", t=float(i), n=i)
    assert len(rec) == 8
    events = rec.snapshot()
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    assert events[0]["fields"]["n"] == 12  # oldest 12 fell off
    assert events[-1]["fields"]["n"] == 19


def test_jsonl_round_trip_and_timeline_render():
    rec = FlightRecorder(clock=lambda: 1.25)
    rec.enabled = True
    rec.record("engine.slab.grow", pages=32, rows=2048)
    rec.record("session.retransmit", t=2.0, seq=4, attempt=1,
               backoff_ms=120.5)
    events = load_jsonl(rec.to_jsonl())
    assert [e["event"] for e in events] == [
        "engine.slab.grow", "session.retransmit"
    ]
    assert events[0]["t"] == 1.25  # recorder clock default
    table = render_timeline(events)
    assert "engine.slab.grow" in table and "backoff_ms=120.5" in table
    assert render_timeline([]) == "(no flight events)"


def test_trigger_dumps_bounded_files(tmp_path):
    rec = FlightRecorder(clock=lambda: 0.0)
    rec.enabled = True
    rec.dump_dir = str(tmp_path)
    rec.record("batcher.flush", reason="timer")
    path = rec.trigger("farm.quarantine", doc=3)
    assert path is not None and os.path.exists(path)
    events = load_jsonl(open(path, encoding="utf-8").read())
    assert events[-1]["event"] == "flight.trigger"
    assert events[-1]["fields"]["reason"] == "farm.quarantine"
    assert any(e["event"] == "batcher.flush" for e in events)
    # the dump budget bounds file count
    from automerge_tpu.obs import flight as flight_mod

    for _ in range(flight_mod.MAX_AUTO_DUMPS + 4):
        rec.trigger("farm.quarantine")
    assert len(rec.dump_paths) == flight_mod.MAX_AUTO_DUMPS


def test_trigger_without_dump_dir_still_records():
    rec = FlightRecorder()
    rec.enabled = True
    rec.dump_dir = None
    assert rec.trigger("watchdog.reset") is None
    assert rec.snapshot()[-1]["event"] == "flight.trigger"


# ---------------------------------------------------------------------- #
# the mesh telemetry channel: ship -> absorb -> one merged timeline

def test_ship_returns_unshipped_tail_exactly_once():
    rec = FlightRecorder(clock=lambda: 0.0)
    rec.enabled = True
    rec.shard = 1
    rec.record("a", x=1)
    rec.record("b")
    shipped = rec.ship()
    assert [e["event"] for e in shipped] == ["a", "b"]
    # shard-tagged: the worker's origin key rides every shipped event
    assert all(e["shard"] == 1 and e["epoch"] == 0 for e in shipped)
    assert shipped[0]["wseq"] == shipped[0]["seq"]
    assert rec.ship() == []          # the mark advanced
    rec.record("c")
    assert [e["event"] for e in rec.ship()] == ["c"]


def test_disabled_telemetry_channel_never_touches_the_ring():
    """The S3 one-attribute assertions: while observability is off,
    ``ship()`` is a counter compare and ``record``/``absorb`` return
    before any ring access — a ring that explodes on use proves it."""
    rec = FlightRecorder()
    assert rec.enabled is False

    class _Boom:
        def __iter__(self):
            raise AssertionError("disabled ship() walked the ring")

        def append(self, item):
            raise AssertionError("disabled path appended to the ring")

    rec._ring = _Boom()
    assert rec.ship() == []
    rec.record("dropped", x=1)
    assert rec.absorb([{"event": "x", "seq": 1}]) == 0


def test_absorb_assigns_fresh_seqs_and_keeps_origin():
    worker = FlightRecorder(clock=lambda: 5.0)
    worker.enabled = True
    worker.shard = 2
    worker.epoch = 3
    worker.record("w.event", n=1)
    ctrl = FlightRecorder(clock=lambda: 9.0)
    ctrl.enabled = True
    ctrl.record("c.event")
    assert ctrl.absorb(worker.ship()) == 1
    events = ctrl.snapshot()
    assert [e["event"] for e in events] == ["c.event", "w.event"]
    absorbed = events[-1]
    assert absorbed["seq"] == 2              # fresh controller seq
    assert (absorbed["shard"], absorbed["epoch"], absorbed["wseq"]) \
        == (2, 3, 1)
    assert absorbed["t"] == 5.0              # the worker's own clock
    assert absorbed["fields"] == {"n": 1}


def test_absorb_dedup_skips_live_shipped_origins():
    """Black-box recovery: the dead worker's tail overlaps what it
    already shipped live — dedup absorbs only the genuinely new events,
    keyed by origin, and the merged timeline stays duplicate-free."""
    worker = FlightRecorder(clock=lambda: 1.0)
    worker.enabled = True
    worker.shard = 1
    ctrl = FlightRecorder(clock=lambda: 2.0)
    ctrl.enabled = True
    worker.record("a")
    worker.record("b")
    ctrl.absorb(worker.ship())               # live ship before the crash
    worker.record("c")                       # died before shipping this
    tail = worker.tail(BLACKBOX_TAIL)        # the black-box shape: a,b,c
    assert ctrl.absorb(tail, dedup=True) == 1
    mesh_events = [e for e in ctrl.snapshot() if e.get("shard") == 1]
    assert [e["event"] for e in mesh_events] == ["a", "b", "c"]


def test_merge_key_orders_colliding_dumps_deterministically():
    """The S1 ordering fix: per-process seqs collide when a controller
    dump and a dead worker's black box are concatenated; the merge key
    ``(seq, epoch, shard, wseq)`` interleaves them deterministically
    (controller rows first, then shards, then respawn epochs)."""
    rows = [
        {"seq": 1, "t": 0.0, "event": "w1", "fields": {},
         "shard": 1, "epoch": 0, "wseq": 1},
        {"seq": 1, "t": 0.0, "event": "c", "fields": {}},
        {"seq": 1, "t": 0.0, "event": "w0e1", "fields": {},
         "shard": 0, "epoch": 1, "wseq": 1},
        {"seq": 1, "t": 0.0, "event": "w0", "fields": {},
         "shard": 0, "epoch": 0, "wseq": 1},
        {"seq": 2, "t": 0.0, "event": "w0b", "fields": {},
         "shard": 0, "epoch": 0, "wseq": 2},
    ]
    merged = load_jsonl("\n".join(json.dumps(r) for r in rows))
    assert [e["event"] for e in merged] == ["c", "w0", "w1", "w0e1", "w0b"]


def test_untagged_dump_keeps_the_pre_mesh_shape():
    """Single-process runs are byte-identical to the pre-mesh format: no
    origin keys in the events, no shard column in the timeline."""
    rec = FlightRecorder(clock=lambda: 1.0)
    rec.enabled = True
    rec.record("a", k=1)
    events = load_jsonl(rec.to_jsonl())
    assert set(events[0]) == {"seq", "t", "event", "fields"}
    table = render_timeline(events)
    assert "shard" not in table.splitlines()[0]


def test_timeline_grows_shard_column_only_when_tagged():
    untagged = [{"seq": 1, "t": 0.0, "event": "local.ev", "fields": {}}]
    tagged = untagged + [{"seq": 2, "t": 0.0, "event": "worker.ev",
                          "fields": {}, "shard": 3, "epoch": 0, "wseq": 1}]
    table = render_timeline(tagged)
    header, row_local, row_worker = table.splitlines()
    assert "shard" in header
    assert "-" in row_local.split("local.ev")[0]    # controller rows: '-'
    assert "3" in row_worker.split("worker.ev")[0]  # worker rows: shard id


def test_blackbox_write_read_round_trip(tmp_path):
    rec = FlightRecorder(clock=lambda: 2.0)
    rec.enabled = True
    rec.shard = 1
    rec.epoch = 2
    for i in range(BLACKBOX_TAIL + 10):
        rec.record("e", i=i)
    path = str(tmp_path / "bb.json")
    write_blackbox(path, rec, phases_jsonl="{}")
    bb = read_blackbox(path)
    assert bb["pid"] == os.getpid()
    assert (bb["shard"], bb["epoch"]) == (1, 2)
    assert len(bb["events"]) == BLACKBOX_TAIL     # bounded tail
    assert bb["events"][-1]["fields"]["i"] == BLACKBOX_TAIL + 9
    assert bb["phases"] == "{}"
    # best-effort by contract: absent and torn files read as None
    assert read_blackbox(str(tmp_path / "missing.json")) is None
    (tmp_path / "torn.json").write_text("{not json", encoding="utf-8")
    assert read_blackbox(str(tmp_path / "torn.json")) is None


# ---------------------------------------------------------------------- #
# fault-path integration: the auto-dump sources

def test_farm_quarantine_entry_records_and_dumps(tmp_path):
    """Entering the farm's quarantine set leaves a farm.quarantine.enter
    event (with the offending hashes) and auto-dumps the ring."""
    with enabled_flight(dump_dir=str(tmp_path)) as rec:
        rec.clear()
        farm = TpuDocFarm(2, capacity=32, quarantine_threshold=1)
        good = _stream(1, 4)[0]
        bad = bytes(bit_flipped(good))
        farm.apply_changes([[good], [bad]])
        events = rec.snapshot()
    kinds = [e["event"] for e in events]
    assert "farm.quarantine.enter" in kinds
    enter = next(e for e in events if e["event"] == "farm.quarantine.enter")
    assert enter["fields"]["doc"] == 1
    assert enter["fields"]["kind"]
    assert rec.dump_paths, "quarantine entry did not dump"
    dumped = load_jsonl(open(rec.dump_paths[0], encoding="utf-8").read())
    assert any(e["event"] == "farm.quarantine.enter" for e in dumped)
    # release leaves its event too
    with enabled_flight():
        farm.release_quarantine()
        assert get_flight().snapshot()[-1]["event"] == "farm.quarantine.release"


def test_session_retry_exhaustion_records_and_dumps(tmp_path):
    """A channel burning its retry budget leaves retransmit events and a
    session.quarantine.enter, and dumps the ring."""
    from automerge_tpu import backend as Backend
    from automerge_tpu.sync_session import (
        BackendDriver,
        SessionConfig,
        SyncSession,
    )
    from automerge_tpu.testing.chaos import ManualClock

    clock = ManualClock()
    with enabled_flight(dump_dir=str(tmp_path)) as rec:
        rec.clear()
        session = SyncSession(
            BackendDriver(Backend.init()), clock=clock,
            rng=random.Random(0),
            config=SessionConfig(timeout=1.0, max_retries=2,
                                 backoff_base=0.1, backoff_cap=0.2),
        )
        # generate one payload frame; never ack it
        assert session.poll() is not None
        for _ in range(8):
            clock.advance(5.0)
            session.poll()
            if session.quarantined:
                break
        assert session.quarantined
        events = rec.snapshot()
    kinds = [e["event"] for e in events]
    assert kinds.count("session.retransmit") >= 2
    assert "session.quarantine.enter" in kinds
    # timestamps came from the injected (simulated) clock
    retransmit = next(e for e in events
                      if e["event"] == "session.retransmit")
    assert retransmit["t"] >= 5.0
    assert rec.dump_paths
    # release leaves its event
    with enabled_flight():
        session.release()
        assert get_flight().snapshot()[-1]["event"] == \
            "session.quarantine.release"


def test_engine_recompile_event_names_shape_bucket():
    with enabled_flight() as rec:
        rec.clear()
        farm = TpuDocFarm(2, capacity=32)
        from automerge_tpu.obs.metrics import enabled_metrics

        with enabled_metrics():
            buf = _stream(1, 4)[0]
            farm.apply_changes([[buf], [buf]])
        events = [e for e in rec.snapshot()
                  if e["event"] == "engine.recompile"]
    assert events, "fresh shapes compiled without a recompile event"
    assert events[0]["fields"]["fn"]
    assert events[0]["fields"]["shapes"]


# ---------------------------------------------------------------------- #
# acceptance shape: chaos+poison loadgen auto-dumps a usable timeline

@pytest.fixture(scope="module")
def poison_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("flight")
    farm = TpuDocFarm(8, capacity=128)
    gen = LoadGen(farm, LoadConfig(
        clients=24, docs=8, edits_per_client=2, ops_per_edit=3,
        spread=0.5, chaos=0.15, poison=0.25, seed=5,
        observability="full", flight_dir=str(tmp),
    ))
    report = gen.run()
    return {"report": report, "farm": farm}


def test_poison_run_quarantines_and_dumps(poison_run):
    report = poison_run["report"]
    assert report["quarantined_docs"] > 0
    assert report["flight_dumps"], "no flight dump despite quarantines"
    for path in report["flight_dumps"]:
        assert os.path.exists(path)


def test_poison_run_timeline_contains_the_quarantine_events(poison_run):
    """The acceptance criterion: the auto-dumped timeline contains the
    quarantine (and any watchdog) events that occurred, causally
    ordered, and renders."""
    path = poison_run["report"]["flight_dumps"][-1]
    events = load_jsonl(open(path, encoding="utf-8").read())
    kinds = {e["event"] for e in events}
    assert "farm.quarantine.enter" in kinds
    assert "batcher.flush" in kinds
    assert "flight.trigger" in kinds
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    quarantined_docs = {
        e["fields"]["doc"] for e in events
        if e["event"] == "farm.quarantine.enter"
    }
    assert quarantined_docs <= set(poison_run["farm"].quarantine) | \
        quarantined_docs  # every event names a doc the farm quarantined
    assert quarantined_docs & set(poison_run["farm"].quarantine)
    table = render_timeline(events)
    assert "farm.quarantine.enter" in table


def test_flight_cli_renders_dump(poison_run, capsys):
    from automerge_tpu.obs.__main__ import main

    path = poison_run["report"]["flight_dumps"][-1]
    assert main(["--flight", path]) == 0
    out = capsys.readouterr().out
    assert "farm.quarantine.enter" in out
    assert "seq" in out.splitlines()[0]
    # machine-readable variant
    assert main(["--flight", path, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert any(e["event"] == "flight.trigger" for e in payload["events"])
