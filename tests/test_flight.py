"""Flight-recorder suite (automerge_tpu/obs/flight.py + fault-path
integration).

Covers the ISSUE 8 contract:
- the ring is bounded and causally ordered (global seq survives wraps);
- auto-dump: entering farm quarantine, a device fault, channel
  quarantine and a watchdog reset each snapshot the ring to JSONL;
- a chaos+poison loadgen run auto-dumps a timeline containing the
  quarantine events that occurred (the acceptance-criteria shape);
- the ``--flight`` CLI renders a dump as a causally-ordered timeline.
"""
import json
import os
import random

import pytest

from automerge_tpu.obs.flight import (
    FlightRecorder,
    enabled_flight,
    get_flight,
    load_jsonl,
    render_timeline,
)
from automerge_tpu.serve.loadgen import LoadConfig, LoadGen
from automerge_tpu.testing.faults import bit_flipped
from automerge_tpu.tpu.farm import TpuDocFarm


def _stream(rounds, ops, actor="aaaaaaaa", seed=0):
    from automerge_tpu.obs.__main__ import _change_stream

    return _change_stream(actor, rounds, ops, seed=seed)


# ---------------------------------------------------------------------- #
# ring mechanics

def test_ring_is_bounded_and_causally_ordered():
    rec = FlightRecorder(capacity=8, clock=lambda: 0.0)
    rec.enabled = True
    for i in range(20):
        rec.record("batcher.flush", t=float(i), n=i)
    assert len(rec) == 8
    events = rec.snapshot()
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    assert events[0]["fields"]["n"] == 12  # oldest 12 fell off
    assert events[-1]["fields"]["n"] == 19


def test_jsonl_round_trip_and_timeline_render():
    rec = FlightRecorder(clock=lambda: 1.25)
    rec.enabled = True
    rec.record("engine.slab.grow", pages=32, rows=2048)
    rec.record("session.retransmit", t=2.0, seq=4, attempt=1,
               backoff_ms=120.5)
    events = load_jsonl(rec.to_jsonl())
    assert [e["event"] for e in events] == [
        "engine.slab.grow", "session.retransmit"
    ]
    assert events[0]["t"] == 1.25  # recorder clock default
    table = render_timeline(events)
    assert "engine.slab.grow" in table and "backoff_ms=120.5" in table
    assert render_timeline([]) == "(no flight events)"


def test_trigger_dumps_bounded_files(tmp_path):
    rec = FlightRecorder(clock=lambda: 0.0)
    rec.enabled = True
    rec.dump_dir = str(tmp_path)
    rec.record("batcher.flush", reason="timer")
    path = rec.trigger("farm.quarantine", doc=3)
    assert path is not None and os.path.exists(path)
    events = load_jsonl(open(path, encoding="utf-8").read())
    assert events[-1]["event"] == "flight.trigger"
    assert events[-1]["fields"]["reason"] == "farm.quarantine"
    assert any(e["event"] == "batcher.flush" for e in events)
    # the dump budget bounds file count
    from automerge_tpu.obs import flight as flight_mod

    for _ in range(flight_mod.MAX_AUTO_DUMPS + 4):
        rec.trigger("farm.quarantine")
    assert len(rec.dump_paths) == flight_mod.MAX_AUTO_DUMPS


def test_trigger_without_dump_dir_still_records():
    rec = FlightRecorder()
    rec.enabled = True
    rec.dump_dir = None
    assert rec.trigger("watchdog.reset") is None
    assert rec.snapshot()[-1]["event"] == "flight.trigger"


# ---------------------------------------------------------------------- #
# fault-path integration: the auto-dump sources

def test_farm_quarantine_entry_records_and_dumps(tmp_path):
    """Entering the farm's quarantine set leaves a farm.quarantine.enter
    event (with the offending hashes) and auto-dumps the ring."""
    with enabled_flight(dump_dir=str(tmp_path)) as rec:
        rec.clear()
        farm = TpuDocFarm(2, capacity=32, quarantine_threshold=1)
        good = _stream(1, 4)[0]
        bad = bytes(bit_flipped(good))
        farm.apply_changes([[good], [bad]])
        events = rec.snapshot()
    kinds = [e["event"] for e in events]
    assert "farm.quarantine.enter" in kinds
    enter = next(e for e in events if e["event"] == "farm.quarantine.enter")
    assert enter["fields"]["doc"] == 1
    assert enter["fields"]["kind"]
    assert rec.dump_paths, "quarantine entry did not dump"
    dumped = load_jsonl(open(rec.dump_paths[0], encoding="utf-8").read())
    assert any(e["event"] == "farm.quarantine.enter" for e in dumped)
    # release leaves its event too
    with enabled_flight():
        farm.release_quarantine()
        assert get_flight().snapshot()[-1]["event"] == "farm.quarantine.release"


def test_session_retry_exhaustion_records_and_dumps(tmp_path):
    """A channel burning its retry budget leaves retransmit events and a
    session.quarantine.enter, and dumps the ring."""
    from automerge_tpu import backend as Backend
    from automerge_tpu.sync_session import (
        BackendDriver,
        SessionConfig,
        SyncSession,
    )
    from automerge_tpu.testing.chaos import ManualClock

    clock = ManualClock()
    with enabled_flight(dump_dir=str(tmp_path)) as rec:
        rec.clear()
        session = SyncSession(
            BackendDriver(Backend.init()), clock=clock,
            rng=random.Random(0),
            config=SessionConfig(timeout=1.0, max_retries=2,
                                 backoff_base=0.1, backoff_cap=0.2),
        )
        # generate one payload frame; never ack it
        assert session.poll() is not None
        for _ in range(8):
            clock.advance(5.0)
            session.poll()
            if session.quarantined:
                break
        assert session.quarantined
        events = rec.snapshot()
    kinds = [e["event"] for e in events]
    assert kinds.count("session.retransmit") >= 2
    assert "session.quarantine.enter" in kinds
    # timestamps came from the injected (simulated) clock
    retransmit = next(e for e in events
                      if e["event"] == "session.retransmit")
    assert retransmit["t"] >= 5.0
    assert rec.dump_paths
    # release leaves its event
    with enabled_flight():
        session.release()
        assert get_flight().snapshot()[-1]["event"] == \
            "session.quarantine.release"


def test_engine_recompile_event_names_shape_bucket():
    with enabled_flight() as rec:
        rec.clear()
        farm = TpuDocFarm(2, capacity=32)
        from automerge_tpu.obs.metrics import enabled_metrics

        with enabled_metrics():
            buf = _stream(1, 4)[0]
            farm.apply_changes([[buf], [buf]])
        events = [e for e in rec.snapshot()
                  if e["event"] == "engine.recompile"]
    assert events, "fresh shapes compiled without a recompile event"
    assert events[0]["fields"]["fn"]
    assert events[0]["fields"]["shapes"]


# ---------------------------------------------------------------------- #
# acceptance shape: chaos+poison loadgen auto-dumps a usable timeline

@pytest.fixture(scope="module")
def poison_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("flight")
    farm = TpuDocFarm(8, capacity=128)
    gen = LoadGen(farm, LoadConfig(
        clients=24, docs=8, edits_per_client=2, ops_per_edit=3,
        spread=0.5, chaos=0.15, poison=0.25, seed=5,
        observability="full", flight_dir=str(tmp),
    ))
    report = gen.run()
    return {"report": report, "farm": farm}


def test_poison_run_quarantines_and_dumps(poison_run):
    report = poison_run["report"]
    assert report["quarantined_docs"] > 0
    assert report["flight_dumps"], "no flight dump despite quarantines"
    for path in report["flight_dumps"]:
        assert os.path.exists(path)


def test_poison_run_timeline_contains_the_quarantine_events(poison_run):
    """The acceptance criterion: the auto-dumped timeline contains the
    quarantine (and any watchdog) events that occurred, causally
    ordered, and renders."""
    path = poison_run["report"]["flight_dumps"][-1]
    events = load_jsonl(open(path, encoding="utf-8").read())
    kinds = {e["event"] for e in events}
    assert "farm.quarantine.enter" in kinds
    assert "batcher.flush" in kinds
    assert "flight.trigger" in kinds
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    quarantined_docs = {
        e["fields"]["doc"] for e in events
        if e["event"] == "farm.quarantine.enter"
    }
    assert quarantined_docs <= set(poison_run["farm"].quarantine) | \
        quarantined_docs  # every event names a doc the farm quarantined
    assert quarantined_docs & set(poison_run["farm"].quarantine)
    table = render_timeline(events)
    assert "farm.quarantine.enter" in table


def test_flight_cli_renders_dump(poison_run, capsys):
    from automerge_tpu.obs.__main__ import main

    path = poison_run["report"]["flight_dumps"][-1]
    assert main(["--flight", path]) == 0
    out = capsys.readouterr().out
    assert "farm.quarantine.enter" in out
    assert "seq" in out.splitlines()[0]
    # machine-readable variant
    assert main(["--flight", path, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert any(e["event"] == "flight.trigger" for e in payload["events"])
