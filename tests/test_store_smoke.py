"""Tier-1 smoke gate for the persistence tier (README "Persistence"),
mirroring the bench-smoke pattern: one `bench.py --store --quick` run
(the `make store` target) gated on machine-independent properties:

- the WAL-attached farm and the farm rebuilt from the on-disk log are
  byte-identical (change-log parity + heads + patches — the `parity`
  bit covers all three in the bench);
- the recovery report is clean: no torn bytes, no corrupt segments;
- full change accounting: every committed change is recovered (the WAL
  appended exactly docs x rounds records and the reopened store replays
  every one — no dryrun path can satisfy this);
- the group-commit policy actually fsynced (one barrier per round in
  quick mode's group_commit=1 config).

The >= 5x batched-hydration floor is a *full-run* gate (`bench.py
--store`, STORE_r01.json) — wall-clock ratios on a loaded CI host are
not machine-independent, so the quick twin only checks the honesty
invariants the speedup measurement rests on.
"""
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RESULT = None


def _smoke():
    global _RESULT
    if _RESULT is None:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py"),
             "--store", "--quick"],
            cwd=_REPO, capture_output=True, text=True, timeout=300,
        )
        lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
        assert lines, (proc.stdout[-2000:], proc.stderr[-2000:])
        result = json.loads(lines[-1])
        assert proc.returncode == 0, (result, proc.stderr[-2000:])
        _RESULT = result
    return _RESULT


def test_quick_gate_passes():
    result = _smoke()
    assert result["ok"], result


def test_hydrated_farm_is_bit_compatible():
    """The reopened farm's change log, heads and patches match the
    writer's — the persisted chunks are the reference-format buffers."""
    result = _smoke()
    assert result["parity"] is True, result


def test_recovery_report_is_clean():
    result = _smoke()
    rec = result["recovery"]
    assert rec["clean"] is True, rec
    assert rec["torn_bytes"] == 0, rec
    assert rec["corrupt_segments"] == 0, rec


def test_every_committed_change_is_accounted_for():
    """docs x rounds changes went through the WAL and every one came
    back on replay — the durability claim is end-to-end, not sampled."""
    result = _smoke()
    cfg = result["config"]
    expected = cfg["docs"] * cfg["rounds"]
    assert result["wal"]["append_records"] == expected, result
    assert result["recovery"]["records"] == expected, result
    assert result["recovery"]["changes"] == expected, result


def test_group_commit_fsynced_each_barrier():
    """Quick mode runs group_commit=1: one kernel fsync per apply round,
    proving the ack boundary actually reaches the durability seam."""
    result = _smoke()
    assert result["wal"]["fsyncs"] == result["config"]["rounds"], result


def test_wal_overhead_is_reported():
    """The WAL-attached run reports a finite overhead ratio vs the bare
    farm (the number the README's group-commit guidance is based on)."""
    result = _smoke()
    assert result["wal"]["overhead"] > 0, result
    assert result["wal"]["append_bytes"] > 0, result
