"""amtrace observability suite (automerge_tpu/obs + the profiling shim).

Covers the acceptance contract of the obs subsystem:
- span trees: nesting, flat aggregation by name, the tree/table renderers
  (including the previously untested PhaseProfile.table()), histogram
  bucket boundaries and p50/p95/p99 extraction, JSON-lines round-trip;
- ambient propagation: contextvars isolation across two interleaved
  contexts (the race the old module-global ambient slot had);
- disabled-mode cost: a disabled span/instrument performs one attribute
  test and touches neither the clock nor the ambient state;
- metrics registry: get-or-create by name (shared across modules), type
  conflicts, enable/disable/reset, rendering;
- integration: farm + engine + sync instrumentation counts real work, and
  the ``python -m automerge_tpu.obs`` CLI prints a span tree with
  percentiles plus a metrics table for a farm merge + sync round-trip.
"""
import contextvars
import json

import pytest

from automerge_tpu.obs import metrics as metrics_mod
from automerge_tpu.obs import spans as spans_mod
from automerge_tpu.obs.metrics import (
    MetricsRegistry,
    enabled_metrics,
    get_metrics,
)
from automerge_tpu.obs.spans import (
    BUCKET_FLOOR_S,
    NUM_BUCKETS,
    SpanNode,
    Trace,
    bucket_bounds,
    bucket_index,
)
from automerge_tpu.profiling import PhaseProfile, get_profile, use_profile


# ---------------------------------------------------------------------- #
# histogram buckets

def test_bucket_index_boundaries():
    # below the floor and zero clamp to the first bucket
    assert bucket_index(0.0) == 0
    assert bucket_index(BUCKET_FLOOR_S / 2) == 0
    assert bucket_index(BUCKET_FLOOR_S) == 0
    # an exact power-of-two boundary starts the NEXT bucket
    assert bucket_index(2 * BUCKET_FLOOR_S) == 1
    assert bucket_index(4 * BUCKET_FLOOR_S) == 2
    assert bucket_index(3.999 * BUCKET_FLOOR_S) == 1
    # far overflow clamps to the last bucket
    assert bucket_index(1e9) == NUM_BUCKETS - 1


def test_bucket_bounds_are_log2_spaced():
    for i in range(NUM_BUCKETS):
        lo, hi = bucket_bounds(i)
        assert hi == pytest.approx(2 * lo)
        assert lo == pytest.approx(BUCKET_FLOOR_S * (1 << i))
    # record() and bounds agree: a value lands inside its bucket
    node = SpanNode("x")
    node.record(5 * BUCKET_FLOOR_S)
    (b,) = node.buckets
    lo, hi = bucket_bounds(b)
    assert lo <= 5 * BUCKET_FLOOR_S < hi


def test_percentiles_read_bucket_upper_bounds():
    node = SpanNode("x")
    node.buckets = {0: 50, 5: 45, 10: 5}
    node.calls = 100
    assert node.percentile(0.50) == pytest.approx(bucket_bounds(0)[1])
    assert node.percentile(0.95) == pytest.approx(bucket_bounds(5)[1])
    assert node.percentile(0.99) == pytest.approx(bucket_bounds(10)[1])
    assert SpanNode("empty").percentile(0.5) is None


# ---------------------------------------------------------------------- #
# PhaseProfile flat views (previously untested)

def test_phase_profile_table_empty():
    assert PhaseProfile().table() == "(no phases recorded)"


def test_phase_profile_table_single_phase():
    prof = PhaseProfile()
    with prof.phase("only"):
        pass
    table = prof.table()
    assert "only" in table
    assert "100.0%" in table
    assert "x1" in table


def test_phase_profile_flat_views_aggregate_by_path():
    """The shim's flat views key by PATH: a nested "b" and a top-level
    "b" are different rows (the by-name views merged them, losing the
    distinction); top-level phases keep their bare names, so the bench's
    phase table is unchanged."""
    prof = PhaseProfile()
    with prof.phase("a"):
        with prof.phase("b"):
            pass
    with prof.phase("b"):
        pass
    assert prof.counts == {"a": 1, "a/b": 1, "b": 1}
    d = prof.as_dict()
    assert sorted(d) == ["a", "a/b", "b"]
    assert d["a/b"]["calls"] == 1
    assert d["b"]["total_s"] >= 0.0
    # the flat table carries every path whatever the nesting
    table = prof.table()
    assert "a/b" in table and "x1" in table


def test_table_keeps_sibling_same_name_span_counts():
    """The renderer regression behind the by-path change: two same-named
    spans under different parents used to merge into one row whose count
    (x2) lost the fact that each path ran once."""
    prof = PhaseProfile()
    with prof.phase("m"):
        with prof.phase("x"):
            pass
    with prof.phase("n"):
        with prof.phase("x"):
            pass
    table = prof.table()
    assert "m/x" in table and "n/x" in table
    assert "x2" not in table  # no silently merged row
    assert prof.counts["m/x"] == 1 and prof.counts["n/x"] == 1
    # percentages are computed over top-level spans only (children are
    # already inside their parents' wall time)
    assert prof.totals_by_path()["m"] >= prof.totals_by_path()["m/x"]


def test_phase_profile_is_a_trace_with_a_tree():
    prof = PhaseProfile()
    with prof.phase("outer"):
        with prof.phase("inner"):
            pass
    assert list(prof.root.children) == ["outer"]
    assert list(prof.root.children["outer"].children) == ["inner"]


# ---------------------------------------------------------------------- #
# span tree renderer + JSONL export

def _sample_trace():
    trace = Trace()
    with trace.span("outer"):
        with trace.span("inner"):
            pass
        with trace.span("inner"):
            pass
    with trace.span("solo"):
        pass
    return trace


def test_tree_table_renders_nesting_and_percentiles():
    table = _sample_trace().tree_table()
    lines = table.splitlines()
    assert "p50" in lines[0] and "p95" in lines[0] and "p99" in lines[0]
    assert any(line.startswith("outer") for line in lines)
    assert any(line.startswith("  inner") for line in lines)
    assert Trace().tree_table() == "(no spans recorded)"


def test_jsonl_round_trip_preserves_the_tree():
    trace = _sample_trace()
    text = trace.to_jsonl()
    # one JSON object per node, each with a path from the root
    entries = [json.loads(line) for line in text.splitlines()]
    assert {tuple(e["path"]) for e in entries} == {
        ("outer",), ("outer", "inner"), ("solo",)
    }
    rebuilt = Trace.from_jsonl(text)
    inner = rebuilt.root.children["outer"].children["inner"]
    assert inner.calls == 2
    assert inner.total_s == pytest.approx(
        trace.root.children["outer"].children["inner"].total_s
    )
    assert inner.buckets == trace.root.children["outer"].children["inner"].buckets
    # concatenated dumps merge (counts accumulate)
    doubled = Trace.from_jsonl(text + text)
    assert doubled.root.children["outer"].children["inner"].calls == 4


# ---------------------------------------------------------------------- #
# ambient propagation: contextvars, not a module global

def test_two_interleaved_contexts_do_not_cross_pollute():
    """The regression the old module-global `_current` had: two logical
    contexts (threads/tasks) interleaving use_profile must each see their
    own ambient profile."""
    seen = {}

    def work(tag, prof):
        with use_profile(prof):
            yield  # suspension point: the other context installs ITS profile
            seen[tag] = get_profile()
            with get_profile().phase(tag):
                pass
            yield

    prof_a, prof_b = PhaseProfile(), PhaseProfile()
    ctx_a, ctx_b = contextvars.copy_context(), contextvars.copy_context()
    gen_a, gen_b = work("a", prof_a), work("b", prof_b)
    ctx_a.run(next, gen_a)  # a installs prof_a
    ctx_b.run(next, gen_b)  # b installs prof_b (clobbers a module global)
    ctx_a.run(next, gen_a)  # a resumes AFTER b installed
    ctx_b.run(next, gen_b)
    for ctx, gen in ((ctx_a, gen_a), (ctx_b, gen_b)):
        with pytest.raises(StopIteration):
            ctx.run(next, gen)  # finish in-context so use_profile unwinds
    assert seen["a"] is prof_a
    assert seen["b"] is prof_b
    assert list(prof_a.counts) == ["a"]
    assert list(prof_b.counts) == ["b"]


def test_ambient_default_is_a_disabled_trace():
    ambient = get_profile()
    assert isinstance(ambient, Trace)
    assert ambient.enabled is False
    # recording through the disabled ambient is a no-op
    with ambient.phase("ignored"):
        pass
    assert ambient.root.children == {}


def test_use_profile_restores_previous_ambient():
    prof = PhaseProfile()
    before = get_profile()
    with use_profile(prof):
        assert get_profile() is prof
    assert get_profile() is before


# ---------------------------------------------------------------------- #
# disabled-mode cost: one attribute test, nothing else

def test_disabled_span_is_attribute_test_only(monkeypatch):
    trace = Trace(enabled=False)

    def boom(*_):
        raise AssertionError("disabled span touched the clock/ambient state")

    monkeypatch.setattr(spans_mod.time, "perf_counter", boom)

    class _Poisoned:
        def get(self):
            raise AssertionError("disabled span read the ambient state")

        def set(self, _):
            raise AssertionError("disabled span wrote the ambient state")

    monkeypatch.setattr(spans_mod, "_STATE", _Poisoned())
    with trace.span("x"):
        pass
    assert trace.root.children == {}


def test_disabled_instruments_do_no_work(monkeypatch):
    reg = MetricsRegistry()  # disabled by default
    c, g, h = reg.counter("c"), reg.gauge("g"), reg.histogram("h")
    monkeypatch.setattr(
        metrics_mod, "bucket_index",
        lambda *_: (_ for _ in ()).throw(AssertionError("bucketed while off")),
    )
    c.inc(5)
    g.set(3.0)
    h.observe(1.0)
    assert c.value == 0
    assert g.value == 0.0
    assert h.count == 0 and h.buckets == {}


# ---------------------------------------------------------------------- #
# metrics registry

def test_registry_get_or_create_shares_instruments_by_name():
    reg = MetricsRegistry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_sequential_and_batched_sync_share_instruments():
    """sync.py and tpu/sync_farm.py fetch the same named counters from the
    process-wide registry: one set of totals whichever driver runs."""
    import automerge_tpu.sync as seq
    import automerge_tpu.tpu.sync_farm as batched

    assert seq._M_MSGS_GEN is batched._M_MSGS_GEN
    assert seq._M_BLOOM_PROBES is batched._M_BLOOM_PROBES


def test_registry_enable_reset_and_render():
    reg = MetricsRegistry()
    c = reg.counter("hits", "how many")
    h = reg.histogram("lat")
    reg.enable()
    c.inc(3)
    h.observe(0.5)
    assert c.value == 3 and h.count == 1
    d = reg.as_dict()
    assert d["hits"] == {"type": "counter", "value": 3}
    assert d["lat"]["type"] == "histogram" and d["lat"]["count"] == 1
    table = reg.table()
    assert "hits" in table and "p50" in table
    reg.reset()
    assert c.value == 0 and h.count == 0 and h.buckets == {}
    # late-created instruments inherit the enabled state
    late = reg.counter("late")
    late.inc()
    assert late.value == 1
    reg.disable()
    late.inc()
    assert late.value == 1


def test_reset_is_uniform_across_instrument_types():
    """Satellite regression (ISSUE 8): registry.reset() delegates to each
    instrument's own reset(), so a Counter's zero, a Gauge's zero and a
    Histogram's empty-percentile state (count 0, percentile None,
    exemplars cleared) can never drift apart mid-run."""
    reg = MetricsRegistry()
    c, g, h = reg.counter("c"), reg.gauge("g"), reg.histogram("h")
    reg.enable()
    c.inc(7)
    g.set(3.5)
    h.observe(0.25, exemplar="t1")
    assert h.snapshot()["p50"] is not None
    reg.reset()
    assert c.snapshot() == {"type": "counter", "value": 0}
    assert g.snapshot() == {"type": "gauge", "value": 0.0}
    snap = h.snapshot()
    assert snap["count"] == 0 and snap["sum"] == 0.0
    assert snap["p50"] is None and snap["p95"] is None and snap["p99"] is None
    assert "exemplars" not in snap and h.exemplars == {}
    # instruments stay enabled across a reset (reset zeroes, not disables)
    c.inc()
    assert c.value == 1


def test_quarantine_gauge_consistent_with_counters_after_midrun_reset():
    """The concrete reset-consistency case from PR 6: the channel-
    quarantine active gauge is derived from the entered/released counters.
    After a mid-run registry.reset(), the gauge and both counters must
    zero together, and the next derivation must keep gauge ==
    max(0, entered - released) instead of going negative or stale."""
    from automerge_tpu import sync_session as ss

    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        ss._M_CHQ_ENTERED.inc()
        ss._set_active_quarantined()
        assert ss._M_CHQ_ACTIVE.value == 1
        reg.reset()
        # uniform reset: counters AND the derived gauge all read zero
        assert ss._M_CHQ_ENTERED.value == 0
        assert ss._M_CHQ_RELEASED.value == 0
        assert ss._M_CHQ_ACTIVE.value == 0
        # a release after the reset re-derives consistently (clamped)
        ss._M_CHQ_RELEASED.inc()
        ss._set_active_quarantined()
        assert ss._M_CHQ_ACTIVE.value == 0
        assert ss._M_CHQ_ACTIVE.value == max(
            0, ss._M_CHQ_ENTERED.value - ss._M_CHQ_RELEASED.value
        )
    reg.reset()


def test_histogram_exemplars_land_in_their_buckets():
    """Exemplar correctness: the exemplar returned for a quantile is the
    trace id of an observation that really landed in that quantile's
    bucket."""
    from automerge_tpu.obs.metrics import Histogram

    h = Histogram("lat")
    h.enabled = True
    values = [2e-6, 5e-5, 1e-3, 0.5]  # four distinct log2 buckets
    by_bucket = {}
    for i, v in enumerate(values):
        h.observe(v, exemplar=f"t{i}")
        by_bucket[bucket_index(v)] = f"t{i}"
    for q in (0.50, 0.95, 0.99):
        b = h.percentile_bucket(q)
        assert h.exemplar_for(q) == by_bucket[b]
    # the p99 exemplar is the largest observation's trace, and that
    # observation's value really buckets where the p99 reads from
    assert h.exemplar_for(0.99) == "t3"
    assert bucket_index(0.5) == h.percentile_bucket(0.99)
    # snapshots carry the bucket -> exemplar map
    assert h.snapshot()["exemplars"][str(bucket_index(0.5))] == "t3"


def test_enabled_metrics_context_restores_state():
    reg = MetricsRegistry()
    with enabled_metrics(reg):
        assert reg.enabled
    assert not reg.enabled
    reg.enable()
    with enabled_metrics(reg):
        pass
    assert reg.enabled  # already-enabled registries stay enabled


# ---------------------------------------------------------------------- #
# integration: farm + engine instrumentation

def _stream(rounds, ops, actor="aaaaaaaa", seed=0):
    from automerge_tpu.obs.__main__ import _change_stream

    return _change_stream(actor, rounds, ops, seed=seed)


def test_farm_phases_flow_through_the_shim():
    """The bench's pre-existing call pattern: PhaseProfile + use_profile
    around farm.apply_changes keeps producing the phase breakdown."""
    from automerge_tpu.tpu.farm import TpuDocFarm

    farm = TpuDocFarm(2, capacity=32)
    buf = _stream(1, 4)[0]
    prof = PhaseProfile()
    with use_profile(prof):
        farm.apply_changes([[buf], [buf]])
    d = prof.as_dict()
    for phase in ("decode", "gate_verdicts", "transcode_columns",
                  "gate+transcode", "pack", "device_dispatch",
                  "visibility", "patch_assembly"):
        assert phase in d, phase
        assert d[phase]["calls"] == 1


def test_farm_and_engine_metrics_count_real_work():
    from automerge_tpu.tpu.farm import TpuDocFarm

    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        farm = TpuDocFarm(5, capacity=96)
        for buf in _stream(2, 4):
            farm.apply_changes([[buf]] * 5)
    # every op became exactly one dense row: 5 docs x 2 rounds x 4 ops
    assert reg.counter("farm.rows.transcoded").value == 40
    # same-width docs => zero padding, occupancy 1.0 observed per call
    assert reg.counter("farm.rows.padding").value == 0
    assert reg.gauge("farm.pad_waste_ratio").value == 0.0
    assert reg.histogram("farm.batch.occupancy").count == 2
    assert reg.counter("farm.changes.applied").value == 10
    # each call dispatches one merge, one (version-memoised) visibility
    # program, and one scoped readback gather — never more, however many
    # docs/slots need patches
    dispatches = reg.counter("engine.device.dispatches").value
    assert dispatches == 6
    hits = reg.counter("engine.jit.cache_hits").value
    recompiles = reg.counter("engine.jit.recompiles").value
    assert hits + recompiles == dispatches
    assert recompiles >= 1  # fresh shapes compiled at least once


def test_farm_pad_waste_with_uneven_docs():
    from automerge_tpu.tpu.farm import TpuDocFarm

    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        farm = TpuDocFarm(2, capacity=32)
        buf = _stream(1, 4)[0]
        # doc 1 contributes zero rows: with paged storage it does not ride
        # the dispatch at all, so an idle doc is no longer counted as pad
        # waste (the old dense engine padded every doc to the batch width)
        farm.apply_changes([[buf], []])
    assert reg.counter("farm.rows.transcoded").value == 4
    assert reg.counter("farm.rows.padding").value == 0
    assert reg.gauge("farm.pad_waste_ratio").value == pytest.approx(0.0)
    # genuinely ragged ACTIVE docs still count: 4-row and 1-row docs pack
    # to width 4, wasting 3 of 8 active cells
    reg.reset()
    with enabled_metrics():
        farm = TpuDocFarm(2, capacity=32)
        b4 = _stream(1, 4)[0]
        b1 = _stream(1, 1, actor="bbbbbbbb")[0]
        farm.apply_changes([[b4], [b1]])
    assert reg.counter("farm.rows.transcoded").value == 5
    assert reg.counter("farm.rows.padding").value == 3
    assert reg.gauge("farm.pad_waste_ratio").value == pytest.approx(3 / 8)
    # the slab-level figure of merit that supersedes pad waste: page
    # occupancy of the allocated slab pages
    assert reg.gauge("farm.pages.occupancy").value > 0


def test_gate_deferral_and_prevalidation_abort_metrics():
    from automerge_tpu.columnar import encode_change
    from automerge_tpu.tpu.farm import TpuDocFarm

    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        farm = TpuDocFarm(1, capacity=32)
        stream = _stream(2, 2)
        # round 2 without round 1: causally unready, the gate defers it
        farm.apply_changes([[stream[1]]])
        assert reg.counter("farm.gate.deferrals").value == 1
        # an op counter beyond the merge-key packing range aborts the call
        big = encode_change({
            "actor": "bbbbbbbb", "seq": 1, "startOp": 1 << 24, "time": 0,
            "deps": [], "ops": [{"action": "set", "obj": "_root", "key": "k",
                                 "datatype": "uint", "value": 1, "pred": []}],
        })
        with pytest.raises(ValueError):
            farm.apply_changes([[big]], isolation="batch")
        assert reg.counter("farm.prevalidation.aborts").value == 1
        # per-doc isolation routes the same failure through the
        # error_kind-dimensioned quarantine cause family instead
        farm.apply_changes([[big]])
        assert reg.counter("farm.quarantine.causes.packing").value == 1
        assert reg.counter("farm.prevalidation.aborts").value == 1


# ---------------------------------------------------------------------- #
# integration: sequential sync protocol metrics

def test_sync_round_trip_metrics():
    import automerge_tpu.backend as Backend
    from automerge_tpu.sync import (
        generate_sync_message,
        init_sync_state,
        receive_sync_message,
    )

    b1, b2 = Backend.init(), Backend.init()
    b1, _ = Backend.apply_changes(b1, _stream(2, 4, actor="aaaaaaaa"))
    b2, _ = Backend.apply_changes(b2, _stream(2, 4, actor="cccccccc", seed=7))

    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        s1, s2 = init_sync_state(), init_sync_state()
        for _ in range(10):
            s1, m1 = generate_sync_message(b1, s1)
            if m1 is not None:
                b2, s2, _ = receive_sync_message(b2, s2, m1)
            s2, m2 = generate_sync_message(b2, s2)
            if m2 is not None:
                b1, s1, _ = receive_sync_message(b1, s1, m2)
            if m1 is None and m2 is None:
                break
    assert Backend.get_heads(b1) == Backend.get_heads(b2)
    gen = reg.counter("sync.messages.generated").value
    assert gen >= 2
    # every generated message was delivered in this loop
    assert reg.counter("sync.messages.received").value == gen
    assert reg.counter("sync.bytes.sent").value == \
        reg.counter("sync.bytes.received").value > 0
    assert reg.counter("sync.changes.sent").value == \
        reg.counter("sync.changes.received").value == 4
    assert reg.counter("sync.bloom.probes").value > 0


# ---------------------------------------------------------------------- #
# the obs CLI

def test_cli_prints_span_tree_and_metrics_table(capsys):
    from automerge_tpu.obs.__main__ import main

    assert main(["--docs", "2", "--rounds", "1", "--ops", "4"]) == 0
    out = capsys.readouterr().out
    assert "== spans ==" in out and "== metrics ==" in out
    assert "p50" in out and "p95" in out and "p99" in out
    assert "merge" in out and "sync" in out
    # farm phases appear nested under the workload spans
    assert "device_dispatch" in out
    # the metric catalog's headline entries are populated
    assert "engine.device.dispatches" in out
    assert "sync.messages.generated" in out


def test_cli_dump_and_trace_render_round_trip(tmp_path, capsys):
    from automerge_tpu.obs.__main__ import main

    dump = tmp_path / "trace.jsonl"
    assert main(["--docs", "2", "--rounds", "1", "--ops", "4",
                 "--dump", str(dump)]) == 0
    capsys.readouterr()
    # rendering a dump runs no workload (and needs no device layer)
    assert main(["--trace", str(dump)]) == 0
    out = capsys.readouterr().out
    assert "merge" in out and "p50" in out
    assert "== metrics ==" not in out


def test_cli_json_output(capsys):
    from automerge_tpu.obs.__main__ import main

    assert main(["--json", "--docs", "2", "--rounds", "1", "--ops", "4"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert {s["name"] for s in payload["spans"]} == {"merge", "sync"}
    assert "engine.device.dispatches" in payload["metrics"]
