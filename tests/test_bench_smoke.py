"""Tier-1 perf smoke gate for the incremental-readback / vectorized-
assembly work (ISSUE 4): the ``visibility + patch_assembly`` share of
end-to-end apply_changes time must stay under the pinned threshold.

BENCH_r05 measured that tail at >65% of wall time (9.79s + 8.31s of
26.7s) because every call re-read and re-walked the whole farm state on
the host. The host row mirror + scoped readback + column-mask assembly
keep it a minority share; this test (and `make bench-smoke`, which runs
the same check at a larger config via ``bench.py --quick``) fails any
change that reintroduces O(whole farm) host work per call.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench

# generous vs the post-fix steady state (~0.4 at the delta config) but
# below the regression signature (tail_share -> 1 as host work returns to
# O(whole farm) per call)
MAX_TAIL_SHARE = 0.55

# gate_verdicts + transcode_columns + gate+transcode + patch_assembly:
# the phases the columnar causal gate + device-emitted patch columns
# retired from per-change host Python (BENCH_r07 measures ~0.03 at the
# delta config; a revert to the scalar chain pushes this past 0.5)
MAX_GATE_SHARE = 0.45

_RESULT = None


def _smoke():
    global _RESULT
    if _RESULT is None:
        _RESULT = bench.bench_smoke(
            num_docs=48, seed_rounds=4, seed_ops=32, delta_rounds=4,
            delta_ops=4,
        )
    return _RESULT


def test_visibility_assembly_share_stays_bounded():
    result = _smoke()
    assert result["ops_per_sec"] > 0
    assert result["tail_share"] <= MAX_TAIL_SHARE, (
        f"visibility+patch_assembly is {result['tail_share']:.0%} of the "
        f"delta-round time (limit {MAX_TAIL_SHARE:.0%}): the incremental "
        f"readback / vectorized assembly path has regressed; phases: "
        f"{result['phases']}"
    )


def test_gate_assembly_share_stays_bounded():
    """The columnar-gate regression signature: per-change Python creeping
    back into gate/transcode or patch assembly drags their combined share
    of the delta-round time back toward the scalar chain's profile."""
    result = _smoke()
    assert result["gate_share"] <= MAX_GATE_SHARE, (
        f"gate+transcode+patch_assembly is {result['gate_share']:.0%} of "
        f"the delta-round time (limit {MAX_GATE_SHARE:.0%}): the columnar "
        f"gate / device patch-column path has regressed; phases: "
        f"{result['phases']}"
    )


def test_gate_is_columnar_with_device_patch_columns():
    """Machine-independent row-count properties: deliveries ride the
    columnar verdict path (no oracle re-routes on a clean workload) and
    patch emission happens on device."""
    result = _smoke()
    assert result["vector_changes"] > 0, result
    assert result["gate_oracle_docs"] == 0, result
    assert result["transcode_oracle_docs"] == 0, result
    assert result["device_patch_columns"] > 0, result


def test_readback_is_incremental():
    """Steady-state delta rounds must serve most rows from the host
    visibility cache: a revert to full-state readback collapses
    rows_skipped to ~0 and fails here whatever the machine speed."""
    result = _smoke()
    assert result["readback_rows"] > 0
    assert result["readback_rows_skipped"] > result["readback_rows"], result


def test_decode_cache_absorbs_the_fanout():
    """The same change fanned across the batch must be parsed ~once, not
    once per doc: decode-cache hits dominate misses."""
    result = _smoke()
    assert result["decode_cache_hits"] > result["decode_cache_misses"], result
