"""Pallas Bloom kernels must be bit-identical to the XLA reference kernels
in sync_batch.py (which are themselves wire-format-identical to
backend/sync.js — see test_sync_batch.py). Runs in interpreter mode on CPU."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from automerge_tpu.tpu import sync_batch  # noqa: E402
from automerge_tpu.tpu.pallas_kernels import bloom_build, bloom_query  # noqa: E402

INTERPRET = jax.default_backend() != "tpu"


def random_xyz(rng, batch, entries):
    return jnp.asarray(
        rng.integers(0, 2**32, size=(batch, entries, 3), dtype=np.uint32)
    )


class TestPallasBloom:
    def test_build_matches_xla(self):
        rng = np.random.default_rng(0)
        xyz = random_xyz(rng, batch=5, entries=12)
        counts = jnp.asarray([12, 7, 1, 0, 3], jnp.int32)
        num_words = 16

        ref_words, ref_modulo = sync_batch.build_filters(xyz, counts, num_words)
        got_words, got_modulo = bloom_build(
            xyz, counts, num_words, interpret=INTERPRET
        )
        np.testing.assert_array_equal(np.asarray(got_modulo), np.asarray(ref_modulo))
        np.testing.assert_array_equal(np.asarray(got_words), np.asarray(ref_words))

    def test_query_matches_xla(self):
        rng = np.random.default_rng(1)
        batch, entries, queries = 4, 10, 9
        xyz = random_xyz(rng, batch, entries)
        counts = jnp.asarray([10, 5, 0, 2], jnp.int32)
        num_words = 8
        words, modulo = sync_batch.build_filters(xyz, counts, num_words)

        # half the queries are members, half are random
        member = np.asarray(xyz)[:, :queries // 2]
        other = rng.integers(0, 2**32, size=(batch, queries - queries // 2, 3),
                             dtype=np.uint32)
        query = jnp.asarray(np.concatenate([member, other], axis=1))

        ref = sync_batch.query_filters(words, modulo, counts, query)
        got = bloom_query(words, modulo, counts, query, interpret=INTERPRET)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_members_always_contained(self):
        rng = np.random.default_rng(2)
        xyz = random_xyz(rng, batch=3, entries=20)
        counts = jnp.asarray([20, 20, 20], jnp.int32)
        num_words = 16
        words, modulo = bloom_build(xyz, counts, num_words, interpret=INTERPRET)
        got = bloom_query(words, modulo, counts, xyz, interpret=INTERPRET)
        assert bool(jnp.all(got))

    def test_multi_tile_grid_matches_xla(self):
        """Entry/query/word counts that exceed one grid tile (the VMEM-bounded
        path real replica-farm sizes take)."""
        from automerge_tpu.tpu import pallas_kernels as pk

        rng = np.random.default_rng(3)
        entries = pk._ENTRY_TILE + 37
        num_words = pk._WORD_TILE + pk._LANES
        queries = pk._QUERY_TILE + 19
        xyz = random_xyz(rng, batch=2, entries=entries)
        counts = jnp.asarray([entries, entries - 50], jnp.int32)

        ref_words, ref_modulo = sync_batch.build_filters(xyz, counts, num_words)
        got_words, got_modulo = bloom_build(xyz, counts, num_words, interpret=INTERPRET)
        np.testing.assert_array_equal(np.asarray(got_modulo), np.asarray(ref_modulo))
        np.testing.assert_array_equal(np.asarray(got_words), np.asarray(ref_words))

        member = np.asarray(xyz)[:, : queries // 2]
        other = rng.integers(
            0, 2**32, size=(2, queries - queries // 2, 3), dtype=np.uint32
        )
        query = jnp.asarray(np.concatenate([member, other], axis=1))
        ref = sync_batch.query_filters(ref_words, ref_modulo, counts, query)
        got = bloom_query(got_words, got_modulo, counts, query, interpret=INTERPRET)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
