"""Unit suite for the sync supervision layer (automerge_tpu/sync_session.py):
frame codec, stop-and-wait seq/ack, retransmission with backoff, channel
quarantine, peer-restart re-handshake, the convergence watchdog, and
resumable session state. Everything runs on a ManualClock with seeded RNGs
— no wall time, no sleeps."""
import random

import pytest

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu import sync as Sync
from automerge_tpu.errors import (
    ChannelQuarantinedError,
    RetryExhaustedError,
    SyncFrameError,
    SyncProtocolError,
)
from automerge_tpu.obs.metrics import enabled_metrics, get_metrics
from automerge_tpu.sync_session import (
    FLAG_PAYLOAD,
    FLAG_V2,
    BackendDriver,
    SessionConfig,
    SyncSession,
    decode_frame,
    encode_frame,
)
from automerge_tpu.testing.chaos import ManualClock


def make_backend(actor, keys=()):
    backend = Backend.init()
    state = None
    for i, key in enumerate(keys):
        buf = am.encode_change({
            "actor": actor, "seq": i + 1, "startOp": i + 1, "time": 0,
            "deps": Backend.get_heads(backend),
            "ops": [{"action": "set", "obj": "_root", "key": key,
                     "datatype": "uint", "value": i, "pred": []}],
        })
        backend, _ = Backend.apply_changes(backend, [buf])
    return backend


def make_pair(a_keys=("x",), b_keys=(), *, config=None, clock=None,
              seed_a=1, seed_b=2):
    clock = clock or ManualClock()
    da = BackendDriver(make_backend("aaaaaaaa", a_keys))
    db = BackendDriver(make_backend("bbbbbbbb", b_keys))
    sa = SyncSession(da, clock=clock, rng=random.Random(seed_a), config=config)
    sb = SyncSession(db, clock=clock, rng=random.Random(seed_b), config=config)
    return clock, sa, sb


def drive(clock, sa, sb, rounds=30, step=0.05):
    """Lossless shuttle: poll both, deliver both, tick the clock."""
    for _ in range(rounds):
        fa, fb = sa.poll(), sb.poll()
        if fa is not None:
            sb.handle(fa)
        if fb is not None:
            sa.handle(fb)
        if fa is None and fb is None and sa.driver.heads() == sb.driver.heads():
            return True
        clock.advance(step if (fa or fb) else 0.26)
    return sa.driver.heads() == sb.driver.heads()


# ---------------------------------------------------------------------- #
# frame codec


class TestFrameCodec:
    def test_round_trip_payload(self):
        frame = encode_frame(7, 3, 2, b"payload-bytes")
        assert decode_frame(frame) == {
            "epoch": 7, "seq": 3, "ack": 2, "flags": FLAG_PAYLOAD,
            "payload": b"payload-bytes",
        }

    def test_round_trip_ack_only(self):
        frame = encode_frame(9, 0, 5, None)
        assert decode_frame(frame) == {
            "epoch": 9, "seq": 0, "ack": 5, "flags": 0, "payload": None,
        }

    def test_v2_flag_rides_the_flags_byte(self):
        frame = encode_frame(7, 3, 2, b"payload-bytes", FLAG_V2)
        decoded = decode_frame(frame)
        assert decoded["flags"] == FLAG_PAYLOAD | FLAG_V2
        assert decoded["payload"] == b"payload-bytes"
        ack = decode_frame(encode_frame(9, 0, 5, None, FLAG_V2))
        assert ack["flags"] == FLAG_V2 and ack["payload"] is None

    @pytest.mark.parametrize("bit", [8, 40, 64, 200])
    def test_corrupt_frame_rejected_by_checksum(self, bit):
        frame = bytearray(encode_frame(1, 1, 0, b"payload-bytes"))
        bit %= len(frame) * 8
        frame[bit >> 3] ^= 1 << (bit & 7)
        with pytest.raises(SyncFrameError):
            decode_frame(bytes(frame))

    def test_truncated_frame_rejected(self):
        frame = encode_frame(1, 1, 0, b"payload-bytes")
        for keep in (0, 1, 3, len(frame) // 2, len(frame) - 1):
            with pytest.raises(SyncFrameError):
                decode_frame(frame[:keep])

    def test_wrong_type_rejected(self):
        with pytest.raises(SyncFrameError):
            decode_frame(b"\x42" + encode_frame(1, 1, 0, b"x")[1:])

    def test_frame_error_is_sync_protocol_error(self):
        assert issubclass(SyncFrameError, SyncProtocolError)
        assert issubclass(RetryExhaustedError, SyncProtocolError)
        assert issubclass(ChannelQuarantinedError, SyncProtocolError)


# ---------------------------------------------------------------------- #
# stop-and-wait + retransmission


class TestSupervision:
    def test_lossless_convergence_and_inner_bytes_unchanged(self):
        """On a clean transport the inner payloads are byte-identical to
        the unsupervised protocol's messages (wire compatibility)."""
        clock, sa, sb = make_pair(("x", "y"), ())
        ref_a = BackendDriver(make_backend("aaaaaaaa", ("x", "y")))
        ref_state = Sync.init_sync_state()
        frame = sa.poll()
        ref_state, ref_msg = Sync.generate_sync_message(ref_a.backend, ref_state)
        assert decode_frame(frame)["payload"] == ref_msg
        sb.handle(frame)
        assert drive(clock, sa, sb)
        assert sa.driver.heads() == sb.driver.heads()

    def test_stop_and_wait_single_outstanding_frame(self):
        clock, sa, sb = make_pair()
        first = sa.poll()
        assert first is not None and sa.pending is not None
        # before the deadline, no retransmission and no new payload
        assert sa.poll() is None
        clock.advance(0.5)
        assert sa.poll() is None

    def test_timeout_retransmits_same_seq_with_backoff(self):
        clock, sa, sb = make_pair()
        first = decode_frame(sa.poll())
        clock.advance(1.01)  # past the 1.0s default timeout
        second = decode_frame(sa.poll())
        assert second["seq"] == first["seq"]
        assert second["payload"] == first["payload"]
        assert sa.stats["retransmits"] == 1
        assert sa.stats["timeouts"] == 1
        # the next deadline includes timeout + jittered backoff
        assert sa.pending["deadline"] >= clock.now() + 1.0

    def test_ack_clears_pending(self):
        clock, sa, sb = make_pair()
        frame = sa.poll()
        sb.handle(frame)
        reply = sb.poll()  # carries ack for sa's frame
        sa.handle(reply)
        assert sa.pending is None

    def test_duplicate_frame_is_idempotent_noop(self):
        clock, sa, sb = make_pair(("x",), ())
        frame = sa.poll()
        sb.handle(frame)
        heads_before = sb.driver.heads()
        saved = Backend.save(sb.driver.backend)
        state_before = dict(sb.state)
        assert sb.handle(frame) is None  # exact duplicate
        assert sb.stats["dup_dropped"] == 1
        assert sb.driver.heads() == heads_before
        assert Backend.save(sb.driver.backend) == saved
        assert sb.state == state_before
        assert sb.ack_owed  # the peer is re-acked so it stops retransmitting

    def test_rejected_payload_is_not_acked(self):
        """An envelope that decodes but carries a corrupt inner payload
        must not advance the seq watermark: the peer's intact
        retransmission has to get a clean retry."""
        clock, sa, sb = make_pair(("x",), ())
        frame = sa.poll()
        inner = decode_frame(frame)
        # a sync-typed payload whose heads count never terminates: the
        # inner decode raises, so the envelope must not be acked
        bad_payload = b"\x42" + b"\xff" * 6
        bad_frame = encode_frame(inner["epoch"], inner["seq"], 0, bad_payload)
        with pytest.raises(SyncProtocolError):
            sb.handle(bad_frame)
        assert sb.last_seen == 0
        assert not sb.ack_owed
        # the intact frame still applies afterwards
        assert sb.handle(frame) is not None or sb.last_seen == inner["seq"]

    def test_retry_budget_exhaustion_quarantines_channel(self):
        config = SessionConfig(timeout=1.0, max_retries=2, backoff_cap=0.1)
        clock, sa, sb = make_pair(config=config)
        assert sa.poll() is not None
        for _ in range(3):
            clock.advance(20.0)
            sa.poll()
        assert sa.quarantined
        assert isinstance(sa.quarantine_cause, RetryExhaustedError)
        assert sa.poll() is None  # quarantined channels emit nothing
        # incoming traffic is shed, not raised
        frame = sb.poll()
        assert sa.handle(frame) is None
        assert sa.stats["shed"] == 1
        with pytest.raises(ChannelQuarantinedError):
            sa.check()
        # release restores service with a fresh budget
        sa.release()
        assert not sa.quarantined
        assert sa.poll() is not None

    def test_backoff_is_deterministic_under_seeded_rng(self):
        def run(seed):
            config = SessionConfig(timeout=1.0, max_retries=6)
            clock, sa, _sb = make_pair(config=config, seed_a=seed, seed_b=99)
            sa.poll()
            deadlines = []
            for _ in range(4):
                clock.advance(1000.0)
                sa.poll()
                deadlines.append(sa.pending["deadline"] - clock.now())
            return deadlines

        assert run(5) == run(5)
        assert run(5) != run(6)

    def test_backoff_grows_toward_cap(self):
        config = SessionConfig(timeout=1.0, max_retries=20,
                               backoff_base=0.5, backoff_cap=8.0)
        clock, sa, _sb = make_pair(config=config)
        sa.poll()
        for attempt in range(1, 10):
            clock.advance(1e6)
            sa.poll()
            delay = sa.pending["deadline"] - clock.now() - config.timeout
            ceiling = min(config.backoff_cap,
                          config.backoff_base * 2 ** (attempt - 1))
            assert 0.0 <= delay <= ceiling


# ---------------------------------------------------------------------- #
# peer restart + resumable sessions


class TestRestartAndResume:
    def test_peer_restart_triggers_clean_rehandshake(self):
        clock, sa, sb = make_pair(("x", "y"), ())
        assert drive(clock, sa, sb)
        # b restarts with nothing: fresh doc, fresh session, new epoch
        db = BackendDriver(Backend.init())
        sb2 = SyncSession(db, clock=clock, rng=random.Random(77))
        assert drive(clock, sa, sb2)
        assert sa.stats["peer_restarts"] == 1
        assert sa.driver.heads() == sb2.driver.heads()

    def test_save_restore_round_trips_session_fields(self):
        clock, sa, sb = make_pair(("x",), ())
        assert drive(clock, sa, sb)
        blob = sa.save()
        restored = SyncSession.restore(blob, sa.driver, clock=clock,
                                       rng=random.Random(9))
        assert restored.epoch == sa.epoch
        assert restored.seq_out == sa.seq_out
        assert restored.last_seen == sa.last_seen
        assert restored.peer_epoch == sa.peer_epoch
        assert restored.state["sharedHeads"] == sa.state["sharedHeads"]

    def test_restored_session_resumes_without_restart_detection(self):
        """A process restart with persisted state is seamless: the peer
        sees the same epoch and the same seq continuity."""
        clock, sa, sb = make_pair(("x",), ())
        assert drive(clock, sa, sb)
        blob = sa.save()
        sa2 = SyncSession.restore(blob, sa.driver, clock=clock,
                                  rng=random.Random(9))
        # new local edit after the resume
        buf = am.encode_change({
            "actor": "aaaaaaaa", "seq": 2, "startOp": 2, "time": 0,
            "deps": sa.driver.heads(),
            "ops": [{"action": "set", "obj": "_root", "key": "z",
                     "datatype": "uint", "value": 9, "pred": []}],
        })
        sa2.driver.backend, _ = Backend.apply_changes(sa2.driver.backend, [buf])
        assert drive(clock, sa2, sb)
        assert sb.stats["peer_restarts"] == 0
        assert sa2.driver.heads() == sb.driver.heads()

    def test_legacy_blob_restores_with_fresh_epoch(self):
        state = Sync.init_sync_state()
        legacy = Sync.encode_sync_state(state)  # no session extension
        restored = SyncSession.restore(
            legacy, BackendDriver(Backend.init()),
            clock=ManualClock(), rng=random.Random(3),
        )
        assert restored.seq_out == 0
        assert restored.last_seen == 0
        assert restored.peer_epoch is None
        assert restored.epoch != 0


# ---------------------------------------------------------------------- #
# convergence watchdog


class TestWatchdog:
    def _stalled_pair(self, config=None):
        """A pair wedged the pathological way: every one of a's changes is
        wrongly marked as already sent (the observable end-state of a
        Bloom false-positive loop under loss), so the inner protocol
        exchanges heads forever without ever attaching the changes. The
        peer is non-empty: an empty peer's heads=[] message triggers the
        reference's own sentHashes reset (sync.js:435), masking the
        stall."""
        config = config or SessionConfig(watchdog_rounds=3)
        clock, sa, sb = make_pair(("x", "y", "z"), ("b0",), config=config)
        hashes = [
            am.decode_change(c)["hash"]
            for c in Backend.get_all_changes(sa.driver.backend)
        ]
        sa.state = dict(sa.state, sentHashes={h: True for h in hashes})
        return clock, sa, sb

    def test_stalled_pair_escalates_and_recovers(self):
        clock, sa, sb = self._stalled_pair()
        assert drive(clock, sa, sb, rounds=120)
        assert sa.stats["stalls"] + sb.stats["stalls"] >= 1
        assert sa.stats["escalations"] + sb.stats["escalations"] >= 1
        assert sa.driver.heads() == sb.driver.heads()

    def test_progress_resets_watchdog(self):
        clock, sa, sb = make_pair(("x", "y"), ())
        assert drive(clock, sa, sb)
        assert sa.stats["stalls"] == 0
        assert sb.stats["stalls"] == 0
        assert sa._wd_rounds == 0

    def test_full_reset_stage_fires_after_rebuild_fails(self):
        """Stage 1 clears sentHashes, which heals the injected stall — so
        to reach stage 2 the poison is re-applied whenever stage 1 cleared
        it, forcing the watchdog through rebuild into the reset exchange."""
        config = SessionConfig(watchdog_rounds=2)
        clock, sa, sb = self._stalled_pair(config=config)
        poison = dict(sa.state["sentHashes"])
        for _ in range(400):
            if sa.stats["resets"] or sb.stats["resets"]:
                break
            # keep the stall alive through stage 1: whenever the rebuild
            # cleared sentHashes, re-poison before the next generate
            if sa._wd_stage == 1 and not sa.state["sentHashes"]:
                sa.state = dict(sa.state, sentHashes=dict(poison))
            fa, fb = sa.poll(), sb.poll()
            if fa is not None:
                sb.handle(fa)
            if fb is not None:
                sa.handle(fb)
            clock.advance(0.05 if (fa or fb) else 0.26)
        assert sa.stats["resets"] >= 1
        # after the reset exchange the pair converges even with the poison
        # left in place once (reset clears it server-side)
        assert drive(clock, sa, sb, rounds=60)


# ---------------------------------------------------------------------- #
# metrics


class TestSessionMetrics:
    def test_session_and_watchdog_metrics_recorded(self):
        metrics = get_metrics()
        metrics.reset()
        with enabled_metrics():
            config = SessionConfig(timeout=1.0, max_retries=2,
                                   watchdog_rounds=3, backoff_cap=0.2)
            clock, sa, sb = make_pair(("x",), (), config=config)
            frame = sa.poll()
            sb.handle(frame)
            sb.handle(frame)  # duplicate
            clock.advance(5.0)
            sa.poll()  # retransmit 1
            for _ in range(3):
                clock.advance(50.0)
                sa.poll()
            assert sa.quarantined
            sa.release()
        snap = metrics.as_dict()
        assert snap["sync.session.dup_dropped"]["value"] == 1
        assert snap["sync.session.retransmits"]["value"] >= 1
        assert snap["sync.session.timeouts"]["value"] >= 2
        assert snap["sync.session.backoff_ms"]["count"] >= 1
        assert snap["sync.channel.quarantine.entered"]["value"] == 1
        assert snap["sync.channel.quarantine.released"]["value"] == 1
        assert snap["sync.channel.quarantine.active"]["value"] == 0
