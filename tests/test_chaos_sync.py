"""Chaos soak suite: supervised sync convergence over a hostile network
(ISSUE 5 acceptance). Two peers — and a 4-peer SyncFarm gossip ring — must
reach identical heads and canonical-JSON-equal documents under seeded
per-link loss/duplication/reordering up to 30%, corruption, truncation,
a peer restart mid-sync, and a partition-heal, all in simulated time
(ManualClock; the suite never sleeps)."""
import json
import random

import pytest

import automerge_tpu as am
from automerge_tpu import Frontend
from automerge_tpu import backend as Backend
from automerge_tpu.errors import SyncProtocolError
from automerge_tpu.sync_session import BackendDriver, SessionConfig, SyncSession
from automerge_tpu.testing import faults
from automerge_tpu.testing.chaos import (
    ChaosConfig,
    ChaosHarness,
    ChaosLink,
    ChaosNetwork,
    ManualClock,
)
from automerge_tpu.tpu.farm import TpuDocFarm
from automerge_tpu.tpu.sync_farm import SyncFarm


class DocDriver:
    """Session driver over the public API's document objects."""

    def __init__(self, doc):
        self.doc = doc

    def generate(self, state):
        return am.generate_sync_message(self.doc, state)

    def receive(self, state, payload):
        self.doc, state, patch = am.receive_sync_message(self.doc, state, payload)
        return state, patch

    def heads(self):
        return Backend.get_heads(Frontend.get_backend_state(self.doc, "heads"))


def canonical(doc) -> str:
    return json.dumps(dict(doc), sort_keys=True)


def edited_doc(actor, keys_values):
    doc = am.init(actor)
    for key, value in keys_values:
        doc = am.change(doc, lambda d, k=key, v=value: d.__setitem__(k, v))
    return doc


def soak_config(p):
    cfg = ChaosConfig.lossy(p)
    cfg.corrupt = p / 3
    cfg.truncate = p / 3
    return cfg


def make_harness(seed, p):
    clock = ManualClock()
    network = ChaosNetwork(random.Random(seed), clock, soak_config(p))
    return clock, network, ChaosHarness(network, clock)


def pair_sessions(harness, clock, da, db, seed, config=None):
    config = config or SessionConfig()
    sa = SyncSession(da, clock=clock, rng=random.Random(seed * 31 + 1),
                     config=config)
    sb = SyncSession(db, clock=clock, rng=random.Random(seed * 31 + 2),
                     config=config)
    harness.add_session("a", "b", sa)
    harness.add_session("b", "a", sb)
    return sa, sb


# ---------------------------------------------------------------------- #
# two peers


class TestTwoPeerSoak:
    @pytest.mark.parametrize("seed", range(3))
    def test_converges_under_30pct_chaos(self, seed):
        clock, _network, harness = make_harness(seed, 0.3)
        da = DocDriver(edited_doc("aaaaaaaa", [(f"a{i}", i) for i in range(6)]))
        db = DocDriver(edited_doc("bbbbbbbb", [(f"b{i}", i) for i in range(6)]))
        sa, sb = pair_sessions(harness, clock, da, db, seed)
        assert harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=600.0)
        assert canonical(da.doc) == canonical(db.doc)

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(3, 12))
    def test_converges_under_30pct_chaos_soak(self, seed):
        clock, _network, harness = make_harness(seed, 0.3)
        da = DocDriver(edited_doc("aaaaaaaa", [(f"a{i}", i) for i in range(10)]))
        db = DocDriver(edited_doc("bbbbbbbb", [(f"b{i}", i) for i in range(10)]))
        sa, sb = pair_sessions(harness, clock, da, db, seed)
        assert harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=900.0)
        assert canonical(da.doc) == canonical(db.doc)

    def test_same_seed_same_failure_schedule(self):
        def run(seed):
            clock, network, harness = make_harness(seed, 0.3)
            da = DocDriver(edited_doc("aaaaaaaa", [("x", 1), ("y", 2)]))
            db = DocDriver(edited_doc("bbbbbbbb", [("z", 3)]))
            sa, sb = pair_sessions(harness, clock, da, db, seed)
            harness.run_until(lambda: da.heads() == db.heads(), max_time=600.0)
            return (clock.now(), sa.stats, sb.stats, network.stats())

        assert run(7) == run(7)
        assert run(7) != run(8)

    def test_peer_restart_mid_sync(self):
        """b dies mid-exchange, loses its doc and session, and comes back
        with a fresh epoch; a detects the restart and re-converges."""
        clock, network, harness = make_harness(21, 0.15)
        da = DocDriver(edited_doc("aaaaaaaa", [(f"a{i}", i) for i in range(5)]))
        db = DocDriver(edited_doc("bbbbbbbb", [("b", 0)]))
        sa, sb = pair_sessions(harness, clock, da, db, 21)
        # let a few frames move, then kill b
        for _ in range(4):
            harness.step()
            clock.advance(0.1)
        network.drop_in_flight("b")
        db2 = DocDriver(edited_doc("bbbbbbbb", [("b", 0)]))
        sb2 = SyncSession(db2, clock=clock, rng=random.Random(999))
        harness.add_session("b", "a", sb2)  # replaces the dead session
        assert harness.run_until(lambda: da.heads() == db2.heads(),
                                 max_time=600.0)
        assert canonical(da.doc) == canonical(db2.doc)
        assert sa.stats["peer_restarts"] == 1

    def test_restart_with_persisted_session_resumes_seamlessly(self):
        clock, network, harness = make_harness(22, 0.1)
        da = DocDriver(edited_doc("aaaaaaaa", [(f"a{i}", i) for i in range(4)]))
        db = DocDriver(edited_doc("bbbbbbbb", []))
        sa, sb = pair_sessions(harness, clock, da, db, 22)
        assert harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=600.0)
        blob = sb.save()
        saved_doc = am.save(db.doc)
        # process restart: doc reloaded from disk, session restored
        db2 = DocDriver(am.load(saved_doc))
        sb2 = SyncSession.restore(blob, db2, clock=clock,
                                  rng=random.Random(1000))
        harness.add_session("b", "a", sb2)
        da.doc = am.change(da.doc, lambda d: d.__setitem__("late", 42))
        assert harness.run_until(lambda: da.heads() == db2.heads(),
                                 max_time=600.0)
        assert canonical(da.doc) == canonical(db2.doc)
        assert sa.stats["peer_restarts"] == 0  # same epoch: no restart seen

    def test_partition_heal(self):
        clock, network, harness = make_harness(23, 0.2)
        da = DocDriver(edited_doc("aaaaaaaa", [("x", 1)]))
        db = DocDriver(edited_doc("bbbbbbbb", [("y", 2)]))
        sa, sb = pair_sessions(harness, clock, da, db, 23)
        assert harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=600.0)
        network.partition("a", "b")
        # both sides edit during the partition
        da.doc = am.change(da.doc, lambda d: d.__setitem__("during_a", 1))
        db.doc = am.change(db.doc, lambda d: d.__setitem__("during_b", 2))
        assert not harness.run_until(lambda: da.heads() == db.heads(),
                                     max_time=30.0)
        # channels may have spent (or be about to spend) their retry
        # budget against the partition — that is the designed
        # degradation; heal, then release (a periodic release probe is
        # how a supervisor reopens circuit-broken channels)
        network.heal("a", "b")
        for _ in range(5):
            sa.release()
            sb.release()
            if harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=120.0):
                break
        assert da.heads() == db.heads()
        assert canonical(da.doc) == canonical(db.doc)
        assert "during_a" in dict(da.doc) and "during_b" in dict(da.doc)


# ---------------------------------------------------------------------- #
# protocol pairings (ISSUE 18): v1<->v1, v1<->v2, v2<->v2 under 30% chaos


def make_backend(actor, keys):
    backend = Backend.init()
    for i, key in enumerate(keys):
        buf = am.encode_change({
            "actor": actor, "seq": i + 1, "startOp": i + 1, "time": 0,
            "deps": Backend.get_heads(backend),
            "ops": [{"action": "set", "obj": "_root", "key": key,
                     "datatype": "uint", "value": i, "pred": []}],
        })
        backend, _ = Backend.apply_changes(backend, [buf])
    return backend


def pairing_harness(seed, p, v2a, v2b):
    clock, network, harness = make_harness(seed, p)
    da = BackendDriver(make_backend("aaaaaaaa", [f"a{i}" for i in range(6)]))
    db = BackendDriver(make_backend("bbbbbbbb", [f"b{i}" for i in range(6)]))
    sa = SyncSession(da, clock=clock, rng=random.Random(seed * 31 + 1),
                     config=SessionConfig(enable_v2=v2a))
    sb = SyncSession(db, clock=clock, rng=random.Random(seed * 31 + 2),
                     config=SessionConfig(enable_v2=v2b))
    harness.add_session("a", "b", sa)
    harness.add_session("b", "a", sb)
    return clock, network, harness, da, db, sa, sb


class TestProtocolPairingSoak:
    """Sync v2 negotiation under fire: every capability pairing must
    converge under 30% chaos. v2 only activates when BOTH sides advertise
    it; the mixed pairings run byte-for-byte v1 (the v2 flag bit is
    invisible to a peer that only tests FLAG_PAYLOAD)."""

    @pytest.mark.parametrize("seed,v2a,v2b", [
        (51, False, False), (52, False, True),
        (53, True, False), (54, True, True),
    ])
    def test_pairing_converges_under_30pct_chaos(self, seed, v2a, v2b):
        clock, _n, harness, da, db, sa, sb = pairing_harness(seed, 0.3, v2a, v2b)
        assert harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=900.0)
        both = v2a and v2b
        assert sa.v2_active == both and sb.v2_active == both
        assert (sa.stats["v2_negotiated"] > 0) == both
        assert sa.stats["v2_fallbacks"] == 0 and sb.stats["v2_fallbacks"] == 0

    def test_v2_soak_never_trips_the_watchdog(self):
        """The acceptance property: under the same 30% chaos, a v2<->v2
        pairing converges with the watchdog ladder untouched — range
        reconciliation has no false-positive stall mode to escalate out
        of."""
        clock, _n, harness, da, db, sa, sb = pairing_harness(55, 0.3, True, True)
        assert harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=900.0)
        for s in (sa, sb):
            assert s.stats["stalls"] == 0
            assert s.stats["escalations"] == 0
            assert s.stats["resets"] == 0


class TestAsymmetricChaos:
    """ISSUE 18 satellite: half-open partitions (one direction drops while
    the other flows) and per-link latency skew."""

    def test_one_way_partition_blocks_and_heals(self):
        clock, network, harness = make_harness(61, 0.1)
        da = DocDriver(edited_doc("aaaaaaaa", [("x", 1)]))
        db = DocDriver(edited_doc("bbbbbbbb", [("y", 2)]))
        sa, sb = pair_sessions(harness, clock, da, db, 61)
        assert harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=600.0)
        # half-open: a's frames vanish, b's frames still arrive at a
        network.partition_one_way("a", "b")
        da.doc = am.change(da.doc, lambda d: d.__setitem__("during_a", 1))
        db.doc = am.change(db.doc, lambda d: d.__setitem__("during_b", 2))
        assert not harness.run_until(lambda: da.heads() == db.heads(),
                                     max_time=30.0)
        # the live direction kept delivering: a heard from b even while
        # its own frames (including acks) were being eaten
        assert network.link("b", "a").stats.frames_delivered > 0
        network.heal_one_way("a", "b")
        for _ in range(5):
            sa.release()
            sb.release()
            if harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=120.0):
                break
        assert da.heads() == db.heads()
        assert canonical(da.doc) == canonical(db.doc)
        assert "during_a" in dict(db.doc) and "during_b" in dict(da.doc)

    @pytest.mark.parametrize("v2", [False, True])
    def test_latency_skew_converges(self, v2):
        """Asymmetric RTT halves: one direction pays 8x the latency of the
        other. The stop-and-wait timers absorb the skew for both
        protocols."""
        clock, network, harness, da, db, sa, sb = pairing_harness(
            62, 0.1, v2, v2
        )
        network.set_latency("a", "b", 0.4)
        network.set_latency("b", "a", 0.05)
        assert harness.run_until(lambda: da.heads() == db.heads(),
                                 max_time=900.0)
        assert sa.v2_active == v2 and sb.v2_active == v2

    def test_skewed_link_applies_base_delay(self):
        clock = ManualClock()
        network = ChaosNetwork(random.Random(0), clock, ChaosConfig())
        network.set_latency("a", "b", 0.3)
        network.send("a", "b", b"frame")
        assert network.deliver("b") == []          # still in flight
        clock.advance(0.2)
        assert network.deliver("b") == []          # 0.2 < 0.3
        clock.advance(0.2)
        assert network.deliver("b") == [("a", b"frame")]


# ---------------------------------------------------------------------- #
# 4-peer SyncFarm gossip ring


class FarmPeer:
    """One ring member: a 1-doc farm + its batched sync driver."""

    def __init__(self, name, actor):
        self.name = name
        self.actor = actor
        self.farm = TpuDocFarm(1, capacity=256)
        self.sync = SyncFarm(self.farm)
        self.seq = 0
        self.max_op = 0

    def edit(self, key, value):
        self.seq += 1
        start = self.max_op + 1
        buf = faults.make_change(
            self.actor, self.seq, start, self.farm.get_heads(0),
            [faults.set_op(key, value)],
        )
        self.max_op = start
        self.farm.apply_changes([[buf]])

    def heads(self):
        return self.farm.get_heads(0)

    def doc_json(self):
        return json.dumps(self.farm.get_patch(0), sort_keys=True)


def ring_harness(seed, p, n_edits, config=None, npeers=4):
    clock = ManualClock()
    network = ChaosNetwork(random.Random(seed), clock, soak_config(p))
    harness = ChaosHarness(network, clock)
    peers = [FarmPeer(i, f"{i:02x}{'ab'*3}") for i in range(npeers)]
    for i, peer in enumerate(peers):
        for k in range(n_edits):
            peer.edit(f"p{i}k{k}", i * 100 + k)
    config = config or SessionConfig()
    rng_base = seed * 1000
    for i in range(npeers):
        j = (i + 1) % npeers
        for src, dst in ((i, j), (j, i)):
            session = peers[src].sync.make_session(
                0, clock=clock,
                rng=random.Random(rng_base + src * npeers + dst),
                config=config,
            )
            harness.add_session(src, dst, session)
    return clock, network, harness, peers


def ring_converged(peers):
    h0 = peers[0].heads()
    return all(p.heads() == h0 for p in peers[1:])


class TestFarmRingSoak:
    def test_ring_converges_under_15pct_chaos(self):
        clock, _n, harness, peers = ring_harness(31, 0.15, n_edits=2)
        assert harness.run_until(lambda: ring_converged(peers),
                                 max_time=900.0)
        docs = {p.doc_json() for p in peers}
        assert len(docs) == 1

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [32, 33, 34])
    def test_ring_converges_under_30pct_chaos(self, seed):
        clock, _n, harness, peers = ring_harness(seed, 0.3, n_edits=3)
        assert harness.run_until(lambda: ring_converged(peers),
                                 max_time=1800.0)
        docs = {p.doc_json() for p in peers}
        assert len(docs) == 1

    def test_ring_peer_restart(self):
        """Peer 2 loses its farm and sessions mid-gossip; the ring heals
        around the restart."""
        clock, network, harness, peers = ring_harness(35, 0.1, n_edits=2)
        for _ in range(6):
            harness.step()
            clock.advance(0.1)
        network.drop_in_flight(2)
        peers[2] = FarmPeer(2, "02" + "ab" * 3)
        for src, dst in ((2, 1), (2, 3)):
            harness.add_session(src, dst, peers[src].sync.make_session(
                0, clock=clock, rng=random.Random(5000 + dst)))
        assert harness.run_until(lambda: ring_converged(peers),
                                 max_time=1200.0)
        assert len({p.doc_json() for p in peers}) == 1


# ---------------------------------------------------------------------- #
# composition with the fault-injection registry


class TestChaosFaultComposition:
    def test_chaos_send_failure_point_fires(self):
        clock = ManualClock()
        link = ChaosLink(random.Random(0), clock, ChaosConfig(), name="a->b")
        seen = []
        with faults.inject("chaos.send", lambda **ctx: seen.append(ctx)):
            link.send(b"frame-1")
        assert seen == [{"link": "a->b", "frame": b"frame-1"}]
        assert link.deliver() == [b"frame-1"]

    def test_injected_transport_fault_composes_with_chaos(self):
        """faults.inject can make a chaos link raise — merge-path faults
        and network faults share one registry."""
        clock = ManualClock()
        link = ChaosLink(random.Random(0), clock, ChaosConfig())
        with faults.inject("chaos.send", faults.fail_always()):
            with pytest.raises(RuntimeError):
                link.send(b"frame")
        link.send(b"frame")  # registry restored
        assert link.deliver() == [b"frame"]

    def test_quarantined_doc_sheds_sync_while_channel_stays_up(self):
        """A doc quarantined by the farm's per-doc isolation (PR 3) stops
        being offered over supervised sync; after release_quarantine the
        same channel converges. Merge fault + lossy network together."""
        clock = ManualClock()
        network = ChaosNetwork(random.Random(41), clock, ChaosConfig(drop=0.1))
        harness = ChaosHarness(network, clock)
        pa = FarmPeer("a", "aa" * 4)
        pb = FarmPeer("b", "bb" * 4)
        pa.edit("x", 1)
        sa = pa.sync.make_session(0, clock=clock, rng=random.Random(1))
        sb = pb.sync.make_session(0, clock=clock, rng=random.Random(2))
        harness.add_session("a", "b", sa)
        harness.add_session("b", "a", sb)
        # quarantine a's doc with repeated garbage deliveries
        for _ in range(pa.farm.quarantine_threshold):
            pa.farm.apply_changes([[faults.garbage(48)]])
        assert 0 in pa.farm.quarantine
        harness.run_until(lambda: False, max_time=10.0)
        assert sa.state["lastSentHeads"] == []  # nothing was generated
        assert pb.heads() == []
        pa.farm.release_quarantine(0)
        assert harness.run_until(lambda: pa.heads() == pb.heads(),
                                 max_time=600.0)
        assert pb.heads() != []
