"""Sync v2 suite (range-based set reconciliation, automerge_tpu/sync_v2.py).

Covers the four layers of the v2 stack:

- wire codec strictness: truncated, garbage, overlapping-range and
  duplicate-item frames all reject with ``SyncProtocolError`` and the
  receiving backend / sync state / hash index provably untouched;
- host/device fingerprint bit-identity: ``HashIndex`` (prefix-XOR on host)
  and ``tpu.fingerprint.FingerprintIndex`` (batched XOR reduction on
  device) must agree bit for bit on every range;
- deterministic convergence: divergent histories reconcile in at most
  2*log2(n) round trips with no probabilistic failure mode;
- the farm path: EVERY live v2 channel's fingerprint queries resolve as
  ONE observatory-pinned device dispatch per sweep;
- session negotiation: v1<->v2 pairings run byte-for-byte v1, v2<->v2
  activates bilaterally, and a mid-session v2 error falls back to v1
  without stalling the channel.
"""
import copy
import hashlib
import math
import random

import pytest

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu import sync as Sync
from automerge_tpu.codecs import Encoder, hex_to_bytes
from automerge_tpu.errors import EncodeError, SyncProtocolError
from automerge_tpu.obs.metrics import enabled_metrics, get_metrics
from automerge_tpu.obs.prof import enabled_observatory, get_observatory
from automerge_tpu.sync import _encode_hashes
from automerge_tpu.sync_session import (
    FLAG_V2,
    BackendDriver,
    SessionConfig,
    SyncSession,
    decode_frame,
)
from automerge_tpu.sync_v2 import (
    ITEM_THRESHOLD,
    MAX_HASH,
    MESSAGE_TYPE_SYNC_V2,
    MIN_HASH,
    RANGE_FINGERPRINT,
    RANGE_ITEMS,
    HashIndex,
    decode_sync_message_v2,
    encode_sync_message_v2,
    generate_sync_message_v2,
    index_for_backend,
    receive_sync_message_v2,
)
from automerge_tpu.testing.chaos import ManualClock
from automerge_tpu.tpu.farm import TpuDocFarm
from automerge_tpu.tpu.fingerprint import FingerprintIndex
from automerge_tpu.tpu.sync_farm import SyncFarm
from automerge_tpu.columnar import encode_change


def fake_hash(i) -> str:
    """Deterministic 256-bit hex hash."""
    return hashlib.sha256(str(i).encode()).hexdigest()


def grow_backend(backend, actor, keys, start_seq=1):
    for i, key in enumerate(keys):
        buf = am.encode_change({
            "actor": actor, "seq": start_seq + i, "startOp": start_seq + i,
            "time": 0, "deps": Backend.get_heads(backend),
            "ops": [{"action": "set", "obj": "_root", "key": key,
                     "datatype": "uint", "value": i, "pred": []}],
        })
        backend, _ = Backend.apply_changes(backend, [buf])
    return backend


def make_backend(actor, n):
    return grow_backend(Backend.init(), actor, [f"k{i}" for i in range(n)])


def converge_v2(ba, bb, max_round_trips=64):
    """Drives the raw v2 entry points until both sides go quiet; returns
    (ba, bb, round_trips)."""
    sa, sb = Sync.init_sync_state(), Sync.init_sync_state()
    ia, ib = index_for_backend(ba), index_for_backend(bb)
    trips = 0
    for _ in range(max_round_trips):
        sa, ma = generate_sync_message_v2(ba, sa, ia)
        sb, mb = generate_sync_message_v2(bb, sb, ib)
        if ma is None and mb is None:
            break
        trips += 1
        if ma is not None:
            bb, sb, _ = receive_sync_message_v2(bb, sb, ib, ma)
        if mb is not None:
            ba, sa, _ = receive_sync_message_v2(ba, sa, ia, mb)
    return ba, bb, trips


def raw_message(heads=(), need=(), ranges=(), changes=()):
    """Hand-encodes a v2 frame WITHOUT the encoder's validation, so tests
    can craft frames the strict encoder refuses to produce."""
    enc = Encoder()
    enc.append_byte(MESSAGE_TYPE_SYNC_V2)
    _encode_hashes(enc, sorted(heads))
    _encode_hashes(enc, sorted(need))
    enc.append_uint32(len(ranges))
    for r in ranges:
        enc.append_raw_bytes(hex_to_bytes(r["lo"]))
        enc.append_raw_bytes(hex_to_bytes(r["hi"]))
        enc.append_byte(r["mode"])
        if r["mode"] == RANGE_FINGERPRINT:
            enc.append_uint53(r["count"])
            enc.append_raw_bytes(hex_to_bytes(r["fp"]))
        else:
            enc.append_uint32(len(r["items"]))
            for h in r["items"]:
                enc.append_raw_bytes(hex_to_bytes(h))
    enc.append_uint32(len(changes))
    for change in changes:
        enc.append_prefixed_bytes(change)
    return enc.buffer


def fp_range(lo, hi, count=1, fp=None):
    return {"lo": lo, "hi": hi, "mode": RANGE_FINGERPRINT,
            "count": count, "fp": fp or fake_hash("fp")}


# ---------------------------------------------------------------------- #
# HashIndex (host fingerprints)


class TestHashIndex:
    def test_fingerprints_match_brute_force(self):
        hashes = sorted(fake_hash(i) for i in range(200))
        index = HashIndex(hashes)
        queries = [
            (MIN_HASH, MAX_HASH),
            (hashes[10], hashes[50]),          # half-open: excludes hi
            (hashes[0], hashes[1]),
            (hashes[7], hashes[7]),            # empty span
            ("2" + "0" * 63, "7" + "f" * 63),  # bounds between members
        ]
        got = index.fingerprint_many(queries)
        for (lo, hi), (count, fp) in zip(queries, got):
            members = [h for h in hashes if lo <= h < hi]
            acc = 0
            for h in members:
                acc ^= int(h, 16)
            assert count == len(members)
            assert fp == format(acc, "064x")

    def test_incremental_insert_refreshes_fingerprints(self):
        index = HashIndex()
        assert index.fingerprint_many([(MIN_HASH, MAX_HASH)]) == [(0, "0" * 64)]
        h = fake_hash(1)
        assert index.insert(h) is True
        assert index.insert(h) is False  # idempotent
        assert index.contains(h)
        assert index.fingerprint_many([(MIN_HASH, MAX_HASH)]) == [(1, h)]

    def test_rejects_malformed_hashes(self):
        index = HashIndex()
        with pytest.raises(SyncProtocolError):
            index.insert("abc")
        with pytest.raises(SyncProtocolError):
            index.insert("z" * 64)

    def test_index_for_backend_refresh_is_idempotent(self):
        backend = make_backend("aaaaaaaa", 5)
        index = index_for_backend(backend)
        assert len(index) == 5
        again = index_for_backend(backend, index)
        assert again is index and len(again) == 5


# ---------------------------------------------------------------------- #
# wire codec


class TestCodecRoundTrip:
    def test_full_round_trip(self):
        items_range = sorted(fake_hash(i) for i in range(3))
        message = {
            "heads": sorted([fake_hash("h1"), fake_hash("h2")]),
            "need": [fake_hash("n1")],
            "ranges": [
                {"lo": MIN_HASH, "hi": items_range[-1], "mode": RANGE_ITEMS,
                 "items": items_range[:-1]},
                fp_range(items_range[-1], MAX_HASH, count=7),
            ],
            "changes": [b"change-one", b"change-two"],
        }
        assert decode_sync_message_v2(encode_sync_message_v2(message)) == message

    def test_empty_message_round_trips(self):
        message = {"heads": [], "need": [], "ranges": [], "changes": []}
        assert decode_sync_message_v2(encode_sync_message_v2(message)) == message

    def test_trailing_bytes_ignored_for_forward_compat(self):
        message = {"heads": [], "need": [], "ranges": [], "changes": []}
        data = encode_sync_message_v2(message) + b"\x00\x01future-fields"
        assert decode_sync_message_v2(data) == message

    def test_encoder_refuses_inverted_bounds(self):
        with pytest.raises(EncodeError):
            encode_sync_message_v2({
                "heads": [], "need": [], "changes": [],
                "ranges": [fp_range(MAX_HASH[:-1] + "e", MIN_HASH)],
            })

    def test_encoder_refuses_overlapping_ranges(self):
        a, b, c = sorted(fake_hash(i) for i in range(3))
        with pytest.raises(EncodeError):
            encode_sync_message_v2({
                "heads": [], "need": [], "changes": [],
                "ranges": [fp_range(a, c), fp_range(b, MAX_HASH)],
            })

    def test_encoder_refuses_unsorted_items(self):
        a, b = sorted(fake_hash(i) for i in range(2))
        with pytest.raises(EncodeError):
            encode_sync_message_v2({
                "heads": [], "need": [], "changes": [],
                "ranges": [{"lo": MIN_HASH, "hi": MAX_HASH,
                            "mode": RANGE_ITEMS, "items": [b, a]}],
            })

    def test_encoder_refuses_unknown_mode(self):
        with pytest.raises(EncodeError):
            encode_sync_message_v2({
                "heads": [], "need": [], "changes": [],
                "ranges": [{"lo": MIN_HASH, "hi": MAX_HASH, "mode": 9}],
            })


class TestCodecRejection:
    """Every malformed shape raises SyncProtocolError — never a raw decode
    exception — and decoding constructs no partial state."""

    def valid(self):
        return raw_message(
            heads=[fake_hash("h")],
            ranges=[fp_range(MIN_HASH, MAX_HASH, count=3)],
            changes=[b"some-change-bytes"],
        )

    def test_every_truncation_rejects(self):
        data = self.valid()
        for keep in range(len(data)):
            with pytest.raises(SyncProtocolError):
                decode_sync_message_v2(data[:keep])

    def test_garbage_rejects(self):
        with pytest.raises(SyncProtocolError):
            decode_sync_message_v2(bytes([MESSAGE_TYPE_SYNC_V2]) + b"\xff" * 40)

    def test_wrong_type_byte_rejects(self):
        with pytest.raises(SyncProtocolError, match="message type"):
            decode_sync_message_v2(b"\x42" + self.valid()[1:])

    def test_inverted_bounds_reject(self):
        data = raw_message(ranges=[
            {"lo": MAX_HASH[:-1] + "e", "hi": MIN_HASH,
             "mode": RANGE_FINGERPRINT, "count": 0, "fp": "0" * 64},
        ])
        with pytest.raises(SyncProtocolError, match="inverted"):
            decode_sync_message_v2(data)

    def test_overlapping_ranges_reject(self):
        a, b, c = sorted(fake_hash(i) for i in range(3))
        data = raw_message(ranges=[fp_range(a, c), fp_range(b, MAX_HASH)])
        with pytest.raises(SyncProtocolError, match="overlapping"):
            decode_sync_message_v2(data)

    def test_duplicate_items_reject(self):
        h = fake_hash(1)
        data = raw_message(ranges=[
            {"lo": MIN_HASH, "hi": MAX_HASH, "mode": RANGE_ITEMS,
             "items": [h, h]},
        ])
        with pytest.raises(SyncProtocolError, match="ascending"):
            decode_sync_message_v2(data)

    def test_out_of_range_item_rejects(self):
        a, b, c = sorted(fake_hash(i) for i in range(3))
        data = raw_message(ranges=[
            {"lo": b, "hi": MAX_HASH, "mode": RANGE_ITEMS, "items": [a]},
        ])
        with pytest.raises(SyncProtocolError, match="outside"):
            decode_sync_message_v2(data)

    def test_unknown_mode_rejects(self):
        enc = Encoder()
        enc.append_byte(MESSAGE_TYPE_SYNC_V2)
        _encode_hashes(enc, [])
        _encode_hashes(enc, [])
        enc.append_uint32(1)
        enc.append_raw_bytes(hex_to_bytes(MIN_HASH))
        enc.append_raw_bytes(hex_to_bytes(MAX_HASH))
        enc.append_byte(7)
        with pytest.raises(SyncProtocolError, match="unknown range mode"):
            decode_sync_message_v2(enc.buffer)


class TestReceiveLeavesStateUntouched:
    """The acceptance property for satellite 3: a rejected frame leaves the
    backend, the sync-state object AND the hash index provably unmodified —
    the channel can keep operating on the same objects."""

    def poisoned_frames(self):
        a, b, c = sorted(fake_hash(i) for i in range(3))
        h = fake_hash(9)
        return [
            raw_message(ranges=[fp_range(MIN_HASH, MAX_HASH)])[:-3],  # truncated
            bytes([MESSAGE_TYPE_SYNC_V2]) + b"\xff" * 17,             # garbage
            raw_message(ranges=[fp_range(a, c), fp_range(b, MAX_HASH)]),
            raw_message(ranges=[{"lo": MIN_HASH, "hi": MAX_HASH,
                                 "mode": RANGE_ITEMS, "items": [h, h]}]),
            # valid envelope, inapplicable change bytes
            raw_message(changes=[b"\x00garbage-not-a-change"]),
        ]

    def test_rejection_mutates_nothing(self):
        backend = make_backend("aaaaaaaa", 4)
        index = index_for_backend(backend)
        state = Sync.init_sync_state()
        heads_before = Backend.get_heads(backend)
        state_snapshot = copy.deepcopy(state)
        index_len = len(index)
        for frame in self.poisoned_frames():
            with pytest.raises(SyncProtocolError):
                receive_sync_message_v2(backend, state, index, frame)
            assert state == state_snapshot
            assert Backend.get_heads(backend) == heads_before
            assert len(index) == index_len
        # ...and the same objects still sync normally afterwards
        ba, bb, _ = converge_v2(backend, make_backend("bbbbbbbb", 2))
        assert Backend.get_heads(ba) == Backend.get_heads(bb)

    def test_rejections_are_counted(self):
        backend = make_backend("aaaaaaaa", 1)
        index = index_for_backend(backend)
        state = Sync.init_sync_state()
        metrics = get_metrics()
        metrics.reset()
        with enabled_metrics():
            with pytest.raises(SyncProtocolError):
                receive_sync_message_v2(
                    backend, state, index,
                    bytes([MESSAGE_TYPE_SYNC_V2]) + b"\xff" * 9,
                )
        assert metrics.as_dict()["sync.v2.messages.rejected"]["value"] == 1

    def test_none_arguments_reject(self):
        backend = make_backend("aaaaaaaa", 1)
        index = index_for_backend(backend)
        with pytest.raises(SyncProtocolError):
            generate_sync_message_v2(None, Sync.init_sync_state(), index)
        with pytest.raises(SyncProtocolError):
            generate_sync_message_v2(backend, None, index)
        with pytest.raises(SyncProtocolError):
            receive_sync_message_v2(backend, None, index, b"\x45")
        with pytest.raises(SyncProtocolError):
            receive_sync_message_v2(None, Sync.init_sync_state(), index, b"\x45")


# ---------------------------------------------------------------------- #
# host/device fingerprint parity


class TestHostDeviceParity:
    def test_fingerprints_bit_identical(self):
        rng = random.Random(5)
        hashes = sorted(fake_hash(i) for i in range(150))
        host = HashIndex(hashes)
        device = FingerprintIndex()
        device.sync_doc(0, hashes)
        spans = [(MIN_HASH, MAX_HASH), (hashes[0], hashes[1]),
                 (hashes[3], hashes[3])]
        for _ in range(25):
            i, j = sorted(rng.sample(range(len(hashes)), 2))
            spans.append((hashes[i], hashes[j]))
        got_host = host.fingerprint_many(spans)
        got_device = device.fingerprint_ranges(
            [(0, lo, hi) for lo, hi in spans]
        )
        assert got_host == got_device

    def test_multi_doc_batch_keeps_documents_apart(self):
        device = FingerprintIndex()
        a = sorted(fake_hash(f"a{i}") for i in range(40))
        b = sorted(fake_hash(f"b{i}") for i in range(9))
        device.sync_doc(0, a)
        device.sync_doc(1, b)
        got = device.fingerprint_ranges([
            (0, MIN_HASH, MAX_HASH), (1, MIN_HASH, MAX_HASH),
            (1, b[2], b[5]), (0, a[0], a[0]),
        ])
        assert got[0] == HashIndex(a).fingerprint_many([(MIN_HASH, MAX_HASH)])[0]
        assert got[1] == HashIndex(b).fingerprint_many([(MIN_HASH, MAX_HASH)])[0]
        assert got[2] == HashIndex(b).fingerprint_many([(b[2], b[5])])[0]
        assert got[3] == (0, "0" * 64)

    def test_empty_query_list_dispatches_nothing(self):
        assert FingerprintIndex().fingerprint_ranges([]) == []


# ---------------------------------------------------------------------- #
# convergence


class TestConvergence:
    @pytest.mark.parametrize("na,nb", [(0, 12), (12, 0), (60, 45), (1, 1)])
    def test_divergent_histories_converge(self, na, nb):
        ba = make_backend("aaaaaaaa", na)
        bb = make_backend("bbbbbbbb", nb)
        ba, bb, trips = converge_v2(ba, bb)
        assert Backend.get_heads(ba) == Backend.get_heads(bb)
        total = max(na + nb, 2)
        assert trips <= 2 * math.log2(total) + 2

    def test_round_trip_bound_holds_at_scale(self):
        """The acceptance shape at test scale: two peers sharing a prefix
        then diverging must reconcile within 2*log2(n) round trips."""
        shared = [f"s{i}" for i in range(64)]
        ba = make_backend("aaaaaaaa", 0)
        ba = grow_backend(ba, "cccccccc", shared)
        bb = grow_backend(Backend.init(), "cccccccc", shared)
        ba = grow_backend(ba, "aaaaaaaa", [f"a{i}" for i in range(130)])
        bb = grow_backend(bb, "bbbbbbbb", [f"b{i}" for i in range(170)])
        ba, bb, trips = converge_v2(ba, bb)
        assert Backend.get_heads(ba) == Backend.get_heads(bb)
        assert trips <= 2 * math.log2(64 + 130 + 170)

    def test_converged_channel_is_silent(self):
        ba = make_backend("aaaaaaaa", 8)
        bb = make_backend("bbbbbbbb", 8)
        ba, bb, _ = converge_v2(ba, bb)
        sa = Sync.init_sync_state()
        sa, first = generate_sync_message_v2(ba, sa, index_for_backend(ba))
        assert first is not None  # fresh state: one advert/probe
        bb2, sb, _ = receive_sync_message_v2(
            bb, Sync.init_sync_state(), index_for_backend(bb), first
        )
        # after the echo round the heads agree and both sides go quiet
        _, _, trips = converge_v2(ba, bb)
        assert trips == 0 or trips <= 3


# ---------------------------------------------------------------------- #
# farm: one batched fingerprint dispatch per sweep


def farm_edit(farm, d, actor, seq, start_op, keys):
    buf = encode_change({
        "actor": actor, "seq": seq, "startOp": start_op, "time": 0,
        "deps": sorted(farm.get_heads(d)),
        "ops": [{"action": "set", "obj": "_root", "key": k,
                 "datatype": "uint", "value": v, "pred": []}
                for v, k in enumerate(keys)],
    })
    per_doc = [[] for _ in range(farm.num_docs)]
    per_doc[d] = [buf]
    farm.apply_changes(per_doc)


class TestFarmBatchedFingerprints:
    NUM_DOCS = 4

    def make_pair(self):
        fa = TpuDocFarm(self.NUM_DOCS, capacity=256)
        fb = TpuDocFarm(self.NUM_DOCS, capacity=256)
        for d in range(self.NUM_DOCS):
            farm_edit(fa, d, "aaaaaaaa", 1, 1, [f"a{d}", f"x{d}"])
            farm_edit(fb, d, "bbbbbbbb", 1, 1, [f"b{d}"])
        return SyncFarm(fa), SyncFarm(fb)

    def test_converges_with_one_dispatch_per_sweep(self):
        """The tentpole farm property: a sweep over N live v2 channels
        resolves ALL fingerprint queries — inbound checks, median splits,
        fresh probes — as ONE compiled-program dispatch, pinned via the
        amprof observatory."""
        sa, sb = self.make_pair()
        n = self.NUM_DOCS
        a_states = [SyncFarm.init_state() for _ in range(n)]
        b_states = [SyncFarm.init_state() for _ in range(n)]
        protocols = ["v2"] * n
        obs = get_observatory()
        prog = obs.programs()["sync.fingerprint_ranges"]
        with enabled_observatory():
            prog.reset()
            for _ in range(12):
                out = sa.generate_messages(
                    list(zip(range(n), a_states)), protocols=protocols
                )
                a_states = [s for s, _ in out]
                sends = [(d, b_states[d], m)
                         for d, (_, m) in enumerate(out) if m is not None]
                if sends:
                    recv = sb.receive_messages(sends, protocols=protocols)
                    for (d, _, _), (state, _p) in zip(sends, recv):
                        b_states[d] = state
                out = sb.generate_messages(
                    list(zip(range(n), b_states)), protocols=protocols
                )
                b_states = [s for s, _ in out]
                sends = [(d, a_states[d], m)
                         for d, (_, m) in enumerate(out) if m is not None]
                if sends:
                    recv = sa.receive_messages(sends, protocols=protocols)
                    for (d, _, _), (state, _p) in zip(sends, recv):
                        a_states[d] = state
                if not sends:
                    break
            sweeps = prog.dispatches
        for d in range(self.NUM_DOCS):
            assert sa.farm.get_heads(d) == sb.farm.get_heads(d), f"doc {d}"
        # at most one fingerprint dispatch per generate_messages sweep —
        # NOT one per channel (4 docs would mean 4x the dispatches)
        assert 0 < sweeps <= 2 * 12

    def test_single_sweep_with_all_channels_probing_is_one_dispatch(self):
        sa, _sb = self.make_pair()
        n = self.NUM_DOCS
        states = [SyncFarm.init_state() for _ in range(n)]
        obs = get_observatory()
        prog = obs.programs()["sync.fingerprint_ranges"]
        with enabled_observatory():
            prog.reset()
            out = sa.generate_messages(
                list(zip(range(n), states)), protocols=["v2"] * n
            )
            assert prog.dispatches == 1
        assert all(m is not None for _, m in out)


# ---------------------------------------------------------------------- #
# session negotiation / interop / fallback


def session_pair(v2a, v2b, *, driver_a=None, driver_b=None, seed=3):
    clock = ManualClock()
    da = driver_a or BackendDriver(make_backend("aaaaaaaa", 6))
    db = driver_b or BackendDriver(make_backend("bbbbbbbb", 4))
    sa = SyncSession(da, clock=clock, rng=random.Random(seed),
                     config=SessionConfig(enable_v2=v2a))
    sb = SyncSession(db, clock=clock, rng=random.Random(seed + 1),
                     config=SessionConfig(enable_v2=v2b))
    return clock, sa, sb


def drive_transcript(clock, sa, sb, rounds=60):
    """Lossless shuttle that records every frame's (sender, flags,
    payload)."""
    frames = []
    for _ in range(rounds):
        fa, fb = sa.poll(), sb.poll()
        for sender, frame, receiver in (("a", fa, sb), ("b", fb, sa)):
            if frame is None:
                continue
            decoded = decode_frame(frame)
            frames.append((sender, decoded["flags"], decoded["payload"]))
            receiver.handle(frame)
        if fa is None and fb is None:
            if sa.driver.heads() == sb.driver.heads():
                return frames, True
        clock.advance(0.05 if (fa or fb) else 0.26)
    return frames, sa.driver.heads() == sb.driver.heads()


class TestSessionNegotiation:
    def test_v1_v2_pairing_is_byte_for_byte_v1(self):
        """A v2-capable peer facing a v1 peer produces EXACTLY today's v1
        transcript: same payload bytes in the same order — the capability
        flag rides the session flags byte, invisible to the inner
        protocol."""
        ref_frames, ok = drive_transcript(*session_pair(False, False))
        mixed_frames, ok2 = drive_transcript(*session_pair(True, False))
        assert ok and ok2
        assert [p for _, _, p in ref_frames] == [p for _, _, p in mixed_frames]
        # the only difference: a's frames advertise the capability
        for (_, ref_flags, _), (sender, flags, _) in zip(ref_frames,
                                                         mixed_frames):
            if sender == "a":
                assert flags == ref_flags | FLAG_V2
            else:
                assert flags == ref_flags

    def test_v2_pairing_activates_bilaterally_and_converges(self):
        metrics = get_metrics()
        metrics.reset()
        with enabled_metrics():
            clock, sa, sb = session_pair(True, True)
            _, ok = drive_transcript(clock, sa, sb)
        assert ok
        assert sa.v2_active and sb.v2_active
        assert sa.stats["v2_negotiated"] == 1
        assert sb.stats["v2_negotiated"] == 1
        snap = metrics.as_dict()
        assert snap["sync.v2.sessions.negotiated"]["value"] == 2
        assert snap["sync.v2.messages.generated"]["value"] > 0
        assert snap.get("sync.v2.fallbacks", {"value": 0})["value"] == 0

    def test_mixed_pairing_never_activates(self):
        clock, sa, sb = session_pair(True, False)
        _, ok = drive_transcript(clock, sa, sb)
        assert ok
        assert not sa.v2_active and not sb.v2_active
        assert sa.stats["v2_negotiated"] == 0


class FailingGenerateDriver(BackendDriver):
    def generate_v2(self, state):
        raise SyncProtocolError("injected v2 planner failure")


class FailingReceiveDriver(BackendDriver):
    def receive_v2(self, state, payload):
        raise SyncProtocolError("injected v2 apply failure")


class TestSessionFallback:
    def test_generate_error_falls_back_same_call(self):
        """A v2 generate error downgrades to v1 inside the SAME poll — the
        channel never goes silent, and the peer symmetrically drops to v1
        when the capability flag disappears."""
        da = FailingGenerateDriver(make_backend("aaaaaaaa", 6))
        clock, sa, sb = session_pair(True, True, driver_a=da)
        _, ok = drive_transcript(clock, sa, sb)
        assert ok
        assert sa.stats["v2_fallbacks"] == 1
        assert not sa.v2_active and not sb.v2_active
        assert sa.stats["stalls"] == 0 and sb.stats["stalls"] == 0

    def test_receive_error_acks_and_falls_back(self):
        """A poisoned v2 frame is ACKed (not retransmitted into quarantine)
        and the receiver latches v1; both sides still converge."""
        db = FailingReceiveDriver(make_backend("bbbbbbbb", 4))
        clock, sa, sb = session_pair(True, True, driver_b=db)
        _, ok = drive_transcript(clock, sa, sb)
        assert ok
        assert sb.stats["v2_fallbacks"] == 1
        assert not sa.quarantined and not sb.quarantined
        assert not sa.v2_active and not sb.v2_active

    def test_fallback_strips_v2_state(self):
        da = FailingGenerateDriver(make_backend("aaaaaaaa", 6))
        clock, sa, sb = session_pair(True, True, driver_a=da)
        drive_transcript(clock, sa, sb)
        assert not any(k.startswith("v2") for k in sa.state)

    def test_peer_restart_renegotiates(self):
        clock, sa, sb = session_pair(True, True)
        _, ok = drive_transcript(clock, sa, sb)
        assert ok and sa.v2_active
        sb2 = SyncSession(sb.driver, clock=clock, rng=random.Random(9),
                          config=SessionConfig(enable_v2=True))
        # a sees the fresh epoch: peer beliefs reset, then the restart
        # frame's own capability flag re-negotiates v2 immediately
        frame = sb2.poll()
        assert frame is not None
        sa.handle(frame)
        assert sa.stats["peer_restarts"] == 1
        assert sa.peer_v2  # re-learned from the restart frame's flags
        assert not any(k.startswith("v2") for k in sa.state)
        _, ok = drive_transcript(clock, sa, sb2)
        assert ok
        assert sa.v2_active and sb2.v2_active
