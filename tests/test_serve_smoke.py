"""Tier-1 smoke gate for the serving front door (ISSUE 6), mirroring the
bench-smoke pattern: a small, fully seeded, simulated-time load-harness
run whose figures are machine-independent (the clock is a ManualClock, so
scheduling, batching windows and retransmission deadlines replay exactly
from the seed on any host — only the wall-clock duration varies).

Properties gated (`bench.py --serve --quick` checks the same at a larger
config; `make serve` runs that):
- every client's heads converge to the farm's (the whole point of the
  session multiplexer + batcher pipeline);
- batch occupancy stays above the floor — the dynamic batcher must keep
  farm dispatches dense, not degrade to request-per-dispatch;
- zero unexplained sheds: with no poison and no chaos, nothing may be
  rejected at admission or dropped from a window.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench

OCCUPANCY_FLOOR = 8

_REPORT = None


def _smoke():
    global _REPORT
    if _REPORT is None:
        _REPORT = bench.bench_serve(
            clients=96, docs=24, edits=2, ops=4, spread=0.4,
        )
    return _REPORT


def test_all_clients_converge():
    report = _smoke()
    assert report["converged"], report
    assert report["unconverged_clients"] == 0
    assert report["surviving_clients"] == 96


def test_batch_occupancy_above_floor():
    """The batcher must produce dense dispatches: mean docs-with-changes
    per farm dispatch at the default flush policy stays >= the floor. A
    regression to per-request dispatching collapses this toward 1."""
    report = _smoke()
    assert report["dispatches"] > 0
    assert report["occupancy_mean"] >= OCCUPANCY_FLOOR, report


def test_zero_unexplained_sheds():
    """No poison, no chaos => nothing may be shed: no admission rejects,
    no quarantine exclusions, no backpressure, no client-rejected frames."""
    report = _smoke()
    assert report["admission"]["rejected_quarantine"] == 0
    assert report["admission"]["rejected_backpressure"] == 0
    assert report["admission"]["shed_mid_window"] == 0
    assert report["frames_shed"] == 0
    assert report["quarantined_docs"] == 0


def test_latency_histogram_populated():
    """The latency figures the bench reports must come from real samples
    (first transmission -> ack), not an empty histogram."""
    report = _smoke()
    lat = report["latency_ms"]
    assert lat["samples"] > 0
    assert lat["p50"] is not None
    assert lat["p50"] <= lat["p95"] <= lat["p99"]
