"""Unit suite for the zero-copy mesh data plane's ring + codec layer
(automerge_tpu/parallel/shm.py).

The rings are the PR 19 tentpole: one bounded SPSC shared-memory ring
per direction per shard, slots moving FREE -> PRODUCER_HELD ->
CONSUMER_HELD -> FREE with a generation counter so a ref published
before a crash reclaim can never alias a re-used slot. This suite pins
the transport-layer contracts in isolation — no workers, no jax:

- codec roundtrips (column batches, result frames with every outcome
  shape the farm produces);
- the slot lifecycle incl. backpressure (acquire waits, counts stalls,
  then raises RingStall — never deadlocks) and producer backout;
- generation staleness: accept() after a reclaim refuses the old ref;
- reclaim semantics (full vs producer-held-only — the result ring must
  preserve consumer-held slots backing live lazy patches);
- segment hygiene: attach/untrack, close-unlinks-everything, and the
  SlotRef int-cast pin (the PR 14 np.int64 JSONL bug class).
"""
import json
import pickle

import numpy as np
import pytest

from automerge_tpu.errors import DecodeError, DeviceFaultError
from automerge_tpu.parallel import shm


def _ring(tag="t", nslots=2, slot_bytes=4096):
    return shm.ColumnRing.create(tag, nslots, slot_bytes)


def _no_am_segments():
    import glob
    return glob.glob("/dev/shm/am-*") == []


# --------------------------------------------------------------------- #
# codecs


def test_column_codec_roundtrip():
    groups = [
        (0, (b"alpha", b"", b"\x00\x01\x02")),
        (17, ()),
        (3, (b"z" * 1000,)),
    ]
    blob = shm.encode_columns(groups)
    assert len(blob) == shm.measure_columns(groups)
    assert shm.decode_columns(memoryview(blob)) == groups


def test_column_codec_writes_into_mapped_slot():
    ring = _ring()
    try:
        groups = [(5, (b"hello", b"world"))]
        slot, gen = ring.acquire()
        view = ring.slot_view(slot)
        used = shm.encode_columns_into(view, groups)
        del view
        assert used == shm.measure_columns(groups)
        ref = ring.publish(slot, gen, used)
        got = ring.accept(ref)
        assert shm.decode_columns(got) == groups
        del got
        ring.release(ref.slot)
    finally:
        ring.close()
    assert _no_am_segments()


def test_result_codec_roundtrip_all_outcome_shapes():
    patches = pickle.dumps([{"objId": "_root", "action": "put"}])
    wires = [
        ("applied", None, None, (), False),
        ("quarantined", pickle.dumps(ValueError("boom")), "decode",
         ("deadbeef", b"\xff\x00raw"), False),
        ("applied", None, None, (), True),  # device fallback
    ]
    frame = shm.encode_result(patches, wires)
    (off, length), got = shm.decode_result(memoryview(frame))
    assert memoryview(frame)[off:off + length] == patches
    assert len(got) == len(wires)
    for want, have in zip(wires, got):
        status, blob, kind, offending, fallback = have
        assert (status, blob, kind, fallback) == (
            want[0], want[1], want[2], want[4])
        assert tuple(offending) == tuple(want[3])
        # str/bytes hash tags survive the flags byte
        for w, h in zip(want[3], offending):
            assert type(w) is type(h)


def test_result_codec_common_case_is_compact():
    # ("applied", None, None, (), False) must stay single-digit bytes —
    # the result frame is per-doc, so bloat here scales with the batch
    frame = shm.encode_result(b"", [("applied", None, None, (), False)])
    assert len(frame) <= 8 + 4 + 1 + 4 + len(b"applied") + 4


# --------------------------------------------------------------------- #
# slot lifecycle + backpressure


def test_slot_lifecycle_and_capacity_stall():
    ring = _ring(nslots=2)
    try:
        refs = []
        for i in range(2):
            slot, gen = ring.acquire(timeout=0.05)
            view = ring.slot_view(slot)
            view[:1] = bytes([i])
            del view
            refs.append(ring.publish(slot, gen, 1))
        assert ring.slots_in_use() == 2
        stalls_before = ring.stalls
        with pytest.raises(shm.RingStall):
            ring.acquire(timeout=0.05)
        assert ring.stalls == stalls_before + 1
        # RingStall is a classifiable DeviceFaultError, not a bare raise
        assert isinstance(shm.RingStall("x"), DeviceFaultError)
        # consumer frees one slot -> producer unblocks
        v = ring.accept(refs[0])
        assert bytes(v) == b"\x00"
        del v
        ring.release(refs[0].slot)
        slot, gen = ring.acquire(timeout=0.05)
        assert slot == refs[0].slot
        ring.abandon(slot)  # producer backout: straight to FREE
        assert ring.slots_in_use() == 1
    finally:
        ring.close()
    assert _no_am_segments()


def test_accept_refuses_stale_generation_after_reclaim():
    ring = _ring()
    try:
        slot, gen = ring.acquire()
        ref = ring.publish(slot, gen, 0)
        assert ring.reclaim() == 1  # "crash": the ref is now stale
        slot2, gen2 = ring.acquire()
        assert slot2 == slot and gen2 == gen + 1
        ring.publish(slot2, gen2, 0)
        with pytest.raises(DeviceFaultError):
            ring.accept(ref)
        # the re-published current ref still accepts fine
        v = ring.accept(shm.SlotRef(slot2, gen2, 0))
        del v
        ring.release(slot2)
    finally:
        ring.close()
    assert _no_am_segments()


def test_accept_refuses_length_mismatch_and_bad_slot():
    ring = _ring()
    try:
        slot, gen = ring.acquire()
        ring.publish(slot, gen, 8)
        with pytest.raises(DecodeError):
            ring.accept(shm.SlotRef(slot, gen, 9))
        with pytest.raises(DecodeError):
            ring.accept(shm.SlotRef(99, 1, 0))
    finally:
        ring.close()


def test_reclaim_preserves_consumer_held_when_asked():
    ring = _ring(nslots=3)
    try:
        # slot A: consumer-held (a live lazy patch), slot B: producer-held
        # (the dead worker was mid-write), slot C: free
        sa, ga = ring.acquire()
        va = ring.accept(ring.publish(sa, ga, 0))
        va.release()  # drops the VIEW only; the slot stays CONSUMER_HELD
        sb, _gb = ring.acquire()
        assert ring.slots_in_use() == 2
        # the result-ring reclaim shape: only the dead producer's slot
        assert ring.reclaim(held_by_producer_only=True) == 1
        assert ring.slots_in_use() == 1
        # the send-ring reclaim shape frees everything
        assert ring.reclaim() == 1
        assert ring.slots_in_use() == 0
        assert sb is not None
    finally:
        ring.close()
    assert _no_am_segments()


# --------------------------------------------------------------------- #
# segment hygiene + control-frame pins


def test_attach_maps_same_bytes_and_owner_unlinks():
    ring = _ring()
    peer = shm.attach_ring(ring.name)
    try:
        slot, gen = ring.acquire()
        view = ring.slot_view(slot)
        view[:5] = b"cross"
        del view
        ref = ring.publish(slot, gen, 5)
        got = peer.accept(ref)
        assert bytes(got) == b"cross"
        del got
        peer.release(ref.slot)
    finally:
        peer.close()       # attacher: close only, never unlink
        assert not _no_am_segments()
        ring.close()       # owner: close + unlink
    assert _no_am_segments()


def test_attach_rejects_non_ring_segment():
    from multiprocessing import shared_memory
    seg = shared_memory.SharedMemory(create=True, size=4096)
    try:
        with pytest.raises(DecodeError):
            shm.attach_ring(seg.name)
    finally:
        seg.close()
        seg.unlink()


def test_ring_sizes_env_knobs(monkeypatch):
    monkeypatch.setenv("AM_MESH_SHM_SLOTS", "5")
    monkeypatch.setenv("AM_MESH_SHM_SLOT_BYTES", "8192")
    assert shm.ring_sizes() == (5, 8192)
    monkeypatch.setenv("AM_MESH_SHM_SLOTS", "1")      # floor: 2
    monkeypatch.setenv("AM_MESH_SHM_SLOT_BYTES", "7")  # floor: 4096
    assert shm.ring_sizes() == (2, 4096)


def test_slotref_is_plain_int_and_pickles():
    """The PR 14 satellite pin: ring offsets/lengths/generations reach
    flight events and JSONL dumps, so SlotRef fields must be plain int
    at construction even when fed np.int64 — ``json.dumps`` must never
    see a numpy scalar."""
    ref = shm.SlotRef(np.int64(3), np.int64(7), np.int64(4096))
    assert type(ref.slot) is int
    assert type(ref.generation) is int
    assert type(ref.nbytes) is int
    json.dumps({"slot": ref.slot, "generation": ref.generation,
                "nbytes": ref.nbytes})
    clone = pickle.loads(pickle.dumps(ref))
    assert (clone.slot, clone.generation, clone.nbytes) == (3, 7, 4096)
    assert type(clone.slot) is int


def test_shm_available_probe_is_cached_and_true_here():
    assert shm.shm_available() is True
    assert shm.shm_available() is True  # cached, no re-probe crash
    assert _no_am_segments()
