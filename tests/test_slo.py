"""SLO engine suite (automerge_tpu/obs/slo.py).

Covers the ISSUE 13 contract:
- objective validation and the three kinds (latency on the log2 bucket
  grid, availability over good/bad counters, ratio gauges read direct);
- multi-window burn rates computed on an injected clock (the simulated
  ``ManualClock`` — the same engine runs on ``time.monotonic`` in
  ``serve_forever``'s flusher);
- vacuous pass on no data (an idle service has not missed its SLO);
- ``export()`` mirroring verdicts into ``slo.*`` gauges, the exposition
  ``# SLO`` comment lines, and snapshot embedding;
- the canned ``default_serve_slos`` / ``default_mesh_slos`` sets and the
  bench gate predicate ``verdicts_ok``.
"""
import pytest

from automerge_tpu.obs.export import render_exposition, snapshot_record
from automerge_tpu.obs.metrics import MetricsRegistry
from automerge_tpu.obs.slo import (
    DEFAULT_WINDOWS,
    Objective,
    SLOEngine,
    availability_objective,
    default_mesh_slos,
    default_serve_slos,
    latency_objective,
    ratio_objective,
    render_verdicts,
    verdicts_ok,
)
from automerge_tpu.testing.chaos import ManualClock


def _registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.enable()
    return reg


# ---------------------------------------------------------------------- #
# objective declaration

def test_objective_validation():
    with pytest.raises(ValueError, match="unknown SLO kind"):
        Objective("x", "throughput", "m")
    with pytest.raises(ValueError, match="needs budget_ms"):
        Objective("x", "latency", "m")
    with pytest.raises(ValueError, match="target"):
        Objective("x", "ratio", "m", target=0.0)
    with pytest.raises(ValueError, match="target"):
        Objective("x", "ratio", "m", target=1.5)
    # the helpers build valid frozen objectives
    o = latency_objective("lat", "rq.ms", 250.0, target=0.95)
    assert (o.kind, o.budget_ms, o.target) == ("latency", 250.0, 0.95)
    o = availability_objective("av", "good", ("bad1", "bad2"))
    assert o.bad_metrics == ("bad1", "bad2")
    assert ratio_objective("r", "g", 0.5).kind == "ratio"


# ---------------------------------------------------------------------- #
# the three compliance kinds

def test_latency_compliance_is_bucketed_on_the_log2_grid():
    """9 fast observations + 1 slow one against a 250 ms budget: the fast
    bucket's upper bound sits under the budget, the slow one's above, so
    compliance is exactly 0.9 — pass at target 0.9, breach at 0.99."""
    reg = _registry()
    hist = reg.histogram("rq.ms", "test latencies")
    for _ in range(9):
        hist.observe(1.0)
    hist.observe(1000.0)
    clock = ManualClock()
    eng = SLOEngine(
        [latency_objective("lat", "rq.ms", 250.0, target=0.9)],
        clock=clock, registry=reg,
    )
    v = eng.evaluate()[0]
    assert v["compliance"] == pytest.approx(0.9)
    assert v["ok"]
    strict = SLOEngine(
        [latency_objective("lat", "rq.ms", 250.0, target=0.99)],
        clock=clock, registry=reg,
    )
    assert not strict.evaluate()[0]["ok"]


def test_availability_compliance_over_good_and_bad_counters():
    reg = _registry()
    reg.counter("ok.count", "").inc(999)
    reg.counter("bad.count", "").inc(1)
    eng = SLOEngine(
        [availability_objective("av", "ok.count", ("bad.count",),
                                target=0.999)],
        clock=ManualClock(), registry=reg,
    )
    v = eng.evaluate()[0]
    assert v["compliance"] == pytest.approx(0.999)
    assert v["ok"]


def test_ratio_gauge_is_read_direct():
    reg = _registry()
    reg.gauge("conv.ratio", "").set(0.95)
    eng = SLOEngine(
        [ratio_objective("conv", "conv.ratio", 0.99)],
        clock=ManualClock(), registry=reg,
    )
    v = eng.evaluate()[0]
    assert v["compliance"] == pytest.approx(0.95)
    assert not v["ok"]
    reg.gauge("conv.ratio").set(0.999)
    assert eng.evaluate()[0]["ok"]


def test_no_data_passes_vacuously():
    """An idle service has not missed its SLO: unregistered metrics (and
    empty histograms) yield compliance None and ok=True."""
    eng = SLOEngine(
        [latency_objective("lat", "never.recorded", 100.0),
         availability_objective("av", "no.good", ("no.bad",))],
        clock=ManualClock(), registry=_registry(),
    )
    verdicts = eng.evaluate()
    assert all(v["compliance"] is None for v in verdicts)
    assert all(v["burn_rate"] is None for v in verdicts)
    assert verdicts_ok(verdicts)


# ---------------------------------------------------------------------- #
# burn rates on the injected clock

def test_multi_window_burn_rates_on_manual_clock():
    """A clean period then an error burst: both windows see the burst's
    error fraction spend the budget 10x faster than sustainable, so the
    objective is 'burning'; a fully clean history burns at 0."""
    reg = _registry()
    good, bad = reg.counter("g", ""), reg.counter("b", "")
    clock = ManualClock()
    eng = SLOEngine(
        [availability_objective("av", "g", ("b",), target=0.9)],
        clock=clock, registry=reg, windows=(10.0, 1000.0),
    )
    good.inc(100)
    eng.sample()                       # t=0: all good so far
    clock.advance(100.0)
    bad.inc(50)                        # the burst: 50 errors, 0 successes
    v = eng.evaluate()[0]              # t=100
    assert v["compliance"] == pytest.approx(100 / 150)
    assert not v["ok"]
    # both windows' deltas are pure errors: burn = (1 - 0) / 0.1 = 10
    assert [w["window_s"] for w in v["windows"]] == [10.0, 1000.0]
    assert all(w["burn_rate"] == pytest.approx(10.0) for w in v["windows"])
    assert v["burn_rate"] == pytest.approx(10.0)
    assert v["burning"]


def test_clean_history_burns_at_zero():
    reg = _registry()
    good = reg.counter("g", "")
    clock = ManualClock()
    eng = SLOEngine(
        [availability_objective("av", "g", ("b",), target=0.9)],
        clock=clock, registry=reg, windows=DEFAULT_WINDOWS,
    )
    for _ in range(5):
        good.inc(10)
        eng.sample()
        clock.advance(30.0)
    v = eng.evaluate()[0]
    assert v["ok"] and not v["burning"]
    assert all(w["burn_rate"] == pytest.approx(0.0) for w in v["windows"])


def test_sample_history_is_bounded():
    from automerge_tpu.obs import slo as slo_mod

    reg = _registry()
    reg.counter("g", "").inc()
    clock = ManualClock()
    eng = SLOEngine(
        [availability_objective("av", "g", ())],
        clock=clock, registry=reg,
    )
    for _ in range(slo_mod.MAX_SAMPLES + 50):
        eng.sample()
        clock.advance(1.0)
    assert len(eng._samples["av"]) == slo_mod.MAX_SAMPLES


# ---------------------------------------------------------------------- #
# export surfaces

def test_export_mirrors_verdicts_into_slo_gauges():
    reg = _registry()
    reg.gauge("conv.ratio", "").set(0.5)
    clock = ManualClock()
    eng = SLOEngine(
        [ratio_objective("conv", "conv.ratio", 0.99),           # breach
         availability_objective("av", "g", ("b",), target=0.9)],  # ok
        clock=clock, registry=reg,
    )
    eng.sample()                      # t=0 baseline: no traffic yet
    reg.counter("g", "").inc(99)
    reg.counter("b", "").inc(1)
    clock.advance(100.0)
    verdicts = eng.export()
    assert reg.find("slo.conv.compliance").value == pytest.approx(0.5)
    assert reg.find("slo.conv.ok").value == 0.0
    assert reg.find("slo.av.compliance").value == pytest.approx(0.99)
    assert reg.find("slo.av.ok").value == 1.0
    assert reg.find("slo.av.burn_rate").value == pytest.approx(0.1)
    assert reg.find("slo.breaches").value == 1.0
    assert not verdicts_ok(verdicts)


def test_render_verdicts_table():
    reg = _registry()
    reg.gauge("conv.ratio", "").set(0.5)
    eng = SLOEngine(
        [ratio_objective("conv", "conv.ratio", 0.99)],
        clock=ManualClock(), registry=reg,
    )
    table = render_verdicts(eng.evaluate())
    assert "conv" in table and "BREACH" in table
    assert "target=0.990" in table
    assert render_verdicts([]) == "(no SLOs declared)"


def test_exposition_page_carries_slo_comment_lines():
    reg = _registry()
    reg.counter("g", "").inc(100)
    eng = SLOEngine(
        [availability_objective("av", "g", (), target=0.999)],
        clock=ManualClock(), registry=reg,
    )
    verdicts = eng.export()
    page = render_exposition(registry=reg, slo=verdicts)
    slo_lines = [ln for ln in page.splitlines() if ln.startswith("# SLO")]
    # one comment line per objective window, plus the slo.* gauges as
    # ordinary samples
    assert len(slo_lines) == len(DEFAULT_WINDOWS)
    assert all("av" in ln and "ok" in ln for ln in slo_lines)
    assert any(ln.startswith("slo_av_ok") for ln in page.splitlines())


def test_snapshot_record_embeds_verdicts():
    reg = _registry()
    reg.gauge("conv.ratio", "").set(1.0)
    eng = SLOEngine(
        [ratio_objective("conv", "conv.ratio", 0.99)],
        clock=ManualClock(), registry=reg,
    )
    verdicts = eng.evaluate()
    record = snapshot_record(t=1.5, registry=reg, slo=verdicts)
    assert record["slo"] == verdicts
    assert snapshot_record(t=1.5, registry=reg).get("slo") is None


# ---------------------------------------------------------------------- #
# canned sets

def test_default_serve_slos_shape():
    slos = default_serve_slos()
    assert [o.name for o in slos] == [
        "serve_latency", "serve_availability", "serve_convergence",
    ]
    assert slos[0].metric == "serve.request.e2e_ms"
    # the load harness swaps in the metrics-only histogram
    swapped = default_serve_slos(latency_metric="serve.sync.latency_ms",
                                 budget_ms=1000.0)
    assert swapped[0].metric == "serve.sync.latency_ms"
    assert swapped[0].budget_ms == 1000.0


def test_default_mesh_slos_shape():
    slos = default_mesh_slos()
    assert [o.name for o in slos] == ["mesh_delivery", "mesh_workers"]
    assert all(o.kind == "availability" for o in slos)
    assert slos[0].bad_metrics == ("mesh.worker.lost_docs",)
    assert slos[1].bad_metrics == ("mesh.worker.crashes",)
