"""amprof observatory suite (automerge_tpu/obs/prof.py + the export
pivots it feeds).

Covers the PR 14 acceptance contract:
- ProfiledProgram: disabled fall-through (one attribute test), cache
  growth -> compile attribution, dispatch tallies on an injected clock,
  recompile flight events carrying program identity, re-registration
  keeping tallies;
- Observatory: storm detector (>= K compiles inside the window fires
  ``prof.recompile.storm`` once and re-arms; a slow drizzle never
  fires), table() plain-int stats, enabled_observatory state restore;
- Sampler: slab/page math (occupancy, fragmentation from the free-list
  run structure), DecodeCache and change-column byte accounting, and
  the int-cast guarantee (np.int64 never reaches a sample dict — the
  JSONL stringification bug);
- export pivots: ``shard_table`` folding ``mesh.pipe.<s>.*`` rows in
  alongside ``mesh.shard.<s>.*`` without shadowing the serving
  ``serve.flush.shard.<s>.docs`` family, and ``program_table`` rolling
  up ``prof.program.<name>.*``.
"""
import json

import numpy as np

from automerge_tpu.obs.export import program_table, shard_table
from automerge_tpu.obs.flight import FlightRecorder
from automerge_tpu.obs.metrics import MetricsRegistry
from automerge_tpu.obs.prof import (
    Observatory,
    Sampler,
    enabled_observatory,
    get_observatory,
    shape_bucket,
)


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class FakeJit:
    """A jitted-function stand-in: every distinct arg shape grows the
    tracing cache by one, like jax.jit's per-signature cache."""

    __name__ = "fake_jit"

    def __init__(self):
        self.shapes = set()
        self.calls = 0

    def __call__(self, x, *rest, **kwargs):
        self.calls += 1
        self.shapes.add(getattr(x, "shape", None))
        return x

    def _cache_size(self):
        return len(self.shapes)


def make_observatory(**kwargs):
    registry = MetricsRegistry(enabled=True)
    flight = FlightRecorder(clock=lambda: 0.0)
    flight.enabled = True
    clock = ManualClock()
    obs = Observatory(registry=registry, flight=flight, clock=clock, **kwargs)
    return obs, registry, flight, clock


def arr(*shape):
    return np.zeros(shape, np.int32)


# ---------------------------------------------------------------------- #
# ProfiledProgram
# ---------------------------------------------------------------------- #

def test_disabled_program_falls_through_without_tallies():
    obs, registry, _flight, _clock = make_observatory()
    fn = FakeJit()
    prog = obs.register("t.prog", fn)
    out = prog(arr(4))
    assert out.shape == (4,)
    assert fn.calls == 1
    assert prog.dispatches == 0 and prog.compiles == 0
    assert "prof.program.t.prog.dispatches" not in registry.as_dict()


def test_enabled_program_attributes_compiles_and_dispatches():
    obs, registry, _flight, clock = make_observatory()
    prog = obs.register("t.prog", FakeJit())
    obs.enable()
    prog(arr(4))          # new shape: compile
    clock.t += 0.25
    prog(arr(4))          # warm shape: plain dispatch
    prog(arr(8))          # new shape: compile
    assert prog.compiles == 2
    assert prog.dispatches == 3
    snap = registry.as_dict()
    assert snap["prof.program.t.prog.compiles"]["value"] == 2
    assert snap["prof.program.t.prog.dispatches"]["value"] == 3
    assert snap["prof.program.t.prog.dispatch_ms"]["count"] == 3


def test_dispatch_wall_time_reads_the_injected_clock():
    obs, _registry, _flight, clock = make_observatory()
    prog = obs.register("t.prog", FakeJit())
    obs.enable()

    original = prog.fn

    def slow(x):
        clock.t += 0.5
        return original(x)

    prog.fn = slow
    prog(arr(4))
    assert prog.stats()["dispatch_ms"] == 500.0


def test_recompile_event_carries_program_identity():
    obs, _registry, flight, _clock = make_observatory()
    prog = obs.register("t.prog", FakeJit())
    obs.enable()
    prog(arr(4), arr(2, 2))
    events = [e for e in flight.snapshot() if e["event"] == "engine.recompile"]
    assert len(events) == 1
    fields = events[0]["fields"]
    assert fields["program"] == "t.prog"
    assert fields["fn"] == "fake_jit"
    assert fields["cache_size"] == 1
    assert [tuple(s) for s in fields["shapes"]] == [(2, 2), (4,)]


def test_recompile_event_fires_even_when_observatory_disabled():
    """Flight emission replaces the old engine._dispatch probe, which was
    gated on the flight recorder alone — the observatory flag only
    gates the tallies/instruments."""
    obs, _registry, flight, _clock = make_observatory()
    prog = obs.register("t.prog", FakeJit())
    _out, grew, _dt = prog.call_profiled((arr(4),), {})
    assert grew == 1
    assert [e["event"] for e in flight.snapshot()] == ["engine.recompile"]
    assert prog.dispatches == 0  # disabled: no tallies


def test_unprobeable_fn_reports_minus_one_growth():
    obs, _registry, flight, _clock = make_observatory()
    prog = obs.register("t.plain", lambda x: x)
    obs.enable()
    _out, grew, _dt = prog.call_profiled((arr(4),), {})
    assert grew == -1
    assert prog.compiles == 0
    assert len(flight) == 0


def test_reregistration_rebinds_fn_but_keeps_tallies():
    obs, _registry, _flight, _clock = make_observatory()
    prog = obs.register("t.prog", FakeJit())
    obs.enable()
    prog(arr(4))
    reloaded = FakeJit()
    again = obs.register("t.prog", reloaded)
    assert again is prog
    assert prog.fn is reloaded
    assert prog.compiles == 1


def test_shape_bucket_walks_nested_containers():
    bucket = shape_bucket(
        (arr(4), [arr(2, 3), (arr(4),)]), {"k": {"n": arr(5)}})
    assert bucket == [(2, 3), (4,), (5,)]
    assert shape_bucket((1, "x"), {}) == []


# ---------------------------------------------------------------------- #
# Observatory: storm detector, table, context manager
# ---------------------------------------------------------------------- #

def test_storm_fires_once_and_rearms():
    obs, _registry, flight, _clock = make_observatory(
        storm_compiles=3, storm_window_s=10.0)
    prog = obs.register("t.prog", FakeJit())
    obs.enable()
    for n in range(1, 6):
        prog(arr(n))  # every call is a fresh shape: 5 compiles
    storms = [e for e in flight.snapshot() if e["event"] == "prof.recompile.storm"]
    # 3 compiles -> storm, detector clears, 2 more compiles stay below K
    assert len(storms) == 1
    fields = storms[0]["fields"]
    assert fields["program"] == "t.prog"
    assert fields["compiles"] == 3
    assert fields["window_s"] == 10.0
    assert fields["buckets"]  # the offending bucket sequence rides along


def test_slow_compile_drizzle_never_storms():
    obs, _registry, flight, clock = make_observatory(
        storm_compiles=3, storm_window_s=10.0)
    prog = obs.register("t.prog", FakeJit())
    obs.enable()
    for n in range(1, 7):
        prog(arr(n))
        clock.t += 6.0  # compiles 6s apart: never 3 inside a 10s window
    assert not [e for e in flight.snapshot() if e["event"] == "prof.recompile.storm"]


def test_table_reports_only_active_programs_as_plain_ints():
    obs, _registry, _flight, _clock = make_observatory()
    obs.register("t.idle", FakeJit())
    prog = obs.register("t.busy", FakeJit())
    obs.enable()
    prog(arr(4))
    table = obs.table()
    assert list(table) == ["t.busy"]
    stats = table["t.busy"]
    assert type(stats["compiles"]) is int
    assert type(stats["dispatches"]) is int
    assert stats["cache_size"] == 1
    assert stats["buckets"] == [[[4]]]
    json.dumps(table)  # fully serializable, no default= needed


def test_enabled_observatory_restores_prior_state():
    obs = get_observatory()
    assert obs.enabled is False
    with enabled_observatory():
        assert obs.enabled is True
        with enabled_observatory():
            assert obs.enabled is True
        assert obs.enabled is True
    assert obs.enabled is False


def test_global_registration_covers_the_tpu_programs():
    """Importing the tpu layer registers every named program — the
    observatory is the one place recompiles can be attributed, so the
    roster is pinned here."""
    import automerge_tpu.tpu.fingerprint  # noqa: F401 - registration side effect
    import automerge_tpu.tpu.paging  # noqa: F401
    import automerge_tpu.tpu.sync_batch  # noqa: F401

    names = set(get_observatory().programs())
    assert {
        "engine.apply_ops", "engine.visible_cmp", "engine.gather_rows",
        "paging.apply_ops", "paging.probe_ops", "paging.visible_plain",
        "paging.visible_ranked", "paging.patch_column_rows",
        "paging.dense_view", "paging.adopt_rows",
        "sync.build_filters", "sync.query_filters",
        "sync.fingerprint_ranges",
    } <= names


# ---------------------------------------------------------------------- #
# Sampler
# ---------------------------------------------------------------------- #

class FakePages:
    def __init__(self, allocated, free):
        self._allocated = allocated
        self._free = list(free)
        self.page_size = np.int64(64)  # deliberately numpy: must be cast

    @property
    def allocated(self):
        return np.int64(self._allocated)

    @property
    def free_count(self):
        return len(self._free)


class FakeEngine:
    def __init__(self, pages, lengths):
        self.pages = pages
        self.lengths = np.asarray(lengths, np.int64)


class FakeCols:
    def __init__(self, nbytes, sorted_nbytes=0):
        self.arr = np.zeros(nbytes, np.uint8)
        self._sorted = (
            (np.zeros(sorted_nbytes, np.uint8),) if sorted_nbytes else None)


class FakeFarm:
    def __init__(self, engine, cols_cache):
        self.engine = engine
        self._cols_cache = cols_cache


def make_sampler():
    registry = MetricsRegistry(enabled=True)
    clock = ManualClock()
    return Sampler(registry=registry, clock=clock), registry, clock


def test_sampler_page_math_and_int_casts():
    # free list {3,4,5, 9}: longest run 3 of 4 free -> fragmentation 0.25
    engine = FakeEngine(FakePages(allocated=6, free=[9, 3, 5, 4]),
                        lengths=[np.int64(100), np.int64(92)])
    sampler, registry, _clock = make_sampler()
    sample = sampler.sample(farm=FakeFarm(engine, {}))
    assert sample["pages_allocated"] == 6
    assert sample["pages_free"] == 4
    assert sample["rows"] == 192
    assert sample["occupancy"] == 0.5       # 192 rows / (6 * 64)
    assert sample["fragmentation"] == 0.25  # 1 - 3/4
    for key, value in sample.items():
        assert not isinstance(value, np.generic), (key, type(value))
    # the satellite bug: np.int64 leaves stringify under default=str
    assert '"' not in json.dumps(list(sample.values()))
    snap = registry.as_dict()
    assert snap["prof.mem.pages.allocated"]["value"] == 6
    assert snap["prof.mem.pages.fragmentation"]["value"] == 0.25


def test_sampler_counts_change_col_bytes_and_sentinels():
    engine = FakeEngine(FakePages(allocated=1, free=[]), lengths=[4])
    cache = {
        "a": FakeCols(100),
        "b": FakeCols(40, sorted_nbytes=10),
        "c": object(),  # uncacheable sentinel: counted, zero bytes
    }
    sampler, registry, _clock = make_sampler()
    sample = sampler.sample(farm=FakeFarm(engine, cache))
    assert sample["change_cols_bytes"] == 150
    assert sample["change_cols_entries"] == 3
    assert registry.as_dict()["prof.mem.change_cols.bytes"]["value"] == 150


def test_sampler_ring_is_bounded():
    engine = FakeEngine(FakePages(allocated=1, free=[]), lengths=[1])
    sampler, _registry, clock = make_sampler()
    sampler.samples = type(sampler.samples)(maxlen=4)
    for _ in range(10):
        clock.t += 1.0
        sampler.sample(engine=engine)
    assert len(sampler.samples) == 4
    assert sampler.samples[-1]["t"] == 10.0


def test_sampler_decode_cache_bytes_from_live_module():
    from automerge_tpu.codecs import DecodeCache

    cache = DecodeCache(4, name="prof-test")
    cache.put(b"x" * 100, {"decoded": True})
    sampler, _registry, _clock = make_sampler()
    sample = sampler.sample()
    assert sample["decode_cache_bytes"] >= 100
    assert type(sample["decode_cache_bytes"]) is int
    cache.clear()


# ---------------------------------------------------------------------- #
# export pivots
# ---------------------------------------------------------------------- #

def _hist(count, total):
    return {"type": "histogram", "count": count, "sum": total, "p99": 1.0}


def test_shard_table_pivots_pipe_rows_without_shadowing():
    snapshot = {
        "mesh.shard.0.docs": {"type": "counter", "value": 12},
        "mesh.shard.0.dispatch_ms": _hist(3, 42.0),
        "mesh.pipe.0.bytes_out": {"type": "counter", "value": 512},
        "mesh.pipe.0.bytes_in": {"type": "counter", "value": 2048},
        "mesh.pipe.0.serialize_ms": _hist(4, 1.5),
        "serve.flush.shard.0.docs": {"type": "counter", "value": 7},
        "mesh.pipe.1.bytes_out": {"type": "counter", "value": 99},
        "farm.changes.applied": {"type": "counter", "value": 5},
    }
    table = shard_table(snapshot)
    assert sorted(table) == [0, 1]
    row = table[0]
    # three families, one row, no shadowing
    assert row["docs"] == 12
    assert row["pipe.bytes_out"] == 512
    assert row["pipe.bytes_in"] == 2048
    assert row["flush.docs"] == 7
    assert row["pipe.serialize_ms"]["count"] == 4
    assert row["dispatch_ms"]["sum"] == 42.0
    assert table[1] == {"pipe.bytes_out": 99}


def test_program_table_rolls_up_prof_rows():
    snapshot = {
        "prof.program.paging.apply_ops.compiles":
            {"type": "counter", "value": 2},
        "prof.program.paging.apply_ops.dispatches":
            {"type": "counter", "value": 9},
        "prof.program.paging.apply_ops.dispatch_ms": _hist(9, 123.4567),
        "prof.program.sync.build_filters.dispatches":
            {"type": "counter", "value": 3},
        "mesh.shard.0.docs": {"type": "counter", "value": 1},
    }
    table = program_table(snapshot)
    assert list(table) == ["paging.apply_ops", "sync.build_filters"]
    assert table["paging.apply_ops"]["compiles"] == 2
    assert table["paging.apply_ops"]["dispatch_ms"] == 123.457
    assert table["sync.build_filters"] == {"dispatches": 3}
