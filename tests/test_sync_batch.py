"""Batched device Bloom filters must be bit-identical to the sequential
wire-format implementation (sync.py BloomFilter) and interoperate with it."""
from hashlib import sha256
from math import ceil

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu import sync as Sync
from automerge_tpu.sync import BITS_PER_ENTRY
from automerge_tpu.tpu import sync_batch


def fake_hashes(tag, n):
    return [sha256(f"{tag}-{i}".encode()).hexdigest() for i in range(n)]


class TestBatchedBloom:
    def test_bit_identical_to_sequential(self):
        hash_lists = [fake_hashes("a", 5), fake_hashes("b", 17), [], fake_hashes("c", 1)]
        xyz, counts = sync_batch.pack_hashes(hash_lists)
        num_words = int(ceil(xyz.shape[1] * BITS_PER_ENTRY / 32)) or 1
        words, modulo = sync_batch.build_filters(xyz, counts, num_words)
        wire = sync_batch.filters_to_bytes(words, modulo, counts)
        for hashes, bloom_bytes in zip(hash_lists, wire):
            expected = Sync.BloomFilter(hashes).bytes
            assert bloom_bytes == expected

    def test_batched_query_matches_sequential(self):
        hash_lists = [fake_hashes("x", 20), fake_hashes("y", 8)]
        queries = [fake_hashes("x", 30), fake_hashes("y", 30)]  # half known, half not
        xyz, counts = sync_batch.pack_hashes(hash_lists)
        num_words = int(ceil(xyz.shape[1] * BITS_PER_ENTRY / 32)) or 1
        words, modulo = sync_batch.build_filters(xyz, counts, num_words)
        q_xyz, _q_counts = sync_batch.pack_hashes(queries)
        contained = np.asarray(sync_batch.query_filters(words, modulo, counts, q_xyz))
        for b, (hashes, qs) in enumerate(zip(hash_lists, queries)):
            bloom = Sync.BloomFilter(hashes)
            for c, q in enumerate(qs):
                assert bool(contained[b, c]) == bloom.contains_hash(q), (b, c)

    def test_empty_filter_contains_nothing(self):
        xyz, counts = sync_batch.pack_hashes([[]])
        words, modulo = sync_batch.build_filters(xyz, counts, 1)
        q_xyz, _ = sync_batch.pack_hashes([fake_hashes("q", 3)])
        contained = np.asarray(sync_batch.query_filters(words, modulo, counts, q_xyz))
        assert not contained.any()

    def test_batched_have_interoperates_with_protocol(self):
        """Filters built on device drive the sequential getChangesToSend."""
        docs = []
        for i in range(3):
            doc = am.init(f"{i:08d}" if i else "aaaaaaaa")
            for j in range(4):
                doc = am.change(doc, lambda d, j=j: d.__setitem__(f"k{j}", j))
            docs.append(doc)
        backends = [am.Frontend.get_backend_state(doc, "test") for doc in docs]
        haves = sync_batch.batched_have_filters(backends, [[], [], []])
        for backend, have in zip(backends, haves):
            # A peer that already has everything: nothing to send
            to_send = Sync.get_changes_to_send(backend, [have], [])
            assert to_send == []
