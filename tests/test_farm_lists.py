"""List/Text support in TpuDocFarm: byte-exact differential suite.

List-touching docs route through the farm's embedded reference walk, so
their incremental patches must equal the sequential engine's exactly (dict
equality — the reference's order-dependent edit-stream quirks included).
Materialised-document equality is additionally asserted both ways (the
cross-backend doc-equality half of the reference's test/wasm.js)."""
import random

import pytest

from automerge_tpu import frontend as Frontend
from automerge_tpu.frontend.datatypes import Counter, Table, Text
from automerge_tpu.columnar import decode_change_columns, encode_change
from automerge_tpu.opset import OpSet
from automerge_tpu.tpu.farm import TpuDocFarm


def make_change(actor, seq, start_op, deps, ops):
    buf = encode_change(
        {"actor": actor, "seq": seq, "startOp": start_op, "time": 0,
         "deps": sorted(deps), "ops": ops}
    )
    return buf, decode_change_columns(buf)["hash"]


def to_plain(value):
    """Recursively strips frontend wrapper types down to plain Python."""
    if isinstance(value, Text):
        return [to_plain(v) for v in value]
    if isinstance(value, Table):
        return {rid: to_plain(value.by_id(rid)) for rid in value.ids}
    if isinstance(value, Counter):
        return ("counter", value.value)
    if isinstance(value, dict):
        return {k: to_plain(v) for k, v in value.items()}
    if isinstance(value, list):
        return [to_plain(v) for v in value]
    return value


def materialize(doc):
    return to_plain(dict(doc))


class ListWorkload:
    """Random workload over one doc mixing list ops (insert at head /
    after random element, update, delete) with map keys; tracks enough
    state to emit causally-valid binary changes."""

    def __init__(self, seed, actors=("aaaaaaaa", "bbbbbbbb")):
        self.rng = random.Random(seed)
        self.actors = actors
        self.seqs = dict.fromkeys(actors, 0)
        self.last_hash = dict.fromkeys(actors, None)
        self.max_op = 0
        # list objects: objectId -> list of live elemIds (host mirror of
        # RGA positions is NOT tracked; refs are picked from live elems)
        self.lists = {}
        self.list_keys = {}  # objectId -> root key
        self.elem_winner = {}  # (obj, elemId) -> winning opId
        self.map_winner = {}  # key -> opId

    def _new_change(self, ops_builder):
        actor = self.rng.choice(self.actors)
        self.seqs[actor] += 1
        start = self.max_op + 1
        ops = ops_builder(start, actor)
        if not ops:
            self.seqs[actor] -= 1
            return None
        deps = set(self.heads)
        if self.last_hash[actor]:
            deps.add(self.last_hash[actor])
        buf, h = make_change(actor, self.seqs[actor], start, deps, ops)
        self.last_hash[actor] = h
        self.max_op = start + len(ops) - 1
        return buf

    def next_change(self, heads):
        self.heads = heads
        rng = self.rng

        def build(start, actor):
            ops = []
            ctr = start
            for _ in range(rng.randrange(1, 4)):
                roll = rng.random()
                if (roll < 0.15 and len(self.lists) < 3) or not self.lists:
                    key = f"list{len(self.lists)}"
                    action = "makeList" if rng.random() < 0.7 else "makeText"
                    ops.append({"action": action, "obj": "_root", "key": key,
                                "pred": ([self.map_winner[key]]
                                         if key in self.map_winner else [])})
                    obj = f"{ctr}@{actor}"
                    self.lists[obj] = []
                    self.list_keys[obj] = key
                    self.map_winner[key] = obj
                elif roll < 0.55:
                    obj = rng.choice(sorted(self.lists))
                    live = self.lists[obj]
                    ref = "_head" if not live or rng.random() < 0.3 else rng.choice(live)
                    ops.append({"action": "set", "obj": obj, "elemId": ref,
                                "insert": True, "datatype": "uint",
                                "value": rng.randrange(1000), "pred": []})
                    elem = f"{ctr}@{actor}"
                    live.append(elem)
                    self.elem_winner[(obj, elem)] = elem
                elif roll < 0.75:
                    obj = rng.choice(sorted(self.lists))
                    live = self.lists[obj]
                    if not live:
                        continue
                    elem = rng.choice(live)
                    ops.append({"action": "set", "obj": obj, "elemId": elem,
                                "datatype": "uint",
                                "value": rng.randrange(1000),
                                "pred": [self.elem_winner[(obj, elem)]]})
                    self.elem_winner[(obj, elem)] = f"{ctr}@{actor}"
                elif roll < 0.85:
                    obj = rng.choice(sorted(self.lists))
                    live = self.lists[obj]
                    if not live:
                        continue
                    elem = rng.choice(live)
                    ops.append({"action": "del", "obj": obj, "elemId": elem,
                                "pred": [self.elem_winner[(obj, elem)]]})
                    live.remove(elem)
                    self.elem_winner.pop((obj, elem), None)
                else:
                    key = f"k{rng.randrange(3)}"
                    prev = self.map_winner.get(key)
                    ops.append({"action": "set", "obj": "_root", "key": key,
                                "datatype": "uint",
                                "value": rng.randrange(1000),
                                "pred": [prev] if prev else []})
                    self.map_winner[key] = f"{ctr}@{actor}"
                ctr = start + len(ops)
            return ops

        return self._new_change(build)


def run_list_differential(num_docs, num_rounds, seed):
    farm = TpuDocFarm(num_docs, capacity=512)
    opsets = [OpSet() for _ in range(num_docs)]
    loads = [ListWorkload(seed + 31 * d) for d in range(num_docs)]
    farm_docs = [Frontend.init() for _ in range(num_docs)]
    seq_docs = [Frontend.init() for _ in range(num_docs)]

    for rnd in range(num_rounds):
        per_doc = []
        for d in range(num_docs):
            buf = loads[d].next_change(opsets[d].heads)
            per_doc.append([buf] if buf else [])
        expected = [opsets[d].apply_changes(per_doc[d]) for d in range(num_docs)]
        got = farm.apply_changes(per_doc)
        for d in range(num_docs):
            if not per_doc[d]:
                continue
            # byte-exact patch parity: the whole patch dict must match,
            # including the order-dependent list edit stream
            assert got[d] == expected[d], (
                f"round {rnd} doc {d}:\n  farm {got[d]}\n  seq  {expected[d]}"
            )
            seq_docs[d] = Frontend.apply_patch(seq_docs[d], expected[d])
            farm_docs[d] = Frontend.apply_patch(farm_docs[d], got[d])
            a = materialize(farm_docs[d])
            b = materialize(seq_docs[d])
            assert a == b, f"round {rnd} doc {d}:\n  farm {a}\n  seq  {b}"

    # whole-document patches are dict-exact as well: the device path (RGA
    # rank kernel + device visibility) must reproduce the sequential scan
    for d in range(num_docs):
        fp, sp = farm.get_patch(d), opsets[d].get_patch()
        assert fp == sp, f"get_patch doc {d}:\n  farm {fp}\n  seq  {sp}"
        fd = Frontend.apply_patch(Frontend.init(), fp)
        sd = Frontend.apply_patch(Frontend.init(), sp)
        assert materialize(fd) == materialize(sd), f"get_patch doc {d}"


class TestFarmListsBasics:
    def test_insert_and_materialize(self):
        farm = TpuDocFarm(1, capacity=32)
        opset = OpSet()
        buf, _ = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "makeList", "obj": "_root", "key": "l", "pred": []},
            {"action": "set", "obj": "1@aaaaaaaa", "elemId": "_head",
             "insert": True, "datatype": "uint", "value": 7, "pred": []},
            {"action": "set", "obj": "1@aaaaaaaa", "elemId": "2@aaaaaaaa",
             "insert": True, "datatype": "uint", "value": 8, "pred": []},
        ])
        expected = opset.apply_changes([buf])
        (got,) = farm.apply_changes([[buf]])
        fd = Frontend.apply_patch(Frontend.init(), got)
        sd = Frontend.apply_patch(Frontend.init(), expected)
        assert materialize(fd) == materialize(sd) == {"l": [7, 8]}

    def test_delete_element(self):
        farm = TpuDocFarm(1, capacity=32)
        opset = OpSet()
        buf1, h1 = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "makeList", "obj": "_root", "key": "l", "pred": []},
            {"action": "set", "obj": "1@aaaaaaaa", "elemId": "_head",
             "insert": True, "datatype": "uint", "value": 1, "pred": []},
            {"action": "set", "obj": "1@aaaaaaaa", "elemId": "2@aaaaaaaa",
             "insert": True, "datatype": "uint", "value": 2, "pred": []},
        ])
        buf2, _ = make_change("aaaaaaaa", 2, 4, [h1], [
            {"action": "del", "obj": "1@aaaaaaaa", "elemId": "2@aaaaaaaa",
             "pred": ["2@aaaaaaaa"]},
        ])
        opset.apply_changes([buf1])
        farm.apply_changes([[buf1]])
        expected = opset.apply_changes([buf2])
        (got,) = farm.apply_changes([[buf2]])
        fd = Frontend.apply_patch(
            Frontend.apply_patch(Frontend.init(), farm.get_patch(0)), got
        )
        assert got["maxOp"] == expected["maxOp"]
        fd = Frontend.apply_patch(Frontend.init(), farm.get_patch(0))
        sd = Frontend.apply_patch(Frontend.init(), opset.get_patch())
        assert materialize(fd) == materialize(sd) == {"l": [2]}

    def test_concurrent_head_inserts_order(self):
        """Two concurrent head inserts: higher opId wins position 0 (RGA)."""
        farm = TpuDocFarm(1, capacity=32)
        opset = OpSet()
        buf0, h0 = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "makeList", "obj": "_root", "key": "l", "pred": []}])
        buf_a, _ = make_change("aaaaaaaa", 2, 2, [h0], [
            {"action": "set", "obj": "1@aaaaaaaa", "elemId": "_head",
             "insert": True, "datatype": "uint", "value": 10, "pred": []}])
        buf_b, _ = make_change("bbbbbbbb", 1, 2, [h0], [
            {"action": "set", "obj": "1@aaaaaaaa", "elemId": "_head",
             "insert": True, "datatype": "uint", "value": 20, "pred": []}])
        expected1 = opset.apply_changes([buf0, buf_a, buf_b])
        (got1,) = farm.apply_changes([[buf0, buf_a, buf_b]])
        fd = Frontend.apply_patch(Frontend.init(), got1)
        sd = Frontend.apply_patch(Frontend.init(), expected1)
        assert materialize(fd) == materialize(sd)

    def test_nested_map_inside_list(self):
        farm = TpuDocFarm(1, capacity=32)
        opset = OpSet()
        buf, _ = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "makeList", "obj": "_root", "key": "l", "pred": []},
            {"action": "makeMap", "obj": "1@aaaaaaaa", "elemId": "_head",
             "insert": True, "pred": []},
            {"action": "set", "obj": "2@aaaaaaaa", "key": "x",
             "datatype": "uint", "value": 5, "pred": []},
        ])
        expected = opset.apply_changes([buf])
        (got,) = farm.apply_changes([[buf]])
        fd = Frontend.apply_patch(Frontend.init(), got)
        sd = Frontend.apply_patch(Frontend.init(), expected)
        assert materialize(fd) == materialize(sd) == {"l": [{"x": 5}]}


class TestFarmListsDifferential:
    def test_single_doc(self):
        run_list_differential(1, 12, seed=11)

    def test_multi_doc(self):
        run_list_differential(3, 10, seed=12)

    def test_longer_churn(self):
        run_list_differential(2, 18, seed=13)
