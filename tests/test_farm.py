"""TpuDocFarm differential suite: the batched device backend must emit
patches byte-equal (as Python dicts) to the sequential reference-parity
OpSet backend for identical binary change streams — the cross-backend
pattern of the reference's test/wasm.js, with the farm playing the role of
the external backend."""
import random

import pytest

from automerge_tpu.columnar import decode_change_columns, encode_change
from automerge_tpu.opset import OpSet
from automerge_tpu.tpu.farm import TpuDocFarm


def make_change(actor, seq, start_op, deps, ops):
    buf = encode_change(
        {"actor": actor, "seq": seq, "startOp": start_op, "time": 0,
         "deps": sorted(deps), "ops": ops}
    )
    return buf, decode_change_columns(buf)["hash"]


def lamport(op_id):
    ctr, actor = op_id.split("@")
    return (int(ctr), actor)


def visible_index(diffs, obj="_root", out=None, objects=None):
    """Walks a whole-doc patch diff into {(obj, key): [(opId, diff)]} plus
    the set of live object ids — the generator's view of current state."""
    if out is None:
        out, objects = {}, {"_root": "map"}
    for key, values in diffs.get("props", {}).items():
        entries = sorted(values.items(), key=lambda kv: lamport(kv[0]))
        if entries:
            out[(obj, key)] = entries
        for op_id, diff in entries:
            if isinstance(diff, dict) and "objectId" in diff:
                objects[diff["objectId"]] = diff["type"]
                visible_index(diff, diff["objectId"], out, objects)
    return out, objects


class Workload:
    """Random map-family workload generator with real concurrency: each
    round snapshots the doc state, creates changes from 1-2 actors against
    that same snapshot (concurrent siblings), and delivers them with random
    delay and order."""

    def __init__(self, seed, actors=("aaaaaaaa", "bbbbbbbb", "cccccccc"),
                 with_counters=True, with_nesting=True, delay_prob=0.25):
        self.rng = random.Random(seed)
        self.actors = actors
        self.with_counters = with_counters
        self.with_nesting = with_nesting
        self.delay_prob = delay_prob
        self.seqs = dict.fromkeys(actors, 0)
        self.last_hash = dict.fromkeys(actors, None)
        self.max_op = 0
        self.in_flight = []  # (due_round, buffer)
        self.round = 0

    def _ops_against(self, index, objects, n_ops):
        ops = []
        for _ in range(n_ops):
            obj = self.rng.choice(sorted(objects))
            key = f"k{self.rng.randrange(5)}"
            entries = index.get((obj, key), [])
            preds = [op_id for op_id, _ in entries]
            counter_ids = [
                op_id for op_id, d in entries
                if isinstance(d, dict) and d.get("datatype") == "counter"
            ]
            roll = self.rng.random()
            if counter_ids:
                ops.append({"action": "inc", "obj": obj, "key": key,
                            "value": self.rng.randrange(1, 10),
                            "pred": [counter_ids[-1]]})
            elif self.with_nesting and roll < 0.18:
                action = "makeMap" if self.rng.random() < 0.7 else "makeTable"
                ops.append({"action": action, "obj": obj, "key": key, "pred": preds})
            elif roll < 0.3 and preds:
                ops.append({"action": "del", "obj": obj, "key": key, "pred": preds})
            elif self.with_counters and roll < 0.42 and not preds:
                ops.append({"action": "set", "obj": obj, "key": key,
                            "datatype": "counter",
                            "value": self.rng.randrange(50), "pred": []})
            else:
                ops.append({"action": "set", "obj": obj, "key": key,
                            "datatype": "uint",
                            "value": self.rng.randrange(1000), "pred": preds})
        return ops

    def next_round(self, oracle: OpSet):
        """Generates this round's changes against the oracle's current
        state and returns the buffers due for delivery this round."""
        self.round += 1
        index, objects = visible_index(oracle.get_patch()["diffs"])
        heads = list(oracle.heads)
        for actor in self.rng.sample(self.actors, self.rng.randrange(1, 3)):
            self.seqs[actor] += 1
            start_op = self.max_op + 1
            ops = self._ops_against(index, objects, self.rng.randrange(1, 4))
            deps = set(heads)
            if self.last_hash[actor]:
                deps.add(self.last_hash[actor])
            buf, hash_ = make_change(actor, self.seqs[actor], start_op, deps, ops)
            self.last_hash[actor] = hash_
            self.max_op = start_op + len(ops) - 1
            due = self.round + (self.rng.randrange(1, 3)
                                if self.rng.random() < self.delay_prob else 0)
            self.in_flight.append((due, buf))
        due_now = [buf for r, buf in self.in_flight if r <= self.round]
        self.in_flight = [(r, buf) for r, buf in self.in_flight if r > self.round]
        self.rng.shuffle(due_now)
        return due_now

    def drain(self):
        """All still-undelivered buffers (to flush queues at the end)."""
        out = [buf for _, buf in self.in_flight]
        self.in_flight = []
        self.rng.shuffle(out)
        return out


def run_farm_differential(num_docs, num_rounds, seed, **workload_kw):
    farm = TpuDocFarm(num_docs, capacity=256)
    opsets = [OpSet() for _ in range(num_docs)]
    loads = [Workload(seed + 17 * d, **workload_kw) for d in range(num_docs)]

    # oracle state BEFORE delivery drives generation, so generate first
    for rnd in range(num_rounds + 3):
        per_doc = []
        for d in range(num_docs):
            if rnd < num_rounds:
                per_doc.append(loads[d].next_round(opsets[d]))
            else:
                per_doc.append(loads[d].drain())
        expected = [opsets[d].apply_changes(per_doc[d]) for d in range(num_docs)]
        got = farm.apply_changes(per_doc)
        for d in range(num_docs):
            assert got[d] == expected[d], (
                f"round {rnd} doc {d}:\n  got  {got[d]}\n  want {expected[d]}"
            )

    for d in range(num_docs):
        assert farm.get_patch(d) == opsets[d].get_patch(), f"final get_patch doc {d}"
        assert farm.get_heads(d) == opsets[d].heads
        assert farm.get_missing_deps(d) == opsets[d].get_missing_deps()


class TestFarmBasics:
    def test_single_set_patch(self):
        farm = TpuDocFarm(1, capacity=16)
        ops = [{"action": "set", "obj": "_root", "key": "x",
                "datatype": "uint", "value": 7, "pred": []}]
        buf, _h = make_change("aaaaaaaa", 1, 1, [], ops)
        opset = OpSet()
        expected = opset.apply_changes([buf])
        (got,) = farm.apply_changes([[buf]])
        assert got == expected

    def test_queued_change_waits_for_deps(self):
        farm = TpuDocFarm(1, capacity=16)
        opset = OpSet()
        ops1 = [{"action": "set", "obj": "_root", "key": "x",
                 "datatype": "uint", "value": 1, "pred": []}]
        buf1, h1 = make_change("aaaaaaaa", 1, 1, [], ops1)
        ops2 = [{"action": "set", "obj": "_root", "key": "x",
                 "datatype": "uint", "value": 2, "pred": ["1@aaaaaaaa"]}]
        buf2, _h2 = make_change("aaaaaaaa", 2, 2, [h1], ops2)

        expected2 = opset.apply_changes([buf2])
        (got2,) = farm.apply_changes([[buf2]])
        assert got2 == expected2
        assert got2["pendingChanges"] == 1
        assert farm.get_missing_deps(0) == [h1]

        expected1 = opset.apply_changes([buf1])
        (got1,) = farm.apply_changes([[buf1]])
        assert got1 == expected1
        assert got1["pendingChanges"] == 0

    def test_duplicate_change_is_idempotent(self):
        farm = TpuDocFarm(1, capacity=16)
        opset = OpSet()
        ops = [{"action": "set", "obj": "_root", "key": "x",
                "datatype": "uint", "value": 1, "pred": []}]
        buf, _h = make_change("aaaaaaaa", 1, 1, [], ops)
        farm.apply_changes([[buf]])
        opset.apply_changes([buf])
        expected = opset.apply_changes([buf])
        (got,) = farm.apply_changes([[buf]])
        assert got == expected

    def test_concurrent_conflict_map(self):
        farm = TpuDocFarm(1, capacity=16)
        opset = OpSet()
        buf_a, _ = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "set", "obj": "_root", "key": "k",
             "datatype": "uint", "value": 1, "pred": []}])
        buf_b, _ = make_change("bbbbbbbb", 1, 1, [], [
            {"action": "set", "obj": "_root", "key": "k",
             "datatype": "uint", "value": 2, "pred": []}])
        expected = opset.apply_changes([buf_a, buf_b])
        (got,) = farm.apply_changes([[buf_a, buf_b]])
        assert got == expected
        assert set(got["diffs"]["props"]["k"]) == {"1@aaaaaaaa", "1@bbbbbbbb"}

    def test_multi_pred_conflict_resolution(self):
        farm = TpuDocFarm(1, capacity=16)
        opset = OpSet()
        buf_a, ha = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "set", "obj": "_root", "key": "k",
             "datatype": "uint", "value": 1, "pred": []}])
        buf_b, hb = make_change("bbbbbbbb", 1, 1, [], [
            {"action": "set", "obj": "_root", "key": "k",
             "datatype": "uint", "value": 2, "pred": []}])
        buf_c, _ = make_change("aaaaaaaa", 2, 2, [ha, hb], [
            {"action": "set", "obj": "_root", "key": "k", "datatype": "uint",
             "value": 3, "pred": ["1@aaaaaaaa", "1@bbbbbbbb"]}])
        expected1 = opset.apply_changes([buf_a, buf_b])
        (got1,) = farm.apply_changes([[buf_a, buf_b]])
        assert got1 == expected1
        expected2 = opset.apply_changes([buf_c])
        (got2,) = farm.apply_changes([[buf_c]])
        assert got2 == expected2
        assert list(got2["diffs"]["props"]["k"]) == ["2@aaaaaaaa"]

    def test_nested_make_map_patch(self):
        farm = TpuDocFarm(1, capacity=16)
        opset = OpSet()
        buf, _ = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "makeMap", "obj": "_root", "key": "cfg", "pred": []},
            {"action": "set", "obj": "1@aaaaaaaa", "key": "x",
             "datatype": "uint", "value": 5, "pred": []}])
        expected = opset.apply_changes([buf])
        (got,) = farm.apply_changes([[buf]])
        assert got == expected

    def test_counter_accumulation_patch(self):
        farm = TpuDocFarm(1, capacity=16)
        opset = OpSet()
        buf1, h1 = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "set", "obj": "_root", "key": "c",
             "datatype": "counter", "value": 10, "pred": []}])
        buf2, _ = make_change("aaaaaaaa", 2, 2, [h1], [
            {"action": "inc", "obj": "_root", "key": "c",
             "value": 3, "pred": ["1@aaaaaaaa"]}])
        expected1 = opset.apply_changes([buf1])
        (got1,) = farm.apply_changes([[buf1]])
        assert got1 == expected1
        expected2 = opset.apply_changes([buf2])
        (got2,) = farm.apply_changes([[buf2]])
        assert got2 == expected2
        assert got2["diffs"]["props"]["c"]["1@aaaaaaaa"]["value"] == 13

    def test_multi_pred_inc_on_conflicting_counters(self):
        """An inc naming two conflicting counters must keep both visible
        (inc successors never hide) and add its value to the highest-opId
        target only (counterStates registration, new.js:621-628)."""
        farm = TpuDocFarm(1, capacity=16)
        opset = OpSet()
        buf_a, ha = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "set", "obj": "_root", "key": "c",
             "datatype": "counter", "value": 10, "pred": []}])
        buf_b, hb = make_change("bbbbbbbb", 1, 1, [], [
            {"action": "set", "obj": "_root", "key": "c",
             "datatype": "counter", "value": 100, "pred": []}])
        buf_c, _ = make_change("cccccccc", 1, 2, [ha, hb], [
            {"action": "inc", "obj": "_root", "key": "c", "value": 7,
             "pred": ["1@aaaaaaaa", "1@bbbbbbbb"]}])
        expected1 = opset.apply_changes([buf_a, buf_b])
        (got1,) = farm.apply_changes([[buf_a, buf_b]])
        assert got1 == expected1
        expected2 = opset.apply_changes([buf_c])
        (got2,) = farm.apply_changes([[buf_c]])
        assert got2 == expected2
        assert farm.get_patch(0) == opset.get_patch()

    def test_seq_reuse_raises(self):
        farm = TpuDocFarm(1, capacity=16)
        ops = [{"action": "set", "obj": "_root", "key": "x",
                "datatype": "uint", "value": 1, "pred": []}]
        buf1, _ = make_change("aaaaaaaa", 1, 1, [], ops)
        buf1b, _ = make_change("aaaaaaaa", 1, 1, [], [
            {"action": "set", "obj": "_root", "key": "y",
             "datatype": "uint", "value": 2, "pred": []}])
        farm.apply_changes([[buf1]])
        # the all-or-nothing escape hatch raises straight out of the call
        with pytest.raises(ValueError, match="sequence number"):
            farm.apply_changes([[buf1b]], isolation="batch")
        # default per-doc isolation captures the same taxonomy error in the
        # outcome report instead (state untouched)
        result = farm.apply_changes([[buf1b]])
        outcome = result.outcomes[0]
        assert outcome.status == "quarantined"
        assert isinstance(outcome.error, ValueError)
        assert "sequence number" in str(outcome.error)
        assert len(farm.get_all_changes(0)) == 1


class TestFarmDifferential:
    def test_maps_and_dels(self):
        run_farm_differential(3, 8, seed=1, with_counters=False,
                              with_nesting=False)

    def test_counters(self):
        run_farm_differential(3, 8, seed=2, with_nesting=False)

    def test_nested(self):
        run_farm_differential(3, 8, seed=3)

    def test_heavy_concurrency_and_delay(self):
        run_farm_differential(4, 12, seed=4, delay_prob=0.5)

    def test_in_order_stream(self):
        run_farm_differential(2, 10, seed=5, delay_prob=0.0)
