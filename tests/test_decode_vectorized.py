"""Byte-corpus parity suite for the vectorized columnar decode
(automerge_tpu/tpu/decode.py).

The vectorized passes must be BIT-FOR-BIT identical to the scalar oracle
(the per-op decoder chain in codecs.py/columnar.py) over:

- the bench change stream and fuzzed changes covering every op shape the
  wire format encodes (nested objects, counters, inc/del, multi-pred,
  list inserts, every value datatype, multi-actor tables);
- corrupt/truncated inputs: the same ``DecodeError``/``ChecksumError``
  taxonomy with caches left untouched;
- save/load round-trips through the document chunk format;
- the column codecs themselves (RLE/Delta/Boolean run grammars).
"""
import random
from unittest import mock

import numpy as np
import pytest

import automerge_tpu.columnar as columnar
from automerge_tpu import backend as Backend
from automerge_tpu import native
from automerge_tpu.codecs import (
    BooleanDecoder,
    BooleanEncoder,
    DecodeCache,
    DeltaDecoder,
    DeltaEncoder,
    Encoder,
    RLEDecoder,
    RLEEncoder,
)
from automerge_tpu.errors import ChecksumError, DecodeError
from automerge_tpu.testing import faults
from automerge_tpu.tpu import decode as vdec


def oracle_decode(buffer):
    """decode_change through the per-op scalar decoder chain only."""
    with mock.patch.object(native, "available", lambda: False):
        with mock.patch.object(columnar, "_VECTOR_DECODER", None):
            return columnar.decode_change(buffer)


def vector_decode(buffer):
    """decode_change through the vectorized backend only (native off)."""
    with mock.patch.object(native, "available", lambda: False):
        return columnar.decode_change(buffer)


def _fuzz_change(rng, actor, seq, start_op, deps, known_ops, known_elems):
    """One structurally valid change exercising the full op vocabulary."""
    ops = []
    ctr = start_op
    n = rng.randrange(1, 9)
    for _ in range(n):
        kind = rng.random()
        key = f"k{rng.randrange(6)}é{rng.randrange(3)}"
        pred = []
        if known_ops and rng.random() < 0.5:
            pred = sorted(
                rng.sample(known_ops, min(len(known_ops), rng.randrange(1, 3))),
                key=lambda p: (int(p.split("@")[0]), p.split("@")[1]),
            )
        if kind < 0.55:
            value = rng.choice([
                rng.randrange(-2**53 + 1, 2**53 - 1),
                rng.random() * 1e9,
                "v" * rng.randrange(0, 5) + "☃",
                b"\x00\xff" * rng.randrange(0, 3),
                True, False, None,
            ])
            op = {"action": "set", "obj": "_root", "key": key,
                  "value": value, "pred": pred}
            if isinstance(value, int) and not isinstance(value, bool):
                op["datatype"] = rng.choice(
                    ["counter", "timestamp", "int", None]
                    + (["uint"] if value >= 0 else [])
                )
                if op["datatype"] is None:
                    del op["datatype"]
            elif isinstance(value, float):
                op["datatype"] = "float64"
        elif kind < 0.7:
            op = {"action": rng.choice(["makeMap", "makeTable"]),
                  "obj": "_root", "key": key, "pred": pred}
        elif kind < 0.8 and known_ops:
            op = {"action": "inc", "obj": "_root", "key": key,
                  "value": rng.randrange(-5, 10), "pred": pred or [known_ops[0]]}
        elif kind < 0.9 and known_ops:
            op = {"action": "del", "obj": "_root", "key": key,
                  "pred": pred or [known_ops[0]]}
        else:
            # list insert: element keyed by elemId, optionally chained
            ref = rng.choice(known_elems) if known_elems and rng.random() < 0.6 else "_head"
            op = {"action": "set", "obj": "_root", "elemId": ref,
                  "insert": True, "value": rng.randrange(100), "pred": []}
            known_elems.append(f"{ctr}@{actor}")
        ops.append(op)
        known_ops.append(f"{ctr}@{actor}")
        ctr += 1
    return {
        "actor": actor, "seq": seq, "startOp": start_op, "time": rng.randrange(2**31),
        "message": rng.choice(["", "méssage", "x" * 40]),
        "deps": sorted(deps), "ops": ops,
    }, ctr


class TestChunkParity:
    def test_bench_stream(self):
        from bench import _make_change_stream

        for buf in _make_change_stream(6, 48, 3):
            assert vector_decode(buf) == oracle_decode(buf)

    @pytest.mark.parametrize("seed", range(6))
    def test_fuzzed_changes(self, seed):
        rng = random.Random(seed)
        known_ops, known_elems = [], []
        start_op, deps = 1, []
        bufs = []
        for i, actor in enumerate(["aaaaaaaa", "bbbbbbbb", "cdcdcdcd"] * 3):
            change, start_op = _fuzz_change(
                rng, actor, i // 3 + 1, start_op, deps, known_ops, known_elems
            )
            buf = columnar.encode_change(change)
            deps = [columnar.decode_change_columns(buf)["hash"]]
            bufs.append(buf)
        oracle = [oracle_decode(b) for b in bufs]
        for b, expected in zip(bufs, oracle):
            assert vector_decode(b) == expected
        # and through the batched entry point, which shares one scan
        with mock.patch.object(native, "available", lambda: False):
            assert vdec.decode_changes_vector(bufs) == oracle

    def test_deflated_change(self):
        big = faults.make_change(
            "aaaaaaaa", 1, 1,
            [], [faults.set_op(f"key{i}", i) for i in range(200)],
        )
        assert len(big) > 0 and vector_decode(big) == oracle_decode(big)


class TestCorruptInputs:
    @pytest.mark.parametrize("name,corrupter,kind", faults.BYTE_CORPUS,
                             ids=[c[0] for c in faults.BYTE_CORPUS])
    def test_same_error_taxonomy(self, name, corrupter, kind):
        base = faults.make_change(
            "aaaaaaaa", 1, 1, [], [faults.set_op("k", 7)]
        )
        bad = bytes(corrupter(base))
        with pytest.raises(Exception) as oracle_exc:
            oracle_decode(bad)
        with pytest.raises(Exception) as vector_exc:
            vector_decode(bad)
        assert type(vector_exc.value) is type(oracle_exc.value)
        assert str(vector_exc.value) == str(oracle_exc.value)
        assert isinstance(vector_exc.value, (DecodeError, ChecksumError))

    def test_corrupt_buffers_left_uncached(self):
        columnar.clear_decode_caches()
        base = faults.make_change("aaaaaaaa", 1, 1, [], [faults.set_op("k", 7)])
        bad = faults.truncated(base)
        before = len(columnar._DECODED_CHANGE_CACHE)
        assert vdec.warm_decode_cache([base, bad]) == 1
        assert len(columnar._DECODED_CHANGE_CACHE) == before + 1
        # the bad buffer still raises its canonical error on the scalar path
        with pytest.raises(DecodeError):
            columnar.decode_change_cached(bad)
        columnar.clear_decode_caches()

    def test_batch_with_one_bad_buffer_raises_like_sequential(self):
        good = faults.make_change("aaaaaaaa", 1, 1, [], [faults.set_op("k", 1)])
        bad = faults.garbage(32)
        with pytest.raises(DecodeError):
            vdec.decode_changes_vector([good, bad])


class TestSaveLoadRoundTrip:
    @pytest.mark.parametrize("seed", range(3))
    def test_document_chunks(self, seed):
        from bench import _make_change_stream

        b = Backend.init()
        for buf in _make_change_stream(5, 24, 200 + seed):
            b, _ = Backend.apply_changes(b, [buf])
        # a second actor layering counters, dels and nested objects on top
        ops = [
            {"action": "set", "obj": "_root", "key": "c",
             "datatype": "counter", "value": 5, "pred": []},
            {"action": "makeMap", "obj": "_root", "key": "child", "pred": []},
        ]
        c1 = faults.make_change("bbbbbbbb", 1, 1, Backend.get_heads(b), ops)
        b, _ = Backend.apply_changes(b, [c1])
        h1 = columnar.decode_change_columns(c1)["hash"]
        ops2 = [
            {"action": "inc", "obj": "_root", "key": "c", "value": 3,
             "pred": ["1@bbbbbbbb"]},
            {"action": "set", "obj": "2@bbbbbbbb", "key": "nested",
             "value": "x", "pred": []},
        ]
        c2 = faults.make_change("bbbbbbbb", 2, 3, [h1], ops2)
        b, _ = Backend.apply_changes(b, [c2])
        saved = Backend.save(b)
        with mock.patch.object(native, "available", lambda: False):
            with mock.patch.object(columnar, "_VECTOR_DECODER", None):
                oracle_patch = Backend.get_patch(Backend.load(saved))
            vector_patch = Backend.get_patch(Backend.load(saved))
        assert vector_patch == oracle_patch
        assert Backend.save(Backend.load(saved)) == saved


def _scalar_rle(type_, buf):
    dec = RLEDecoder(type_, buf)
    out = []
    while not dec.done:
        out.append(dec.read_value())
    return out


class TestColumnCodecs:
    """Column-level parity: vector expansion vs the scalar decoders over
    generated run/literal/null mixes."""

    @pytest.mark.parametrize("seed", range(8))
    def test_rle_uint(self, seed):
        rng = random.Random(seed)
        values = []
        for _ in range(rng.randrange(1, 30)):
            v = rng.choice([None, rng.randrange(0, 2**50)])
            values.extend([v] * rng.randrange(1, 6))
        enc = RLEEncoder("uint")
        for v in values:
            enc.append_value(v)
        buf = enc.buffer
        scan = vdec._Scan([buf])
        lo, hi = scan.seg(0)
        got = vdec._rle_expand(scan, lo, hi, signed=False)
        expected = _scalar_rle("uint", buf)
        got_l = [None if x == native.NULL_SENTINEL else x for x in got.tolist()]
        assert got_l == expected

    @pytest.mark.parametrize("seed", range(8))
    def test_delta(self, seed):
        rng = random.Random(seed)
        values = []
        cur = 0
        for _ in range(rng.randrange(1, 40)):
            if rng.random() < 0.2:
                values.append(None)
            else:
                cur += rng.randrange(-50, 50)
                values.append(cur)
        enc = DeltaEncoder()
        for v in values:
            enc.append_value(v)
        buf = enc.buffer
        dec = DeltaDecoder(buf)
        expected = []
        while not dec.done:
            expected.append(dec.read_value())
        scan = vdec._Scan([buf])
        lo, hi = scan.seg(0)
        got = vdec._delta_expand(scan, lo, hi)
        got_l = [None if x == native.NULL_SENTINEL else x for x in got.tolist()]
        assert got_l == expected

    @pytest.mark.parametrize("seed", range(8))
    def test_boolean(self, seed):
        rng = random.Random(seed)
        values = []
        for _ in range(rng.randrange(1, 20)):
            values.extend([rng.random() < 0.5] * rng.randrange(1, 7))
        enc = BooleanEncoder()
        for v in values:
            enc.append_value(v)
        buf = enc.buffer
        dec = BooleanDecoder(buf)
        expected = []
        while not dec.done:
            expected.append(dec.read_value())
        scan = vdec._Scan([buf])
        lo, hi = scan.seg(0)
        assert vdec._bool_expand(scan, lo, hi).tolist() == expected

    @pytest.mark.parametrize("seed", range(8))
    def test_strrle(self, seed):
        rng = random.Random(seed)
        words = ["", "a", "longer-key", "élément", "x" * 200]
        values = []
        for _ in range(rng.randrange(1, 25)):
            v = rng.choice([None] + words)
            values.extend([v] * rng.randrange(1, 5))
        enc = RLEEncoder("utf8")
        for v in values:
            enc.append_value(v)
        buf = enc.buffer
        expected = _scalar_rle("utf8", buf)
        blob, offs = vdec._strrle_expand(buf)
        got = [
            None if s < 0 else blob[s:e].decode("utf-8", "surrogatepass")
            for s, e in offs.tolist()
        ]
        assert got == expected

    def test_bad_run_grammar_defers_to_oracle(self):
        """Streams the scalar decoder rejects make the vector pass raise
        _Fallback (the chunk then re-decodes through the oracle, which
        owns the canonical error)."""
        def rle_bytes(records):
            enc = Encoder()
            for rec in records:
                for kind, v in rec:
                    if kind == "i":
                        enc.append_int53(v)
                    else:
                        enc.append_uint53(v)
            return enc.buffer

        bad_streams = [
            rle_bytes([[("i", 1), ("u", 5)]]),                 # count of 1
            rle_bytes([[("i", 0), ("u", 0)]]),                 # zero null run
            rle_bytes([[("i", 0), ("u", 2)], [("i", 0), ("u", 2)]]),  # 2 null runs
            rle_bytes([[("i", 3), ("u", 7)], [("i", 2), ("u", 7)]]),  # same rep
            rle_bytes([[("i", -1), ("u", 4)], [("i", -1), ("u", 5)]]),  # 2 literals
            rle_bytes([[("i", -2), ("u", 4), ("u", 4)]]),      # rep in literal
        ]
        for buf in bad_streams:
            with pytest.raises(DecodeError):
                _scalar_rle("uint", bytes(buf))
            scan = vdec._Scan([bytes(buf)])
            with pytest.raises(vdec._Fallback):
                vdec._rle_expand(scan, *scan.seg(0), signed=False)


class TestLeb128Scan:
    @pytest.mark.parametrize("seed", range(4))
    def test_roundtrip(self, seed):
        rng = random.Random(seed)
        uvals = [rng.randrange(0, 2**53) for _ in range(200)]
        ivals = [rng.randrange(-2**52, 2**52) for _ in range(200)]
        ue, ie = Encoder(), Encoder()
        for v in uvals:
            ue.append_uint53(v)
        for v in ivals:
            ie.append_int53(v)
        su = vdec.leb128_scan(np.frombuffer(ue.buffer, np.uint8))
        assert su[2].tolist() == uvals
        si = vdec.leb128_scan(np.frombuffer(ie.buffer, np.uint8))
        assert si[3].tolist() == ivals

    def test_truncated_stream_falls_back(self):
        enc = Encoder()
        enc.append_uint53(2**40)
        data = np.frombuffer(enc.buffer[:-1], np.uint8)
        with pytest.raises(vdec._Fallback):
            vdec.leb128_scan(data)

    def test_wide_varint_falls_back(self):
        data = np.frombuffer(bytes([0x80] * 9 + [0x01]), np.uint8)
        with pytest.raises(vdec._Fallback):
            vdec.leb128_scan(data)

    def test_device_scan_matches_host(self):
        rng = random.Random(9)
        enc = Encoder()
        vals = [rng.randrange(0, 2**50) for _ in range(300)]
        for v in vals:
            enc.append_uint53(v)
        data = np.frombuffer(enc.buffer, np.uint8)
        host = vdec.leb128_scan(data)
        dev = vdec.leb128_scan_device(data)
        for h, d in zip(host, dev):
            assert np.array_equal(h, np.asarray(d))


class TestDecodeCacheBudget:
    def test_byte_budget_evicts(self):
        cache = DecodeCache(100, name="test.cache.budget", max_bytes=100)
        for i in range(10):
            cache.put(bytes([i]) * 40, i)
        assert len(cache) <= 3  # 40-byte keys under a 100-byte budget
        assert cache._bytes <= 100
        # the newest entries survive
        assert cache.get(bytes([9]) * 40) == 9

    def test_single_oversized_entry_still_caches(self):
        cache = DecodeCache(8, name="test.cache.huge", max_bytes=64)
        cache.put(b"x" * 1000, "huge")
        assert cache.get(b"x" * 1000) == "huge"
        assert len(cache) == 1

    def test_entry_count_bound_still_applies(self):
        cache = DecodeCache(3, name="test.cache.count", max_bytes=10**9)
        for i in range(10):
            cache.put(bytes([i]), i)
        assert len(cache) == 3
