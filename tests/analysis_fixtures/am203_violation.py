"""AM203 violating fixture: dtype-less construction near device code."""
import jax.numpy as jnp


def make_table(n):
    return jnp.zeros((n, n))
