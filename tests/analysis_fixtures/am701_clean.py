"""AM701 clean fixture: lengths are pow2-bucketed before the dispatch.

The executable twin of am701_violation.py: the same four batch lengths
collapse onto at most two pow2 buckets, so the runtime storm detector
stays quiet and the static rule sees a sanitizer on every dataflow path.
"""
import jax.numpy as jnp

from automerge_tpu.tpu.sync_farm import _pow2
from automerge_tpu.tpu.jitprof import profiled_jit


@profiled_jit("fixture.shape.bucketed")
def _embed(xs):
    return xs * 2


def drive(batches):
    outs = []
    for rows in batches:
        n = _pow2(max(len(rows), 1))
        outs.append(_embed(jnp.zeros((n,), dtype=jnp.int32)))
    return outs
