# amlint: mesh-worker — fixture: controller import + global registry (AM502)
from automerge_tpu.obs.metrics import get_metrics
from automerge_tpu.parallel.meshfarm import MeshFarm


def serve_shard(spec):
    """The forbidden worker shape: pulls the controller into the child
    and records into the worker-process singleton, where the numbers
    never surface."""
    get_metrics().counter("mesh.worker.rpcs").inc()
    return MeshFarm(spec["num_docs"])
