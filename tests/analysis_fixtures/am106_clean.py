# amlint: hot-path — fixture: record-level walks stay clean (AM106)


def expand_records(counts, values):
    """O(records) Python, O(rows) array work: the walk steps per RECORD
    (two varints at a time), never per byte."""
    out = []
    i = 0
    while i < len(counts):
        out.append((counts[i], values[i]))
        i += 2  # record stride, not a byte cursor
    return out


def boundary_mask(flags):
    """The vectorized shape: boundaries come from a mask, not a loop."""
    return [j for j, cont in enumerate(flags) if not cont]
