"""AM701 suppressed fixture: a deliberately shape-dynamic dispatch."""
import jax.numpy as jnp

from automerge_tpu.tpu.jitprof import profiled_jit


@profiled_jit("fixture.shape.justified")
def _embed(xs):
    return xs * 2


def drive(batches):
    outs = []
    for rows in batches:
        n = len(rows)
        # amlint: disable=AM701 — fixture: one-shot offline tool, each
        # length dispatches exactly once so there is no storm to bucket
        outs.append(_embed(jnp.zeros((n,), dtype=jnp.int32)))
    return outs
