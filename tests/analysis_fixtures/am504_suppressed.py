# amlint: mesh-data-plane — fixture: the justified pickle-oracle path
# silences AM504
import pickle


def send_oracle_frame(conn, op, payload):
    """The one blessed pickle on the data plane: the parity-ORACLE
    transport, where the whole batch rides the pipe frame as the
    byte-for-byte baseline the shm transport is judged against (and the
    fallback for hosts without POSIX shared memory)."""
    # amlint: disable=AM504 — this IS the pickle parity-oracle transport:
    # under mesh_transport="pickle" the batch legitimately rides the frame
    buf = pickle.dumps((op, payload), protocol=pickle.HIGHEST_PROTOCOL)
    conn.send_bytes(buf)
    return len(buf)
