"""AM203 clean fixture: every constructed array pins its dtype."""
import jax.numpy as jnp


def make_table(n):
    return jnp.zeros((n, n), jnp.int64)
