"""AM302 clean fixture: the transfer happens in a host phase."""
import numpy as np

from automerge_tpu.profiling import get_profile


def dispatch(engine, batch):
    prof = get_profile()
    with prof.phase("device_dispatch"):
        out = engine.apply_batch(batch)
    with prof.phase("readback"):
        return np.asarray(out)
