"""AM204 clean fixture: traced code builds only local state."""
import jax
from jax import jit


@jit
def record(x):
    parts = []
    parts.append(x)
    return parts[0]
