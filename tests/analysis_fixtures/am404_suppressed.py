"""AM404 suppressed fixture: a deliberate internal-invariant raise."""
# amlint: v2-wire-codec


def fingerprint_width(n):
    if n < 0:
        raise AssertionError("caller bug, not wire input")  # amlint: disable=AM404 — internal invariant, unreachable from decoded frames
    return 1 << n
