"""AM104 suppressed fixture."""
MAX_COUNTER = 1 << 24


def check(ctr):
    if ctr >= MAX_COUNTER:
        # amlint: disable=AM104 — intentionally legacy wording
        raise ValueError(f"op counter {ctr} is out of range")
