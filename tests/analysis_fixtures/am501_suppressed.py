# amlint: mesh-routing — fixture: justified suppressions silence AM501


def debug_route_table(shard_of, local_of, num_docs):
    """A deliberately-cold debug dump of the routing table."""
    rows = []
    # amlint: disable=AM501 — debug-only dump, never on the delivery path
    for g in range(num_docs):
        rows.append((g, shard_of[g], local_of[g]))
    return rows
