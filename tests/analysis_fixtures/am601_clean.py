# amlint: durability-plane — fixture: blessed writer + reads stay clean
import json

from automerge_tpu.store.atomic import atomic_write


def save_manifest(path, manifest):
    """The blessed shape: the atomic writer owns tmp + fsync + rename, so
    a crash leaves either the old manifest or the new one, never a mix."""
    atomic_write(path, json.dumps(manifest, sort_keys=True))


def load_manifest(path):
    with open(path) as fh:
        return json.load(fh)


def read_segment(path):
    with open(path, "rb") as fh:
        return fh.read()
