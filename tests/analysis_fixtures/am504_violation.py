# amlint: mesh-data-plane — fixture: pickled bulk payload on the shm
# data plane (AM504)
import pickle


def stage_delivery(send_ring, batch):
    """The forbidden shape: the column batch is flat bytes already, but
    this path re-serializes it through pickle before it touches the ring
    — the zero-copy transport silently pays the tax it was built to
    remove while every dashboard still says "shm"."""
    blob = pickle.dumps(batch)
    slot, gen = send_ring.acquire()
    view = send_ring.slot_view(slot)
    view[:len(blob)] = blob
    return send_ring.publish(slot, gen, len(blob))


def persist_frame(fh, outcome_wires):
    pickle.dump(outcome_wires, fh)
