# amlint: mesh-worker — fixture: exposition-layer telemetry in a worker (AM305)
from automerge_tpu.obs.export import render_exposition
from automerge_tpu.obs.flight import get_flight


def serve_shard(conn):
    """The forbidden worker shape: records into the worker's own flight
    recorder and then publishes the worker's own registry on an
    exposition page the controller never scrapes — the numbers split-brain
    instead of shipping over the pipe."""
    get_flight().record("mesh.worker.spawns")
    conn.send(("page", render_exposition(), None, None))
