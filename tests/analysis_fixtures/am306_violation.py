"""Fixture: bare jax.jit references bypassing the amprof observatory
(AM306). All three shapes fire — the decorator, the partial-wrapped
decorator, and the direct call."""
from functools import partial

import jax


@jax.jit
def merge_rows(state, batch):
    """Anonymous compiled program: its recompiles surface with no
    program name in the flight timeline."""
    return state + batch


@partial(jax.jit, static_argnums=(1,))
def probe_rows(state, page_size):
    return state * page_size


gather = jax.jit(lambda state: state)
