# amlint: durability-plane — fixture: bare writes on the durability plane (AM601)
import json
import os


def save_manifest(path, manifest):
    """The forbidden shape: a plain truncate-and-write of a file the
    recovery scan trusts — a crash mid-write leaves a torn manifest with
    no checksum to catch it and no rename to anchor the commit point."""
    with open(path, "w") as fh:
        fh.write(json.dumps(manifest))


def append_record(fd, frame):
    os.write(fd, frame)
