"""AM202 clean fixture: device math stays in jax.numpy."""
import jax
from jax import jit
import jax.numpy as jnp


@jit
def total(x):
    return jnp.sum(x)
