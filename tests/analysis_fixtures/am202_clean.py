"""AM202 clean fixture: device math stays in jax.numpy."""
import jax
import jax.numpy as jnp


@jax.jit
def total(x):
    return jnp.sum(x)
