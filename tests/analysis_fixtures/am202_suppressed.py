"""AM202 suppressed fixture."""
import jax
import numpy as np


@jax.jit
def total(x):
    return np.asarray(x).sum()  # amlint: disable=AM202
