"""AM202 suppressed fixture."""
import jax
from jax import jit
import numpy as np


@jit
def total(x):
    return np.asarray(x).sum()  # amlint: disable=AM202
