"""AM404 clean fixture: every v2 wire-codec raise is a taxonomy class."""
# amlint: v2-wire-codec
from automerge_tpu.errors import EncodeError, SyncProtocolError


def decode_frame_v2(buf):
    if not buf:
        raise SyncProtocolError("empty v2 frame")
    return buf[1:]


def encode_range(lo, hi):
    if lo >= hi:
        raise EncodeError("range bounds must satisfy lo < hi")
    return (lo, hi)
