"""AM402 clean fixture: the injectable clock/RNG pattern the rule demands."""
# amlint: sync-data-plane
import random


def make_rng(seed):
    # constructing an RNG instance IS the injection point — allowed
    return random.Random(seed)


def deadline_passed(clock, sent_at, timeout):
    return clock() - sent_at > timeout


def backoff(rng, attempt, cap):
    return rng.uniform(0.0, min(cap, 0.5 * 2 ** attempt))
