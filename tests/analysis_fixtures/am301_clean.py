"""AM301 clean fixture: host-only module stays in the host layer."""
# amlint: host-only
from automerge_tpu.columnar import decode_change  # noqa: F401
