# amlint: hot-path — fixture: the vectorised equivalent stays clean


def slot_rows(ops, actions, visible, lam_keys, argsort):
    """Column-mask filtering plus a precomputed sort-key column: per-row
    Python only touches rows that survive the masks."""
    order = argsort(lam_keys, kind="stable")
    keep = [j for j in order if visible[j]]
    return [(ops[j], actions[j]) for j in keep]


def winner_totals(totals, emit_mask):
    return [t for t, emitted in zip(totals, emit_mask) if emitted]
