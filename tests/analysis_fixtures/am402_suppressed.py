"""AM402 suppressed fixture: the single justified real-time default."""
# amlint: sync-data-plane
import time


def default_clock():
    # every other call site takes this (or a test clock) as a parameter
    return time.monotonic()  # amlint: disable=AM402 — the injectable-clock default
