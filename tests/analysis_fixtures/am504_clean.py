# amlint: mesh-data-plane — fixture: struct codecs on the send path,
# receive-side unpickling stays free (AM504)
import pickle

from automerge_tpu.parallel import shm


def stage_delivery(send_ring, batch):
    """The blessed shape: the batch goes through the shm codec straight
    into the mapped slot — counts + lengths + raw bytes, no serializer
    on the path."""
    nbytes = shm.measure_columns(batch)
    slot, gen = send_ring.acquire()
    view = send_ring.slot_view(slot)
    used = shm.encode_columns_into(view, batch)
    del view
    assert used == nbytes
    return send_ring.publish(slot, gen, used)


def materialize_patches(result_ring, ref):
    """Receive-side ``pickle.loads`` is the contract, not a leak: the
    patch blob inside a result frame is opaque pickled bytes by design,
    unpickled lazily straight out of the mapped segment."""
    view = result_ring.accept(ref)
    (off, length), _wires = shm.decode_result(view)
    patches = pickle.loads(view[off:off + length])
    del view
    result_ring.release(ref.slot)
    return patches
