"""AM104 clean fixture: diagnostic names the range it guards."""
MAX_COUNTER = 1 << 24


def check(ctr):
    if ctr >= MAX_COUNTER:
        raise ValueError(f"op counter {ctr} exceeds the merge-key packing range")
