# amlint: hot-path — fixture: per-row assembly anti-patterns (AM105)


def slot_rows(ops, actions, visible, lamport):
    """The old row-at-a-time assembly shape: coerce every row, then sort
    with a per-element Python callback."""
    out = []
    for i in range(len(ops)):
        out.append((int(ops[i]), bool(visible[i]), actions[i]))
    out.sort(key=lambda r: lamport(r[0]))
    return out


def winner_totals(totals, rows):
    return sorted(
        [int(totals[i]) for i in range(len(rows))],
        key=lambda t: (t, 0),
    )
