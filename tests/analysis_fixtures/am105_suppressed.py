# amlint: hot-path — fixture: justified suppressions silence AM105


def debug_rows(ops, visible):
    """A deliberately-cold debug dump inside a hot module."""
    out = []
    for i in range(len(ops)):
        out.append(int(ops[i]))  # amlint: disable=AM105 — debug-only dump
    # amlint: disable=AM105 — tiny fixed-size table, not per-row work
    out.sort(key=lambda v: -v)
    return out
