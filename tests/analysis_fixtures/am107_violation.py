# amlint: hot-path — fixture: per-change/per-op gate loops (AM107)


def gate_round(pending, committed):
    """The scalar causal-gate shape: one Python iteration per change."""
    applied = []
    for change in pending:
        if all(dep in committed for dep in change["deps"]):
            applied.append(change)
    return applied


def transcode(change, rows):
    """The scalar transcode shape: one Python iteration per op."""
    for op in change["ops"]:
        rows.append((op["action"], op["key"]))
    return rows


def drain(applied_ops):
    seen = []
    for entry in applied_ops:
        seen.append(entry)
    return seen
