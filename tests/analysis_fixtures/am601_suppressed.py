# amlint: durability-plane — fixture: justified raw handle silences AM601


def open_wal_appender(path):
    """The one blessed raw handle: the append-only WAL file itself, whose
    every frame carries length + sha256 so recovery proves the torn
    boundary without a rename."""
    # amlint: disable=AM601 — this IS the checksummed appender the rule
    # points everything else at
    return open(path, "ab")
