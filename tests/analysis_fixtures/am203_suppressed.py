"""AM203 suppressed fixture."""
import jax.numpy as jnp


def make_table(n):
    return jnp.zeros((n, n))  # amlint: disable=AM203
