"""AM402 violating fixture: wall-clock and global-RNG calls in supervised
sync control flow."""
# amlint: sync-data-plane
import random
import time
from time import monotonic


def deadline_passed(sent_at, timeout):
    return time.time() - sent_at > timeout


def backoff(attempt, cap):
    time.sleep(min(cap, 0.5 * 2 ** attempt))
    return random.uniform(0.0, cap)


def jitter_now():
    return monotonic() + random.random()
