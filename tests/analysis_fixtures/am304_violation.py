"""AM304 violating fixture: records a metric name with no README catalog
row (and a flight event with no event-catalog row)."""
# amlint: metric-catalog
from automerge_tpu.obs.flight import get_flight
from automerge_tpu.obs.metrics import get_metrics


def work():
    get_metrics().counter("fixture.not_in.catalog").inc()
    get_flight().record("fixture.uncataloged.event", doc=1)
