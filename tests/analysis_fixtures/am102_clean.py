"""AM102 clean fixture: packing shifts use the named constant."""
from automerge_tpu.tpu.engine import ACTOR_BITS


def pack(ctr, actor_idx):
    return (ctr << ACTOR_BITS) | actor_idx
