# amlint: mesh-worker — fixture: shipped telemetry keeps worker code clean


def serve_shard(conn, farm, recorder):
    """The blessed worker shape: the flight recorder arrives injected;
    its unshipped event tail rides the result frame and the controller
    absorbs it into the unified timeline — no exposition access, no
    process-global accessor."""
    op, payload = conn.recv()
    result = farm.apply_changes(payload)
    conn.send(("ok", result, None, recorder.ship()))
