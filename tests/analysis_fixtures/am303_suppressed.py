"""AM303 suppressed fixture."""
import jax
from jax import jit

from automerge_tpu.obs.metrics import get_metrics


@jit
def merge(x):
    get_metrics().counter("merge.calls").inc()  # amlint: disable=AM303
    return x * 2
