"""AM201 clean fixture: data-dependent select stays on device."""
import jax
from jax import jit
import jax.numpy as jnp


@jit
def relu(x):
    return jnp.where(x > 0, x, jnp.zeros_like(x))
