"""AM201 clean fixture: data-dependent select stays on device."""
import jax
import jax.numpy as jnp


@jax.jit
def relu(x):
    return jnp.where(x > 0, x, jnp.zeros_like(x))
