# amlint: hot-path — fixture: justified suppressions silence AM106


def oracle_varint(buf, offset):
    """A deliberate scalar oracle inside a decode module."""
    value = 0
    shift = 0
    # amlint: disable=AM106 — scalar parity oracle for the vector pass
    while buf[offset] & 0x80:
        value |= (buf[offset] & 0x7F) << shift
        shift += 7
        offset += 1
    return value | (buf[offset] << shift), offset + 1
