"""AM503 suppressed fixture: a justified dead handler (staged rollout —
the sender ships in the next release, the handler lands first so old
controllers never hit an unhandled op)."""
# amlint: pipe-protocol


def worker_loop(conn):
    op, payload = conn.recv()
    if op == "apply":
        conn.send(("ok", {}, {}, []))
    # amlint: disable=AM503 — fixture: handler lands one release before
    # its sender so mixed fleets stay compatible during the rollout
    if op == "get_stats":
        conn.send(("ok", {}, {}, []))


class Handle:
    def apply(self, payload):
        return self.call("apply", payload)
