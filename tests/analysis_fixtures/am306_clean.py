"""Fixture: compiled programs registered through the amprof observatory
— the blessed shape AM306 checks for."""
from automerge_tpu.tpu.jitprof import profiled_jit


@profiled_jit("fixture.merge_rows", static_argnames=("page_size",))
def merge_rows(state, batch, page_size):
    """Named program: compiles, dispatch latencies and shape buckets all
    land under ``prof.program.fixture.merge_rows.*``."""
    return state + batch
