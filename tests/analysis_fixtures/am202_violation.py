"""AM202 violating fixture: host numpy applied to a tracer."""
import jax
import numpy as np


@jax.jit
def total(x):
    return np.asarray(x).sum()
