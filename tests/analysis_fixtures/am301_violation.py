"""AM301 violating fixture: host-only module pulls device kernels."""
# amlint: host-only
from automerge_tpu.tpu.engine import ACTOR_BITS  # noqa: F401
