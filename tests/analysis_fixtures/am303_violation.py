"""AM303 violating fixture: metric recording inside traced code."""
import jax

from automerge_tpu.obs.metrics import get_metrics


@jax.jit
def merge(x):
    get_metrics().counter("merge.calls").inc()
    return x * 2
