"""AM103 violating fixture: uncapped interner feeding packed keys."""
from automerge_tpu.tpu.transcode import _Interner

actors = _Interner()
