# amlint: hot-path — fixture: justified suppressions silence AM107


def oracle_gate(pending, committed):
    """A deliberate scalar oracle kept next to the columnar gate."""
    applied = []
    # amlint: disable=AM107 — scalar parity oracle: owns the canonical error
    for change in pending:
        if all(dep in committed for dep in change["deps"]):
            applied.append(change)
    return applied
