# amlint: mesh-routing — fixture: dense per-doc routing loop (AM501)


def route(per_doc_buffers, shard_of, local_of, subs):
    """The O(farm) controller shape: a statement loop scanning every doc
    slot on every delivery, active or not."""
    for d in range(len(per_doc_buffers)):
        subs[shard_of[d]][local_of[d]] = per_doc_buffers[d]
    return subs


def merge(results, shard_of, local_of, num_docs):
    patches = []
    for g in range(num_docs):
        patches.append(results[shard_of[g]][local_of[g]])
    return patches
