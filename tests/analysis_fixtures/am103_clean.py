"""AM103 clean fixture: the packing cap is explicit."""
from automerge_tpu.tpu.transcode import _Interner

actors = _Interner(max_size=1 << 20, name="actor")
