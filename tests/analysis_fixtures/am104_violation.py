"""AM104 violating fixture: diagnostic points at the wrong range."""
MAX_COUNTER = 1 << 24


def check(ctr):
    if ctr >= MAX_COUNTER:
        raise ValueError(f"op counter {ctr} exceeds the rank kernel's packing range")
