"""AM302 suppressed fixture."""
import numpy as np

from automerge_tpu.profiling import get_profile


def dispatch(engine, batch):
    prof = get_profile()
    with prof.phase("device_dispatch"):
        out = engine.apply_batch(batch)
        return np.asarray(out)  # amlint: disable=AM302
