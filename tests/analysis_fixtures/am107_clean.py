# amlint: hot-path — fixture: columnar gate/transcode stays clean (AM107)
import numpy as np


def gate_verdict_columns(dep_idx, dep_counts):
    """The columnar shape: verdicts for the whole delivery from dep-index
    columns — no per-change statement loop."""
    batch = np.ones(len(dep_counts), np.int64)
    batch[np.asarray(dep_idx) < -1] = 0
    return batch


def commit_order(batch):
    committed = np.nonzero(batch > 0)[0]
    return committed[np.argsort(batch[committed], kind="stable")]


def plan_rows(cached_blocks):
    """Sparse bookkeeping comprehensions are fine — they build plan
    lists, not per-op work."""
    return [block.rows for block in cached_blocks if block is not None]
