"""AM204 suppressed fixture."""
import jax

_seen = []


@jax.jit
def record(x):
    _seen.append(x)  # amlint: disable=AM204
    return x
