"""AM204 suppressed fixture."""
import jax
from jax import jit

_seen = []


@jit
def record(x):
    _seen.append(x)  # amlint: disable=AM204
    return x
