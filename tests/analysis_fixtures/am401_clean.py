"""AM401 clean fixture: data-plane raises use the taxonomy."""
# amlint: error-taxonomy
from automerge_tpu.errors import CausalityError, DecodeError


def decode_header(buf):
    if not buf:
        raise DecodeError("empty buffer")
    return buf[0]


def gate(seq, expected):
    if seq < expected:
        raise CausalityError(f"Reuse of sequence number {seq}")
