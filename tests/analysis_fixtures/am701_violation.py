"""AM701 violating fixture: a raw ``len()`` feeds the jit dispatch shape.

Deliberately executable: tests/test_static_analysis.py drives ``drive``
under an enabled observatory+flight and asserts the runtime twin
(``prof.recompile.storm``) fires for the same dispatch the static rule
flags — four distinct batch lengths mean four distinct shapes mean four
XLA compiles inside the storm window.
"""
import jax.numpy as jnp

from automerge_tpu.tpu.jitprof import profiled_jit


@profiled_jit("fixture.shape.raw")
def _embed(xs):
    return xs * 2


def drive(batches):
    outs = []
    for rows in batches:
        n = len(rows)
        outs.append(_embed(jnp.zeros((n,), dtype=jnp.int32)))
    return outs
