"""AM201 suppressed fixture."""
import jax
from jax import jit
import jax.numpy as jnp


@jit
def relu(x):
    if x > 0:  # amlint: disable=AM201
        return x
    return jnp.zeros_like(x)
