"""AM403 violating fixture: blocking calls inside serve event-loop code."""
# amlint: serve-event-loop
import socket
import time
from time import sleep


def flush_wait(batch, jax):
    time.sleep(0.05)
    ready = batch.block_until_ready()
    return jax.device_get(ready)


def dial(host, port):
    return socket.create_connection((host, port))


def nap():
    sleep(1)
