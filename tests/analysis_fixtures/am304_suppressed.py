"""AM304 suppressed fixture: an uncataloged name under a justified
suppression (e.g. an experiment-local metric that must not enter the
operator contract yet)."""
# amlint: metric-catalog
from automerge_tpu.obs.metrics import get_metrics


def work():
    # amlint: disable=AM304 — experiment-local metric, not yet an operator contract
    get_metrics().counter("fixture.experimental.metric").inc()
