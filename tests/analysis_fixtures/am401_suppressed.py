"""AM401 suppressed fixture: a deliberate bare raise with justification."""
# amlint: error-taxonomy


def check_args(hashes):
    if not isinstance(hashes, list):
        raise TypeError("hashes must be a list")  # amlint: disable=AM401 — argument-type validation
