# amlint: hot-path — fixture: per-byte decode loops (AM106)


def read_varint(buf, offset):
    """The scalar LEB128 shape: one Python iteration per byte."""
    value = 0
    shift = 0
    while buf[offset] & 0x80:
        value |= (buf[offset] & 0x7F) << shift
        shift += 7
        offset += 1
    return value | (buf[offset] << shift), offset + 1


def count_runs(data):
    runs = 0
    i = 0
    while i < len(data):
        if not data[i] & 0x80:
            runs += 1
        i += 1
    return runs
