"""AM404 violating fixture: v2 wire-codec raises outside the taxonomy."""
# amlint: v2-wire-codec


def decode_frame_v2(buf):
    if not buf:
        raise RuntimeError("empty v2 frame")
    if buf[0] != 0x45:
        raise LookupError("wrong message type byte")
    return buf[1:]
