# amlint: mesh-worker — fixture: justified suppressions silence AM305


def worker_main(conn, blackbox_path):
    """The one blessed global-recorder pattern: the worker's own flight
    recorder IS the shipping buffer — events leave via ship() over the
    pipe and the bounded black-box file, never an exposition page."""
    # amlint: disable=AM502,AM305 — the worker's own recorder is the
    # shipping buffer; events leave via ship() and the black-box file
    from automerge_tpu.obs.flight import get_flight, write_blackbox

    flight = get_flight()  # amlint: disable=AM502,AM305 — shipping buffer
    flight.enabled = True
    conn.send(("ready", None, None, flight.ship()))
    write_blackbox(blackbox_path, flight)
