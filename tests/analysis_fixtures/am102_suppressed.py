"""AM102 suppressed fixture."""
from automerge_tpu.tpu.engine import ACTOR_BITS


def pack(ctr, actor_idx):
    return (ctr << 20) | actor_idx  # amlint: disable=AM102
