"""AM204 violating fixture: traced code mutates captured host state."""
import jax

_seen = []


@jax.jit
def record(x):
    _seen.append(x)
    return x
