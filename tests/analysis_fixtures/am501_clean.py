# amlint: mesh-routing — fixture: sparse active lists stay clean


def route(per_doc_buffers, shard_of, local_of, subs):
    """The blessed controller shape: comprehension-built sparse active
    list, statement loop only over docs that actually carry buffers."""
    active = [d for d, bufs in enumerate(per_doc_buffers) if bufs]
    for d in active:
        subs[shard_of[d]][local_of[d]] = per_doc_buffers[d]
    return subs


def merge(results, shard_of, local_of, num_docs):
    """Whole-batch transforms are comprehensions: one pass, no
    per-iteration statement overhead."""
    return [results[shard_of[g]][local_of[g]] for g in range(num_docs)]
