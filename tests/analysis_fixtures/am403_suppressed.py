"""AM403 suppressed fixture: the batcher's one justified dispatch point."""
# amlint: serve-event-loop


def dispatch(jax, batch):
    # the flush's single synchronous device readback: every queued doc
    # pays this latency together, which is the whole point of batching
    return jax.device_get(batch)  # amlint: disable=AM403 — the batcher's flush dispatch point
