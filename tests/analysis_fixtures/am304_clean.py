"""AM304 clean fixture: every recorded name has its README catalog row."""
# amlint: metric-catalog
from automerge_tpu.obs.flight import get_flight
from automerge_tpu.obs.metrics import get_metrics


def work():
    get_metrics().counter("farm.changes.applied").inc()
    get_flight().record("batcher.flush", reason="timer")
