"""Fixture: justified ``# amlint: unprofiled-jit`` escapes silence AM306
— the marker is a line suppression with the same trailing/standalone
placement as ``disable=``."""
import jax

# one-shot shape probe: compiled once at import, never dispatched on the
# hot path, so observatory attribution would only add noise
probe = jax.jit(lambda x: x * 2)  # amlint: unprofiled-jit — import-time probe

# amlint: unprofiled-jit — microbench-only reference program
reference = jax.jit(lambda x: x + 1)
