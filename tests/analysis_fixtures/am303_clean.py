"""AM303 clean fixture: recording happens on the host, around the dispatch."""
import jax
from jax import jit

from automerge_tpu.obs.metrics import get_metrics


@jit
def merge(x):
    return x * 2


def dispatch(x):
    out = merge(x)
    get_metrics().counter("merge.calls").inc()
    return out
