"""AM101 violating fixture: mask does not match its bit width."""
ACTOR_BITS = 20
ACTOR_MASK = (1 << 19) - 1  # wrong: one bit short of ACTOR_BITS
