"""AM102 violating fixture: hardcoded packing shift."""
from automerge_tpu.tpu.engine import ACTOR_BITS


def pack(ctr, actor_idx):
    return (ctr << 20) | actor_idx
