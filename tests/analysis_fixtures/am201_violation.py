"""AM201 violating fixture: Python branch on a traced value."""
import jax
import jax.numpy as jnp


@jax.jit
def relu(x):
    if x > 0:
        return x
    return jnp.zeros_like(x)
