"""AM103 suppressed fixture."""
from automerge_tpu.tpu.transcode import _Interner

# amlint: disable=AM103 — payload table, never packed into merge keys
values = _Interner()
