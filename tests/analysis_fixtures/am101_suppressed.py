"""AM101 suppressed fixture."""
ACTOR_BITS = 20
ACTOR_MASK = (1 << 19) - 1  # amlint: disable=AM101
