"""AM403 clean fixture: the non-blocking serve event-loop idiom —
cooperative sleeps, injected clocks, transports owned by asyncio."""
# amlint: serve-event-loop
import asyncio


async def flush_loop(server, interval):
    while True:
        await asyncio.sleep(interval)  # cooperative: yields the loop
        server.tick()


def due(clock, window_start, interval):
    return clock() - window_start >= interval


async def serve(handler, host, port):
    return await asyncio.start_server(handler, host, port)
