"""AM301 suppressed fixture."""
# amlint: host-only
from automerge_tpu.tpu.engine import ACTOR_BITS  # noqa: F401  # amlint: disable=AM301
