"""AM503 clean fixture: the pipe contract holds — every sent op has a
handler arm and vice versa, responses are 4-tuples and requests
2-tuples at every construction and unpack site, and every response
field the controller reads is written by a worker-side producer."""
# amlint: pipe-protocol


def result_to_wire():
    resp = {"patches": [], "outcomes": []}
    resp["wall_s"] = 0.0
    return resp


def worker_loop(conn):
    while True:
        op, payload = conn.recv()
        if op == "shutdown":
            conn.send(("ok", None, {}, []))
            return
        if op == "apply":
            conn.send(("ok", result_to_wire(), {}, []))


class Handle:
    def request(self, op, payload):
        self.conn.send((op, payload))

    def close(self):
        self.conn.send(("shutdown", None))

    def apply(self, payload):
        resp = self.call("apply", payload)
        return resp["patches"], resp.get("wall_s")

    def call(self, op, payload):
        self.request(op, payload)
        status, data, metrics, events = self._recv()
        return data
