"""AM503 violating fixture: pipe-protocol drift in one mini
controller/worker pair — a response frame missing its flight_events
element, a dead handler, a sent op with no handler arm, and a response
field read that no worker-side producer writes."""
# amlint: pipe-protocol


def _do_apply(payload):
    resp = {"outcomes": []}
    resp["wall_s"] = 0.0
    return resp


def worker_loop(conn):
    while True:
        op, payload = conn.recv()
        if op == "shutdown":
            conn.send(("ok", None, {}))  # 3-tuple: drops flight_events
            return
        if op == "get_stats":  # dead handler: nothing sends get_stats
            conn.send(("ok", {}, {}, []))
        if op == "apply":
            conn.send(("ok", _do_apply(payload), {}, []))


class Handle:
    def apply(self, payload):
        resp = self.call("apply_changes", payload)  # no handler arm
        return resp["patches"]  # no producer writes "patches"

    def call(self, op, payload):
        self.conn.send((op, payload))
        status, data, metrics, events = self._recv()
        return data
