"""AM401 violating fixture: bare stdlib raises on the data plane."""
# amlint: error-taxonomy


def decode_header(buf):
    if not buf:
        raise ValueError("empty buffer")
    if not isinstance(buf, bytes):
        raise TypeError("not bytes")
    return buf[0]
