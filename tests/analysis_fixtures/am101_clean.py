"""AM101 clean fixture: a self-consistent canonical layout."""
ACTOR_BITS = 20
ACTOR_MASK = (1 << ACTOR_BITS) - 1
_OP_BITS = 44
_OP_MASK = (1 << _OP_BITS) - 1
MAX_COUNTER = 1 << (_OP_BITS - ACTOR_BITS)
MAX_ELEMS = 1 << (63 - _OP_BITS)
