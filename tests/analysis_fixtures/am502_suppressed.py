# amlint: mesh-worker — fixture: justified suppressions silence AM502


def worker_main(conn):
    """The one blessed global-registry pattern: the worker records into
    ITS OWN process singleton and ships deltas over the pipe."""
    # amlint: disable=AM502 — this is the worker process's own registry,
    # used as the delta shipping buffer, never the controller's
    from automerge_tpu.obs.metrics import get_metrics

    metrics = get_metrics()  # amlint: disable=AM502 — same shipping buffer
    metrics.enable()
    conn.send(("ready", metrics.frame(), None))
