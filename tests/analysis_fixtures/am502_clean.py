# amlint: mesh-worker — fixture: injected sinks keep worker code clean


def serve_shard(conn, farm, registry):
    """The blessed worker shape: the farm and the metrics sink arrive as
    arguments; results and metric deltas ship back over the pipe."""
    last = registry.frame()
    op, payload = conn.recv()
    result = farm.apply_changes(payload)
    delta = {
        name: entry for name, entry in registry.frame().items()
        if entry != last.get(name)
    }
    conn.send(("ok", result, delta))
