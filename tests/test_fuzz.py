"""Convergence fuzzing against an independent reference-model oracle.

Pattern from the reference suite (/root/reference/test/fuzz_test.js): a
~100-line miniature CRDT (LWW maps + RGA lists, no columnar anything) is the
executable specification. Random changes are applied through the full
backend in several different (causally valid) orders, and the materialised
documents must match the oracle and each other. Save/load round trips are
interleaved to cover persistence.
"""
import itertools
import random

import automerge_tpu as am
from automerge_tpu.columnar import encode_change
from automerge_tpu.common import parse_op_id
from automerge_tpu.frontend.datatypes import List as AmList, Map as AmMap


class Micromerge:
    """Miniature model CRDT: maps with LWW per key, lists with RGA insertion
    ordering. Used as the expected-behaviour oracle."""

    def __init__(self):
        self.by_actor = {}
        self.by_obj = {"_root": {}}
        self.meta = {"_root": {}}

    @property
    def root(self):
        return self.by_obj["_root"]

    @staticmethod
    def _earlier(id1, id2):
        p1, p2 = parse_op_id(id1), parse_op_id(id2)
        return (p1.counter, p1.actor_id) < (p2.counter, p2.actor_id)

    def apply_change(self, change):
        last_seq = len(self.by_actor.get(change["actor"], []))
        if change["seq"] != last_seq + 1:
            raise ValueError(f"Expected sequence number {last_seq + 1}, got {change['seq']}")
        self.by_actor.setdefault(change["actor"], []).append(change)
        for index, op in enumerate(change["ops"]):
            self._apply_op(dict(op, opId=f"{change['startOp'] + index}@{change['actor']}"))

    def _apply_op(self, op):
        if op["obj"] not in self.meta:
            raise ValueError(f"Object does not exist: {op['obj']}")
        if op["action"] == "makeMap":
            self.by_obj[op["opId"]] = {}
            self.meta[op["opId"]] = {}
        elif op["action"] == "makeList":
            self.by_obj[op["opId"]] = []
            self.meta[op["opId"]] = []
        elif op["action"] not in ("set", "del"):
            raise ValueError(f"Unsupported operation type: {op['action']}")

        if isinstance(self.meta[op["obj"]], list):
            if op.get("insert"):
                self._list_insert(op)
            else:
                self._list_update(op)
        else:
            # Map keys are multi-value registers: an op removes exactly the
            # values it names in pred (so a concurrent set survives a
            # delete); the visible winner is the remaining op with the
            # greatest Lamport opId.
            key = op["key"]
            values = self.meta[op["obj"]].setdefault(key, {})
            for pred in op.get("pred", []):
                values.pop(pred, None)
            if op["action"].startswith("make"):
                values[op["opId"]] = self.by_obj[op["opId"]]
            elif op["action"] == "set":
                values[op["opId"]] = op["value"]
            if values:
                winner = max(
                    values.keys(),
                    key=lambda o: (parse_op_id(o).counter, parse_op_id(o).actor_id),
                )
                self.by_obj[op["obj"]][key] = values[winner]
            else:
                self.by_obj[op["obj"]].pop(key, None)

    def _find(self, obj_id, elem_id):
        meta = self.meta[obj_id]
        visible = 0
        for index, entry in enumerate(meta):
            if entry["elemId"] == elem_id:
                return index, visible
            if not entry["deleted"]:
                visible += 1
        raise ValueError(f"List element not found: {elem_id}")

    def _list_insert(self, op):
        meta = self.meta[op["obj"]]
        value = self.by_obj[op["opId"]] if op["action"].startswith("make") else op["value"]
        elem_ref = op.get("elemId", op.get("key"))
        if elem_ref == "_head":
            index, visible = -1, 0
        else:
            index, visible = self._find(op["obj"], elem_ref)
        if index >= 0 and not meta[index]["deleted"]:
            visible += 1
        index += 1
        while index < len(meta) and self._earlier(op["opId"], meta[index]["elemId"]):
            if not meta[index]["deleted"]:
                visible += 1
            index += 1
        meta.insert(index, {"elemId": op["opId"], "valueId": op["opId"], "deleted": False})
        self.by_obj[op["obj"]].insert(visible, value)

    def _list_update(self, op):
        elem_ref = op.get("elemId", op.get("key"))
        index, visible = self._find(op["obj"], elem_ref)
        meta = self.meta[op["obj"]][index]
        if op["action"] == "del":
            if not meta["deleted"]:
                del self.by_obj[op["obj"]][visible]
            meta["deleted"] = True
        elif self._earlier(meta["valueId"], op["opId"]):
            if not meta["deleted"]:
                value = self.by_obj[op["opId"]] if op["action"].startswith("make") else op["value"]
                self.by_obj[op["obj"]][visible] = value
            meta["valueId"] = op["opId"]


def materialize(value):
    """Converts a document tree to plain dict/list/primitives."""
    if isinstance(value, (AmMap, dict)):
        return {k: materialize(v) for k, v in value.items()}
    if isinstance(value, (AmList, list)):
        return [materialize(v) for v in value]
    return value


class ChangeGenerator:
    """Generates random causally-consistent changes across several actors,
    operating on the root map and one shared list."""

    def __init__(self, seed, num_actors=3):
        self.rng = random.Random(seed)
        self.actors = [f"{chr(97 + i) * 8}" for i in range(num_actors)]

    def generate(self, num_changes):
        """Simulates replicas that all start from one initial change and then
        make concurrent edits, periodically 'seeing' each other's changes.
        Returns a list of change dicts in a causally valid order."""
        rng = self.rng
        init_actor = self.actors[0]
        changes = []
        list_obj = f"1@{init_actor}"
        init = {
            "actor": init_actor, "seq": 1, "startOp": 1, "time": 0, "deps": [],
            "ops": [
                {"action": "makeList", "obj": "_root", "key": "list", "pred": []},
                {"action": "set", "obj": list_obj, "elemId": "_head", "insert": True,
                 "value": "seed", "pred": []},
            ],
        }
        changes.append(init)
        init_hash = am.decode_change(encode_change(init))["hash"]

        # per-actor view of the world: (seq, max_op, deps, known elems, key preds)
        views = {
            a: {
                "seq": 1 if a == init_actor else 0,
                "max_op": 2,
                "deps": [init_hash],
                "elems": [(f"2@{init_actor}", f"2@{init_actor}")],  # (elemId, valueOpId)
                "keys": {},
                "hashes": [init_hash],
            }
            for a in self.actors
        }

        for _ in range(num_changes):
            actor = rng.choice(self.actors)
            view = views[actor]
            view["seq"] += 1
            start_op = view["max_op"] + 1
            ctr = start_op
            ops = []
            for _ in range(rng.randrange(1, 4)):
                kind = rng.random()
                if kind < 0.4:
                    key = f"k{rng.randrange(5)}"
                    pred = view["keys"].get(key, [])
                    ops.append({"action": "set", "obj": "_root", "key": key,
                                "datatype": "uint", "value": rng.randrange(100), "pred": pred})
                    view["keys"][key] = [f"{ctr}@{actor}"]
                elif kind < 0.7 and view["elems"]:
                    ref = rng.choice([e for e, _v in view["elems"]] + ["_head"])
                    ops.append({"action": "set", "obj": list_obj,
                                "elemId": ref, "insert": True,
                                "value": rng.randrange(100), "pred": []})
                    view["elems"].append((f"{ctr}@{actor}", f"{ctr}@{actor}"))
                elif kind < 0.85 and view["elems"]:
                    elem_id, value_id = rng.choice(view["elems"])
                    ops.append({"action": "set", "obj": list_obj, "elemId": elem_id,
                                "insert": False, "value": rng.randrange(100),
                                "pred": [value_id]})
                    view["elems"] = [
                        (e, f"{ctr}@{actor}" if e == elem_id else v) for e, v in view["elems"]
                    ]
                else:
                    key = f"k{rng.randrange(5)}"
                    pred = view["keys"].get(key)
                    if not pred:
                        continue
                    ops.append({"action": "del", "obj": "_root", "key": key, "pred": pred})
                    view["keys"][key] = []
                ctr += 1
            if not ops:
                view["seq"] -= 1
                continue
            change = {"actor": actor, "seq": view["seq"], "startOp": start_op,
                      "time": 0, "deps": sorted(view["deps"]), "ops": ops}
            changes.append(change)
            view["max_op"] = ctr - 1
            h = am.decode_change(encode_change(change))["hash"]
            view["deps"] = [h]
            view["hashes"].append(h)

            # occasionally sync this actor's view with another's (merge views)
            if rng.random() < 0.4:
                other = views[rng.choice(self.actors)]
                merged_deps = sorted(set(view["deps"]) | set(other["deps"]))
                other_elems = {e: v for e, v in other["elems"]}
                for e, v in view["elems"]:
                    if e not in other_elems:
                        other_elems[e] = v
                # keep value ids with the greater opId on shared elems
                for e, v in view["elems"]:
                    if e in other_elems:
                        pv = parse_op_id(other_elems[e])
                        nv = parse_op_id(v)
                        if (nv.counter, nv.actor_id) > (pv.counter, pv.actor_id):
                            other_elems[e] = v
                merged_keys = dict(other["keys"])
                for k, preds in view["keys"].items():
                    if k not in merged_keys:
                        merged_keys[k] = preds
                    else:
                        merged_keys[k] = sorted(
                            set(merged_keys[k]) | set(preds),
                            key=lambda p: (parse_op_id(p).counter, parse_op_id(p).actor_id),
                        )
                other["deps"] = merged_deps
                other["elems"] = sorted(other_elems.items())
                other["keys"] = merged_keys
                other["max_op"] = max(other["max_op"], view["max_op"])
                view["deps"] = merged_deps
                view["elems"] = list(other["elems"])
                view["keys"] = dict(merged_keys)
        return changes


def apply_via_backend(changes, shuffle_seed=None):
    """Applies binary changes through the full backend; optionally in a
    shuffled (but causally buffered) order. The document is materialised via
    save/load: CRDT convergence is guaranteed on the backend state. (The
    *incremental* patch stream is not asserted order-independent here: as in
    the reference engine, a merge run grouping several ascending keys can
    walk over an unrelated doc op without re-emitting it in the patch --
    new.js:1125-1128 with the silent take-doc-op branch at new.js:1225-1230
    -- so intermediate frontend views may transiently differ by arrival
    order until the next full materialisation.)"""
    binaries = [encode_change(c) for c in changes]
    if shuffle_seed is not None:
        rng = random.Random(shuffle_seed)
        binaries = binaries[:1] + rng.sample(binaries[1:], len(binaries) - 1)
    doc = am.init("ffffffff")
    doc, _patch = am.apply_changes(doc, binaries)
    return am.load(am.save(doc), "ffffffff")


class TestFuzzConvergence:
    def test_backend_matches_oracle(self):
        for seed in range(5):
            changes = ChangeGenerator(seed).generate(15)
            oracle = Micromerge()
            for change in changes:
                oracle.apply_change(change)
            doc = apply_via_backend(changes)
            assert materialize(doc) == materialize(oracle.root), f"seed {seed}"

    def test_order_independence(self):
        for seed in range(5):
            changes = ChangeGenerator(seed + 100).generate(12)
            reference = materialize(apply_via_backend(changes))
            for shuffle in range(3):
                shuffled = materialize(apply_via_backend(changes, shuffle_seed=shuffle))
                assert shuffled == reference, f"seed {seed} shuffle {shuffle}"

    def test_save_load_mid_stream(self):
        for seed in range(3):
            changes = ChangeGenerator(seed + 200).generate(12)
            binaries = [encode_change(c) for c in changes]
            mid = len(binaries) // 2
            doc = am.init("ffffffff")
            doc, _ = am.apply_changes(doc, binaries[:mid])
            doc = am.load(am.save(doc), "eeeeeeee")
            doc, _ = am.apply_changes(doc, binaries[mid:])
            expected = materialize(apply_via_backend(changes))
            assert materialize(doc) == expected, f"seed {seed}"

    def test_save_load_byte_stability(self):
        for seed in range(3):
            changes = ChangeGenerator(seed + 300).generate(10)
            doc = apply_via_backend(changes)
            saved = am.save(doc)
            doc2 = am.load(saved)
            state = am.Frontend.get_backend_state(doc2, "x")
            state.state.binary_doc = None  # force re-encode from op rows
            assert state.state.save() == saved, f"seed {seed}"
