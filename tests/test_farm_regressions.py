"""Regression tests for farm packing-limit and routing holes (round-2
ADVICE findings): each test fails on the pre-fix code.

- interner caps: the raw _Interner used by the farm bypassed the 2^19 slot
  guard that only existed in BatchTranscoder.slot_id
- map-op counters >= 2^24 silently corrupted the engine's merge-key sort
  order (the guard only covered insert ops)
- _prevalidate_limits counted duplicate deliveries' inserts (spurious
  rejections) and ignored queued changes (mid-commit failures later)
- a queued list-targeting change released by a later map-only delivery
  bypassed the list walk and crashed patch assembly
"""
import pytest

from automerge_tpu.opset import OpSet
from automerge_tpu.tpu import rga
from automerge_tpu.tpu.farm import TpuDocFarm
from automerge_tpu.tpu.transcode import _Interner
from automerge_tpu.columnar import decode_change_columns, encode_change


def make_change(actor, seq, start_op, deps, ops):
    buf = encode_change(
        {"actor": actor, "seq": seq, "startOp": start_op, "time": 0,
         "deps": sorted(deps), "ops": ops}
    )
    return buf, decode_change_columns(buf)["hash"]


def test_interner_max_size_enforced():
    interner = _Interner(max_size=2, name="slot")
    assert interner.intern("a") == 0
    assert interner.intern("b") == 1
    assert interner.intern("a") == 0  # existing entries still resolve
    with pytest.raises(ValueError, match="slot table overflow"):
        interner.intern("c")


def test_farm_interners_are_capped():
    farm = TpuDocFarm(1)
    assert farm.slots.max_size == 1 << 19
    assert farm.actors.max_size == 1 << 20


def test_map_op_counter_beyond_packing_range_rejected():
    farm = TpuDocFarm(1)
    buf, _ = make_change(
        "aaaaaaaa", 1, 1, [],
        [{"action": "set", "obj": "_root", "key": "a", "value": 1, "pred": []}],
    )
    farm.apply_changes([[buf]])
    # startOp at 2^24 overflows the (slot << 44 | ctr << 20 | actor) packing
    big, _ = make_change(
        "aaaaaaaa", 2, 1 << 24, [farm.get_heads(0)[0]],
        [{"action": "set", "obj": "_root", "key": "b", "value": 2, "pred": []}],
    )
    with pytest.raises(ValueError, match="packing range"):
        farm.apply_changes([[big]], isolation="batch")
    # default per-doc isolation quarantines the delivery instead of raising
    result = farm.apply_changes([[big]])
    assert result.outcomes[0].status == "quarantined"
    assert result.outcomes[0].error_kind == "packing"
    # nothing committed: the doc still has exactly one applied change
    assert len(farm.get_all_changes(0)) == 1
    patch = farm.get_patch(0)
    assert set(patch["diffs"]["props"]) == {"a"}


def _insert_ops(n, after=None):
    ops = []
    ref = after
    for _ in range(n):
        ops.append({"action": "set", "obj": "1@aaaaaaaa", "elemId": ref or "_head",
                    "insert": True, "value": "x", "pred": []})
        ref = None  # consecutive inserts chain on the previous op implicitly
    return ops


def test_duplicate_delivery_does_not_count_toward_elem_budget(monkeypatch):
    monkeypatch.setattr(rga, "MAX_ELEMS", 4)
    farm = TpuDocFarm(1)
    opset = OpSet()
    buf1, h1 = make_change(
        "aaaaaaaa", 1, 1, [], [{"action": "makeList", "obj": "_root", "key": "l", "pred": []}]
    )
    buf2, _ = make_change("aaaaaaaa", 2, 2, [h1], _insert_ops(3))
    farm.apply_changes([[buf1]])
    farm.apply_changes([[buf2]])
    opset.apply_changes([buf1, buf2])
    # re-delivering the same change must be a no-op, not a capacity error
    patch = farm.apply_changes([[buf2]])[0]
    expected = opset.apply_changes([buf2])
    assert patch == expected


def test_queued_inserts_count_toward_elem_budget(monkeypatch):
    monkeypatch.setattr(rga, "MAX_ELEMS", 4)
    farm = TpuDocFarm(1)
    buf1, h1 = make_change(
        "aaaaaaaa", 1, 1, [], [{"action": "makeList", "obj": "_root", "key": "l", "pred": []}]
    )
    farm.apply_changes([[buf1]])
    # queued change with 2 inserts (dep never delivered)
    missing_dep = "0000000000000000000000000000000000000000000000000000000000000000"
    qbuf, _ = make_change("bbbbbbbb", 1, 10, [missing_dep], _insert_ops(2))
    farm.apply_changes([[qbuf]])
    assert farm.get_patch(0)["pendingChanges"] == 1
    # 3 more inserts would pass alone (0 applied + 3 <= 4) but must be
    # rejected up front: the queued 2 could become ready in the same call
    buf3, _ = make_change("aaaaaaaa", 2, 2, [h1], _insert_ops(3))
    with pytest.raises(ValueError, match="list elements"):
        farm.apply_changes([[buf3]], isolation="batch")
    result = farm.apply_changes([[buf3]])  # per-doc isolation: quarantined
    assert result.outcomes[0].status == "quarantined"
    assert len(farm.get_all_changes(0)) == 1  # nothing committed


def test_queued_list_change_released_by_map_only_delivery():
    """A list-targeting change sitting in the queue must keep producing
    reference-exact patches when a later map-only delivery releases it
    (pins the queue-release routing that patch assembly relies on)."""
    farm = TpuDocFarm(1)
    opset = OpSet()
    buf1, h1 = make_change(
        "aaaaaaaa", 1, 1, [],
        [{"action": "set", "obj": "_root", "key": "a", "value": 1, "pred": []}],
    )
    buf2, h2 = make_change(
        "bbbbbbbb", 1, 2, [h1],
        [{"action": "makeList", "obj": "_root", "key": "l", "pred": []}]
        + [{"action": "set", "obj": "2@bbbbbbbb", "elemId": "_head",
            "insert": True, "value": "x", "pred": []}],
    )
    # deliver the list change first: it queues on the missing dep
    p_farm = farm.apply_changes([[buf2]])[0]
    p_ref = opset.apply_changes([buf2])
    assert p_farm == p_ref
    # the map-only delivery releases it; both must apply atomically
    p_farm = farm.apply_changes([[buf1]])[0]
    p_ref = opset.apply_changes([buf1])
    assert p_farm == p_ref
    assert farm.get_patch(0) == opset.get_patch()


def test_prevalidation_skipped_for_docs_with_no_delivery(monkeypatch):
    """Docs that receive no changes in an apply_changes call must not pay
    the O(queue ops) prevalidation re-scan: their queue was validated at its
    original delivery and cannot become ready without new changes (ADVICE
    round 5). Counts the prevalidation work via a spy."""
    farm = TpuDocFarm(2)
    missing_dep = "00" * 32
    qbuf, _ = make_change("bbbbbbbb", 1, 10, [missing_dep],
                          [{"action": "set", "obj": "_root", "key": "q",
                            "value": 1, "pred": []}])
    farm.apply_changes([[qbuf], []])
    assert farm.get_patch(0)["pendingChanges"] == 1

    prevalidated = []
    orig = TpuDocFarm._prevalidate_limits

    def spy(self, d, decoded):
        prevalidated.append(d)
        return orig(self, d, decoded)

    monkeypatch.setattr(TpuDocFarm, "_prevalidate_limits", spy)
    buf, _ = make_change("aaaaaaaa", 1, 1, [],
                         [{"action": "set", "obj": "_root", "key": "a",
                           "value": 1, "pred": []}])
    # doc 0 gets nothing (its stuck queue must not be re-scanned);
    # doc 1 receives a change and must still be prevalidated
    farm.apply_changes([[], [buf]])
    assert prevalidated == [1]
    # a doc that receives changes keeps validating its queue too
    buf2, _ = make_change("aaaaaaaa", 1, 1, [],
                          [{"action": "set", "obj": "_root", "key": "b",
                            "value": 2, "pred": []}])
    farm.apply_changes([[buf2], []])
    assert prevalidated == [1, 0]
