"""Parity suite for the incremental visibility readback + vectorized patch
assembly (ISSUE 4).

The farm's patch contract is defined by the sequential reference walk
(OpSet): whatever the host mirror caches, however little the scoped
readback transfers, and however the assembly masks are computed, every
patch must stay BYTE-IDENTICAL (asserted via canonical JSON, stricter than
dict equality) to the walk's output — across random fuzz workloads, across
quarantine/rollback interleavings from the fault corpus (the visibility
cache must be invalidated on rollback), and across device-failure fallback
interleavings. A separate invariant test pins the host row mirror to the
device state via the retained full-readback path (_read_visibility).
"""
import json

import numpy as np
import pytest

from automerge_tpu.opset import OpSet
from automerge_tpu.testing import faults
from automerge_tpu.tpu.farm import TpuDocFarm

from test_farm import Workload

SEEDS = [11, 23, 47]
ROUNDS = 10


def canon(patch):
    """Canonical bytes of a patch: nested child patches are plain dicts, so
    sorted-key JSON is a byte-exact representation."""
    return json.dumps(patch, sort_keys=True)


def assert_patch_equal(got, want, context=""):
    assert canon(got) == canon(want), (
        f"{context}: patch diverged from the reference walk\n"
        f"got:  {canon(got)}\nwant: {canon(want)}"
    )


def run_workload(seed, num_docs=3, rounds=ROUNDS, deliver=None):
    """Drives `num_docs` copies of one random workload through a farm and
    per-doc OpSet oracles, asserting per-call patch parity. `deliver` can
    rewrite the per-doc delivery (fault interleavings)."""
    farm = TpuDocFarm(num_docs, capacity=64, quarantine_threshold=None)
    oracles = [OpSet() for _ in range(num_docs)]
    workload = Workload(seed)
    for r in range(rounds):
        # the oracle state BEFORE delivery drives generation (test_farm)
        buffers = workload.next_round(oracles[0])
        if not buffers:
            continue
        per_doc = [list(buffers) for _ in range(num_docs)]
        if deliver is not None:
            per_doc = deliver(r, per_doc)
        patches = farm.apply_changes(per_doc)
        for d in range(num_docs):
            want = oracles[d].apply_changes(list(per_doc[d]))
            assert_patch_equal(
                patches[d], want, f"seed={seed} round={r} doc={d}"
            )
    for d in range(num_docs):
        assert_patch_equal(
            farm.get_patch(d), oracles[d].get_patch(),
            f"seed={seed} whole-doc doc={d}",
        )
    return farm, oracles


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_corpus_patch_parity(seed):
    """Random map-family workloads (concurrent actors, counters, nesting,
    deletes, delayed delivery): every incremental patch and the final
    whole-doc patch are byte-identical to the reference walk."""
    run_workload(seed)


@pytest.mark.parametrize("seed", SEEDS)
def test_mirror_matches_device_state(seed):
    """The host row mirror IS the device op table: keys/opIds/actions match
    the full-state readback row for row, and the refreshed visible/total
    cache matches the device visibility program for every live row."""
    farm, _ = run_workload(seed)
    keys, ops, visible, totals, actions = farm._read_visibility()
    for d in range(farm.num_docs):
        farm._refresh_visibility([d])
        n = farm._vis_mkey[d].shape[0]
        assert int((np.asarray(keys[d]) != np.iinfo(np.int32).max).sum()) == n
        np.testing.assert_array_equal(farm._vis_key[d], keys[d][:n])
        np.testing.assert_array_equal(farm._vis_op[d], ops[d][:n])
        np.testing.assert_array_equal(farm._vis_action[d], actions[d][:n])
        np.testing.assert_array_equal(farm._vis_visible[d], visible[d][:n])
        np.testing.assert_array_equal(farm._vis_total[d], totals[d][:n])


@pytest.mark.parametrize("name,corrupt,kind", faults.BYTE_CORPUS)
def test_quarantine_rollback_keeps_parity(name, corrupt, kind):
    """A poisoned delivery quarantines one doc (state rolled back, cache
    invalidated); subsequent clean deliveries to that doc must still
    produce byte-identical patches — stale cached visibility after the
    rollback would diverge here."""
    poison_round, poison_doc = 3, 1

    def deliver(r, per_doc):
        if r == poison_round and per_doc[poison_doc]:
            per_doc[poison_doc] = [
                bytes(corrupt(buf)) for buf in per_doc[poison_doc]
            ]
        return per_doc

    num_docs = 3
    farm = TpuDocFarm(num_docs, capacity=64, quarantine_threshold=None)
    oracles = [OpSet() for _ in range(num_docs)]
    workload = Workload(7)
    saw_quarantine = False
    for r in range(ROUNDS):
        buffers = workload.next_round(oracles[0])
        if not buffers:
            continue
        per_doc = deliver(r, [list(buffers) for _ in range(num_docs)])
        patches = farm.apply_changes(per_doc)
        for d in range(num_docs):
            if patches.outcomes[d].status == "quarantined":
                saw_quarantine = True
                assert d == poison_doc and r == poison_round
                continue  # oracle does not see the poisoned delivery
            want = oracles[d].apply_changes(list(per_doc[d]))
            assert_patch_equal(patches[d], want, f"{name} round={r} doc={d}")
    # the poisoned doc diverges from its oracle only by the dropped
    # delivery; both must agree on their own full state
    for d in range(num_docs):
        if d == poison_doc and saw_quarantine:
            continue
        assert_patch_equal(farm.get_patch(d), oracles[d].get_patch(), name)


def test_gate_rollback_mid_batch_keeps_parity():
    """A causality fault AFTER earlier changes of the same call committed
    exercises the deepest rollback (partial gate commit + mirror-adjacent
    state): the visibility cache must be invalidated with it."""
    farm = TpuDocFarm(2, capacity=64, quarantine_threshold=None)
    oracle = OpSet()

    import automerge_tpu.columnar as col

    a1 = faults.make_change("aa" * 4, 1, 1, [], [faults.set_op("k", 1)])
    farm.apply_changes([[a1], [a1]])
    oracle.apply_changes([a1])
    h1 = col.decode_change_columns(a1)["hash"]
    a2 = faults.make_change("aa" * 4, 2, 2, [h1], [faults.set_op("k", 2)])
    a2_dup_seq = faults.make_change(
        "aa" * 4, 2, 3, [col.decode_change_columns(a2)["hash"]],
        [faults.set_op("k", 3)],
    )
    # doc 0: valid a2 then seq-reuse -> whole delivery rolls back
    result = farm.apply_changes([[a2, a2_dup_seq], [a2]])
    assert result.outcomes[0].status == "quarantined"
    assert result.outcomes[1].status == "applied"
    want = oracle.apply_changes([a2])
    assert_patch_equal(result[1], want, "doc 1 beside a rollback")
    # doc 0 state must equal the pre-call state (a1 only)
    pre = OpSet()
    pre.apply_changes([a1])
    assert_patch_equal(farm.get_patch(0), pre.get_patch(), "rolled-back doc")
    # and a clean retry of a2 lands byte-identically
    retry = farm.apply_changes([[a2], []])
    assert_patch_equal(retry[0], want, "retry after rollback")


def test_device_failure_fallback_interleaving_keeps_parity():
    """Mid-stream device failure: the poisoned doc quarantines, survivors
    fall back to the walk for that call, and every later call (device
    healthy again) stays byte-identical — including whole-doc reads."""
    num_docs = 4
    farm = TpuDocFarm(num_docs, capacity=64, quarantine_threshold=None)
    oracles = [OpSet() for _ in range(num_docs)]
    workload = Workload(13)
    for r in range(ROUNDS):
        buffers = workload.next_round(oracles[0])
        if not buffers:
            continue
        per_doc = [list(buffers) for _ in range(num_docs)]
        if r == 4:
            with faults.inject("farm.device_dispatch", faults.fail_docs([2])):
                patches = farm.apply_changes(per_doc)
        else:
            patches = farm.apply_changes(per_doc)
        for d in range(num_docs):
            if patches.outcomes[d].status == "quarantined":
                assert r == 4 and d == 2
                continue
            want = oracles[d].apply_changes(list(per_doc[d]))
            assert_patch_equal(patches[d], want, f"round={r} doc={d}")
    for d in range(num_docs):
        if d == 2:
            continue
        assert_patch_equal(
            farm.get_patch(d), oracles[d].get_patch(), f"whole-doc {d}"
        )


def test_decode_cache_shares_parses_not_state():
    """One buffer fanned to N docs is decoded once, but each doc's gate/
    state stays independent: byte-identical patches for every doc, and the
    cache survives duplicate (no-op) redelivery."""
    from automerge_tpu.obs.metrics import enabled_metrics, get_metrics

    num_docs = 8
    farm = TpuDocFarm(num_docs, capacity=32)
    oracles = [OpSet() for _ in range(num_docs)]
    a1 = faults.make_change("bb" * 4, 1, 1, [], [faults.set_op("x", 41)])
    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        patches = farm.apply_changes([[a1]] * num_docs)
        for d in range(num_docs):
            want = oracles[d].apply_changes([a1])
            assert_patch_equal(patches[d], want, f"fanout doc={d}")
        # duplicate redelivery is a no-op for every doc
        dup = farm.apply_changes([[a1]] * num_docs)
        for d in range(num_docs):
            want = oracles[d].apply_changes([a1])
            assert_patch_equal(dup[d], want, f"duplicate doc={d}")
    hits = reg.counter("codecs.decode_cache.hits").value
    misses = reg.counter("codecs.decode_cache.misses").value
    assert hits >= 2 * num_docs - 1 - misses
    assert misses <= 1
