"""amserve suite (ISSUE 6): session multiplexer, dynamic batcher, parity
and chaos/poison composition.

Everything runs in simulated time on a ManualClock — the server core is
sans-io, so tests drive receive/tick/pump directly and the batching
window, retransmission deadlines and backoff never sleep for real.
"""
import json
import random

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu.errors import (
    AdmissionRejectedError,
    AutomergeError,
    BackpressureError,
    DecodeError,
)
from automerge_tpu.serve import AmServer, BatcherConfig, LoadConfig, LoadGen
from automerge_tpu.sync_session import BackendDriver, SessionConfig, SyncSession
from automerge_tpu.testing import faults
from automerge_tpu.testing.chaos import ChaosConfig, ChaosNetwork, ManualClock
from automerge_tpu.tpu.farm import TpuDocFarm


# ---------------------------------------------------------------------- #
# harness helpers


class Client:
    """One test client: a reference-backend replica + supervised session."""

    def __init__(self, actor, clock, seed, config=None):
        self.actor = actor
        self.driver = BackendDriver(Backend.init())
        self.session = SyncSession(
            self.driver, clock=clock, rng=random.Random(seed),
            config=config or SessionConfig(),
        )
        self.seq = 0
        self.max_op = 0

    def edit(self, key, value):
        self.seq += 1
        start = self.max_op + 1
        buf = faults.make_change(
            self.actor, self.seq, start,
            Backend.get_heads(self.driver.backend),
            [faults.set_op(key, value)],
        )
        self.max_op = start
        self.driver.backend, _ = Backend.apply_changes(
            self.driver.backend, [buf]
        )
        return buf

    def heads(self):
        return self.driver.heads()


def make_server(num_docs, clock, *, config=None, threshold=3):
    farm = TpuDocFarm(num_docs, capacity=256, quarantine_threshold=threshold)
    server = AmServer(
        farm, clock=clock, rng=random.Random(7),
        config=config or BatcherConfig(flush_interval=0.05, max_docs=64),
    )
    return farm, server


def drive(server, clients, clock, predicate, max_time=120.0):
    """Pumps frames client<->server directly (no network) until the
    predicate holds or simulated max_time elapses."""
    deadline = clock() + max_time
    while clock() < deadline:
        if predicate():
            return True
        moved = False
        for cid, client in clients.items():
            frame = client.session.poll()
            if frame is not None:
                moved = True
                try:
                    server.receive(cid, frame)
                except AutomergeError:
                    pass  # shed: the client's retransmission is the retry
        if server.tick() is not None:
            moved = True
        for cid, frame in server.pump():
            clients[cid].session.handle(frame)
            moved = True
        clock.advance(0.02 if moved else 0.06)
    return predicate()


# ---------------------------------------------------------------------- #
# session multiplexer


class TestMultiplexer:
    def test_clients_converge_through_the_batched_front_door(self):
        clock = ManualClock()
        farm, server = make_server(2, clock)
        clients = {}
        for i, doc in enumerate([0, 0, 1]):
            client = Client(f"{i:02x}" * 4, clock, seed=i + 1)
            client.edit(f"k{i}", i)
            clients[i] = client
            server.connect(i, doc)

        def converged():
            return (
                clients[0].heads() == farm.get_heads(0)
                and clients[1].heads() == farm.get_heads(0)
                and clients[2].heads() == farm.get_heads(1)
            )

        assert drive(server, clients, clock, converged)
        # co-editors of doc 0 merged each other's edits via the farm
        assert len(farm.get_heads(0)) == 1 or clients[0].heads() == clients[1].heads()

    def test_resume_continues_without_restart_exchange(self):
        clock = ManualClock()
        farm, server = make_server(1, clock)
        client = Client("aa" * 4, clock, seed=3)
        client.edit("x", 1)
        server.connect(0, 0)
        clients = {0: client}
        assert drive(server, clients, clock,
                     lambda: client.heads() == farm.get_heads(0))
        blob = server.save_session(0)
        # server restart: channel rebuilt from the persisted blob
        server.resume(0, 0, blob)
        client.edit("y", 2)
        assert drive(server, clients, clock,
                     lambda: client.heads() == farm.get_heads(0))
        assert client.session.stats["peer_restarts"] == 0  # same epoch

    def test_client_restart_detected_via_epoch_machinery(self):
        clock = ManualClock()
        farm, server = make_server(1, clock)
        client = Client("aa" * 4, clock, seed=4)
        client.edit("x", 1)
        server.connect(0, 0)
        clients = {0: client}
        assert drive(server, clients, clock,
                     lambda: client.heads() == farm.get_heads(0))
        # the client dies and reconnects with a fresh session (new epoch);
        # connect() keeps the server-side session, whose restart detection
        # re-handshakes cleanly
        fresh = Client("aa" * 4, clock, seed=5)
        fresh.driver = client.driver  # same replica, new session state
        fresh.session = SyncSession(fresh.driver, clock=clock,
                                    rng=random.Random(99))
        clients[0] = fresh
        server.connect(0, 0)
        fresh.seq, fresh.max_op = client.seq, client.max_op
        fresh.edit("y", 2)
        assert drive(server, clients, clock,
                     lambda: fresh.heads() == farm.get_heads(0))
        channel = server.channels[0]
        assert channel.session.stats["peer_restarts"] == 1

    def test_converged_channels_go_quiet(self):
        """The advert-suppression path: once a pair converges, repeated
        pumping produces no frames and no new sequence numbers (without
        it, ack->regenerate chatter spins forever)."""
        clock = ManualClock()
        farm, server = make_server(1, clock)
        client = Client("aa" * 4, clock, seed=6)
        client.edit("x", 1)
        server.connect(0, 0)
        clients = {0: client}
        assert drive(server, clients, clock,
                     lambda: client.heads() == farm.get_heads(0))
        # drain whatever acks are still owed
        drive(server, clients, clock, lambda: False, max_time=10.0)
        seq_before = (client.session.seq_out,
                      server.channels[0].session.seq_out)
        for _ in range(25):
            assert client.session.poll() is None
            server.wake(0)
            assert server.pump() == []
            clock.advance(0.1)
        assert (client.session.seq_out,
                server.channels[0].session.seq_out) == seq_before


# ---------------------------------------------------------------------- #
# dynamic batcher: flush boundaries (ISSUE 6 satellite)


def handshake_frame(clock, seed=11):
    """A fresh client's first payload frame (a sync handshake)."""
    client = Client("cc" * 4, clock, seed=seed)
    return client, client.session.poll()


class TestBatcherFlushBoundaries:
    def make(self, clock, num_docs=4, max_docs=3, pending=8):
        return make_server(
            num_docs, clock,
            config=BatcherConfig(flush_interval=0.05, max_docs=max_docs,
                                 max_pending_per_tenant=pending),
        )

    def test_timer_only_flush(self):
        """T elapses with fewer than N dirty docs -> the window flushes on
        the timer."""
        clock = ManualClock()
        farm, server = self.make(clock)
        for i, doc in enumerate((0, 1)):
            client, frame = handshake_frame(clock, seed=20 + i)
            server.connect(i, doc)
            server.receive(i, frame)
        assert not server.batcher.due()          # 2 dirty docs < N=3
        assert server.tick() is None
        clock.advance(0.05)
        assert server.batcher.due()
        report = server.tick()
        assert report is not None
        assert len(report.committed) == 2
        assert server.batcher.pending == 0

    def test_count_only_flush(self):
        """N distinct docs dirty before T -> due immediately."""
        clock = ManualClock()
        farm, server = self.make(clock)
        for i, doc in enumerate((0, 1, 2)):
            client, frame = handshake_frame(clock, seed=30 + i)
            server.connect(i, doc)
            server.receive(i, frame)
        assert server.batcher.due()              # no clock advance needed
        report = server.tick()
        assert len(report.committed) == 3

    def test_empty_ticks_dispatch_nothing(self):
        clock = ManualClock()
        farm, server = self.make(clock)
        assert server.tick() is None
        report = server.batcher.flush()
        assert not report.dispatched
        assert report.committed == [] and report.docs_dispatched == 0
        clock.advance(1.0)
        assert server.tick() is None             # still nothing queued

    def test_doc_quarantined_mid_window_is_excluded_from_its_flush(self):
        clock = ManualClock()
        farm, server = self.make(clock)
        client = Client("aa" * 4, clock, seed=41)
        client.edit("x", 1)
        server.connect(0, 0)
        frame = client.session.poll()             # first payload frame
        server.receive(0, frame)                  # admitted: doc is clean
        # the doc quarantines AFTER admission, before the flush
        farm.quarantine[0] = DecodeError("poisoned mid-window")
        clock.advance(0.05)
        report = server.tick()
        assert report.shed_quarantined == 1
        assert report.committed == []
        # not acked: the server's seq watermark did not move, so the
        # client's retransmission retries after release
        assert server.channels[0].session.last_seen == 0
        farm.release_quarantine(0)
        assert drive(server, {0: client}, clock,
                     lambda: client.heads() == farm.get_heads(0))

    def test_backpressure_releases_after_drain(self):
        clock = ManualClock()
        farm, server = self.make(clock, pending=2)
        frames = []
        for i in range(3):
            client, frame = handshake_frame(clock, seed=50 + i)
            server.connect(i, i % 4, tenant="tenantA")
            frames.append(frame)
        server.receive(0, frames[0])
        server.receive(1, frames[1])
        with pytest.raises(BackpressureError):
            server.receive(2, frames[2])
        assert server.batcher.pending_for("tenantA") == 2
        clock.advance(0.05)
        assert server.tick() is not None          # window drains
        server.receive(2, frames[2])              # budget released
        assert server.batcher.pending_for("tenantA") == 1

    def test_quarantined_doc_rejected_at_admission(self):
        clock = ManualClock()
        farm, server = self.make(clock)
        farm.quarantine[2] = DecodeError("already poisoned")
        client, frame = handshake_frame(clock, seed=60)
        server.connect(9, 2)
        with pytest.raises(AdmissionRejectedError):
            server.receive(9, frame)
        assert server.batcher.pending == 0


# ---------------------------------------------------------------------- #
# FarmApplyResult.applied / .quarantined (ISSUE 6 satellite)


class TestFarmApplyResultAccessors:
    def test_applied_and_quarantined_partition_the_outcomes(self):
        farm = TpuDocFarm(3, capacity=64)
        buf = faults.make_change("aa" * 4, 1, 1, [],
                                [faults.set_op("k", 1)])
        result = farm.apply_changes([[buf], [faults.garbage(48)], []])
        assert set(result.applied) == {0, 2}
        assert set(result.quarantined) == {1}
        assert all(o.status == "applied" for o in result.applied.values())
        assert result.quarantined[1].error_kind == "decode"
        # the two views partition the outcome list exactly
        assert len(result.applied) + len(result.quarantined) == len(result.outcomes)

    def test_applied_includes_fallback_served_docs(self):
        farm = TpuDocFarm(1, capacity=64)
        buf = faults.make_change("aa" * 4, 1, 1, [],
                                [faults.set_op("k", 1)])
        with faults.inject("farm.device_dispatch", faults.fail_always()):
            result = farm.apply_changes([[buf]])
        assert set(result.applied) == {0}
        assert result.applied[0].fallback is True


# ---------------------------------------------------------------------- #
# parity: batched serving path vs direct apply_changes (acceptance)


class TestServingParity:
    def test_patches_bit_for_bit_vs_direct_apply(self):
        """Every patch the batcher fans out must be byte-identical to the
        same deliveries applied through direct apply_changes calls (the
        style of tests/test_parity_incremental.py)."""
        clock = ManualClock()
        farm, server = make_server(4, clock)
        mirror = TpuDocFarm(4, capacity=256)
        clients = {}
        for i in range(4):
            client = Client(f"{i:02x}" * 4, clock, seed=70 + i)
            client.edit(f"a{i}", i)
            client.edit(f"b{i}", i * 10)
            clients[i] = client
            server.connect(i, i)

        flushed = []
        original_tick = server.tick

        def recording_tick():
            report = original_tick()
            if report is not None and report.changes_by_doc:
                flushed.append(report)
            return report

        server.tick = recording_tick

        def converged():
            return all(
                clients[i].heads() == farm.get_heads(i) for i in range(4)
            )

        assert drive(server, clients, clock, converged)
        assert flushed, "no change-carrying flush happened"

        # replay the exact per-flush groupings through direct calls
        for report in flushed:
            per_doc = [[] for _ in range(4)]
            for doc, changes in report.changes_by_doc.items():
                per_doc[doc] = list(changes)
            mirror_patches = mirror.apply_changes(per_doc)
            served = {
                channel.doc: patch
                for channel, patch in report.committed
                if channel.doc in report.changes_by_doc
            }
            for doc, patch in served.items():
                assert json.dumps(patch, sort_keys=True) == json.dumps(
                    mirror_patches[doc], sort_keys=True
                ), f"patch divergence on doc {doc}"
        for d in range(4):
            assert mirror.get_heads(d) == farm.get_heads(d)
            assert json.dumps(mirror.get_patch(d), sort_keys=True) == (
                json.dumps(farm.get_patch(d), sort_keys=True)
            )


# ---------------------------------------------------------------------- #
# chaos + poison composition (acceptance)


class TestChaosPoisonComposition:
    def test_serve_loop_survives_chaos_plus_poison(self):
        """30% per-link chaos composed with a 12.5%-poison workload: no
        crash, poisoned docs quarantine and shed at admission, every
        client on a clean doc still converges."""
        farm = TpuDocFarm(16, capacity=256)
        config = LoadConfig(
            clients=48, docs=16, edits_per_client=2, ops_per_edit=2,
            spread=0.5, chaos=0.3, poison=0.125, seed=13, max_time=600.0,
        )
        harness = LoadGen(farm, config)
        report = harness.run()
        assert report["poisoned_docs"] == 2
        # the poison quarantined its docs, nothing else
        assert set(farm.quarantine) <= harness.poison_docs
        assert report["quarantined_docs"] >= 1
        # quarantine-aware shedding engaged at the front door
        assert report["admission"]["rejected_quarantine"] > 0 or (
            report["frames_shed"] > 0
        )
        # all surviving (clean-doc) clients converged — no crash, no stall
        assert report["converged"], report
        assert report["unconverged_clients"] == 0

    def test_loadgen_deterministic_per_seed(self):
        def run(seed):
            farm = TpuDocFarm(4, capacity=256)
            config = LoadConfig(clients=8, docs=4, edits_per_client=1,
                                ops_per_edit=2, spread=0.2, chaos=0.2,
                                seed=seed, max_time=300.0)
            report = LoadGen(farm, config).run()
            return (report["simulated_s"], report["dispatches"],
                    report["changes_committed"], report["converged"])

        assert run(5) == run(5)


# ---------------------------------------------------------------------- #
# chaos transport helpers added for the serve harness


class TestChaosNetworkAggregates:
    def test_in_flight_and_next_arrival(self):
        clock = ManualClock()
        net = ChaosNetwork(random.Random(0), clock,
                           ChaosConfig(delay=1.0, min_delay=0.5,
                                       max_delay=0.5))
        assert net.in_flight == 0
        assert net.next_arrival() is None
        net.send("a", "b", b"x")
        net.send("b", "a", b"y")
        assert net.in_flight == 2
        arrival = net.next_arrival()
        assert arrival == pytest.approx(0.5)
        clock.advance(0.6)
        assert net.deliver("b") == [("a", b"x")]
        assert net.in_flight == 1


# ---------------------------------------------------------------------- #
# asyncio adapter (real transport smoke)


class TestAsyncioAdapter:
    def test_hello_and_sync_over_streams(self):
        import asyncio
        import socket

        # reserve an ephemeral loopback port for the adapter
        probe = socket.socket()
        try:
            probe.bind(("127.0.0.1", 0))
        except OSError as exc:
            probe.close()
            pytest.skip(f"loopback unavailable: {exc}")
        port = probe.getsockname()[1]
        probe.close()

        async def main():
            farm = TpuDocFarm(1, capacity=256)
            server = AmServer(farm, rng=random.Random(1),
                              config=BatcherConfig(flush_interval=0.02))
            task = asyncio.ensure_future(
                server.serve_forever("127.0.0.1", port)
            )
            await asyncio.sleep(0.1)
            loop = asyncio.get_event_loop()
            client = Client("aa" * 4, loop.time, seed=1)
            client.edit("x", 1)
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            hello = b"HELLO c1 0 default"
            writer.write(len(hello).to_bytes(4, "big") + hello)
            await writer.drain()

            async def read_frame():
                header = await reader.readexactly(4)
                return await reader.readexactly(int.from_bytes(header, "big"))

            deadline = loop.time() + 15.0
            while loop.time() < deadline:
                if farm.get_heads(0) and client.heads() == farm.get_heads(0):
                    break
                frame = client.session.poll()
                if frame is not None:
                    writer.write(len(frame).to_bytes(4, "big") + frame)
                    await writer.drain()
                try:
                    client.session.handle(
                        await asyncio.wait_for(read_frame(), 0.1)
                    )
                except asyncio.TimeoutError:
                    pass
            writer.close()
            task.cancel()
            return bool(farm.get_heads(0)) and (
                client.heads() == farm.get_heads(0)
            )

        try:
            converged = asyncio.new_event_loop().run_until_complete(
                asyncio.wait_for(main(), 30.0)
            )
        except (OSError, RuntimeError) as exc:
            pytest.skip(f"asyncio loopback unavailable: {exc}")
        assert converged
