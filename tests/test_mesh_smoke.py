"""Tier-1 smoke gate for the doc-sharded MeshFarm (ISSUE 10), mirroring
the bench-smoke pattern: one `bench.py --mesh --quick` run on 8 FORCED
virtual CPU host devices (the child env sets
--xla_force_host_platform_device_count, so the full multi-device fan-out
runs on any host) gated on machine-independent properties:

- every shard received dispatches and the per-shard metrics prove it;
- the run merged its whole workload for real — `farm.changes.applied`
  across the shards equals one change per doc per round (no dryrun
  path can satisfy this);
- zero cross-shard doc leaks: the controller's ownership audit
  (routing arrays vs per-shard owner tables, exactly-once slots) is
  clean after a forced mid-run migration;
- the migrated document's state survived the page transplant
  byte-for-bit (its patch matches an unmigrated doc fed the identical
  change stream);
- the cross-shard actor-table reconcile converges: a second pass
  immediately after the first syncs zero entries.

The `make_mesh`/`MeshFarm` argument-validation contracts ride along as
plain unit tests (the satellite fix for `sp` being silently ignored).
"""
import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RESULT = None


def _smoke():
    global _RESULT
    if _RESULT is None:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py"),
             "--mesh", "--quick"],
            cwd=_REPO, capture_output=True, text=True, timeout=300,
        )
        lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
        assert lines, (proc.stdout[-2000:], proc.stderr[-2000:])
        result = json.loads(lines[-1])
        assert proc.returncode == 0, (result, proc.stderr[-2000:])
        _RESULT = result
    return _RESULT


def test_quick_gate_passes():
    result = _smoke()
    assert result["ok"], result


def test_all_shards_dispatched_for_real():
    """8 forced devices -> 8 shards, every one dispatched, and the causal
    gates committed exactly the workload (one change per doc per round) —
    the cross-check that rules out any dryrun/skip path."""
    result = _smoke()
    assert result["n_devices"] == 8
    assert result["num_shards"] == 8
    assert result["all_shards_dispatched"], result["per_shard"]
    assert all(
        shard["docs_dispatched"] > 0 for shard in result["per_shard"].values()
    )
    assert result["changes_applied"] == result["changes_expected"]
    assert result["quarantined_docs"] == 0


def test_migration_preserves_state_and_ownership():
    """The forced mid-run migration moved exactly one doc, the ownership
    audit found no cross-shard leaks, and the migrated doc's patch is
    byte-identical to an unmigrated doc's (identical change streams)."""
    result = _smoke()
    assert result["docs_migrated"] == 1
    assert result["migrated"] is not None
    assert result["audit_ok"]
    assert result["migration_parity_ok"]


def test_reconcile_converges():
    result = _smoke()
    assert result["reconcile"]["second_sync"] == 0


def test_quick_gate_pins_obs_overhead_and_slo_verdicts():
    """The ISSUE 13 machine-independent gates: the quick run measures an
    off-baseline vs full-stack (metrics+flight+SLO) pass whose overhead
    ratio must stay under the cap, and the mesh SLO verdicts must all be
    in compliance — both already folded into ``result["ok"]``, pinned
    here so a silent gate removal fails tier-1."""
    result = _smoke()
    assert result["observability"] == "full"
    overhead = result["obs_overhead"]
    assert overhead["cap"] == 2.0
    assert overhead["baseline_elapsed_s"] > 0
    assert overhead["full_elapsed_s"] > 0
    assert 0 < overhead["ratio"] <= overhead["cap"]
    slo = result["slo"]
    assert slo["ok"] is True
    verdicts = {v["objective"]: v for v in slo["verdicts"]}
    assert set(verdicts) == {"mesh_delivery", "mesh_workers"}
    assert all(v["ok"] for v in verdicts.values())
    assert verdicts["mesh_delivery"]["kind"] == "availability"
    assert result["flight_events"] > 0


# --------------------------------------------------------------------- #
# make_mesh / MeshFarm argument validation (satellite: `sp` used to be
# silently ignored when it did not divide the device count)


def test_make_mesh_rejects_sp_that_does_not_divide_devices():
    from automerge_tpu.parallel import make_mesh

    import jax

    n = len(jax.devices())
    with pytest.raises(ValueError, match="does not divide"):
        make_mesh(sp=n + 1)


def test_make_mesh_rejects_nonpositive_sp():
    from automerge_tpu.parallel import make_mesh

    with pytest.raises(ValueError, match="sp must be >= 1"):
        make_mesh(sp=0)


def test_make_mesh_valid_split():
    from automerge_tpu.parallel import make_mesh

    mesh = make_mesh(sp=1)
    assert mesh.axis_names == ("dp", "sp")
    assert mesh.devices.shape[1] == 1


def test_meshfarm_rejects_more_shards_than_docs():
    from automerge_tpu.parallel import MeshFarm

    with pytest.raises(ValueError, match="num_shards"):
        MeshFarm(2, num_shards=3, capacity=32)


def test_meshfarm_rejects_batch_isolation():
    from automerge_tpu.parallel import MeshFarm

    mesh = MeshFarm(4, num_shards=2, capacity=32)
    with pytest.raises(ValueError, match="isolation"):
        mesh.apply_changes([[] for _ in range(4)], isolation="batch")
