"""Tier-1 smoke for the process-backed mesh (parallel/workers.py).

Three machine-independent contracts:

1. **Parity**: a 2-worker ``mesh_backend="process"`` farm converges to
   byte-identical patches/outcomes/quarantine vs the inline backend (the
   parity oracle) over a multi-round workload, including reconcile and a
   clean ownership audit.
2. **No leaks**: ``close()`` leaves zero live child processes.
3. **Spawn safety**: importing ``automerge_tpu.parallel.workers`` must
   NOT import jax or the farm — spawned children re-import the module
   tree before applying env overrides, so a heavy import at module scope
   would both slow every spawn and initialise jax with the wrong env.

The heavy 8-shard soak is marked slow (``make mesh-workers`` runs the
process bench at full fidelity).
"""
import json
import multiprocessing
import subprocess
import sys

import pytest

from automerge_tpu.opset import OpSet
from automerge_tpu.parallel.meshfarm import MeshFarm
from test_farm import Workload

NUM_DOCS = 8
ROUNDS = 5


def drive(backend, num_shards=2, seed=7, rounds=ROUNDS):
    """Runs a deterministic workload and returns every observable byte:
    per-round patches + outcome statuses, final patches, quarantine."""
    mesh = MeshFarm(NUM_DOCS, num_shards=num_shards, capacity=64,
                    mesh_backend=backend)
    gen = OpSet()
    w = Workload(seed)
    outs = []
    try:
        for _ in range(rounds):
            buffers = w.next_round(gen)
            if not buffers:
                continue
            per_doc = [list(buffers) for _ in range(NUM_DOCS)]
            res = mesh.apply_changes(per_doc, isolation="doc")
            outs.append([
                json.dumps(res[d], sort_keys=True) for d in range(NUM_DOCS)
            ])
            outs.append([o.status for o in res.outcomes])
        outs.append([
            json.dumps(mesh.get_patch(d), sort_keys=True)
            for d in range(NUM_DOCS)
        ])
        outs.append(sorted(mesh.quarantine))
        outs.append(mesh.reconcile_actors())
        mesh.audit()
    finally:
        mesh.close()
    return outs


def test_process_backend_parity_and_clean_close():
    inline = drive("inline")
    process = drive("process")
    assert inline == process
    assert multiprocessing.active_children() == []


@pytest.mark.slow
def test_eight_shard_soak():
    inline = drive("inline", num_shards=8, seed=11, rounds=12)
    process = drive("process", num_shards=8, seed=11, rounds=12)
    assert inline == process
    assert multiprocessing.active_children() == []


def test_workers_module_imports_without_jax():
    """Pinned spawn-safety contract (see workers.py module docstring)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; import automerge_tpu.parallel.workers; "
         "assert 'jax' not in sys.modules, 'workers.py imported jax'; "
         "assert 'automerge_tpu.tpu.farm' not in sys.modules, "
         "    'workers.py imported the farm'"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
