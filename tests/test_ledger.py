"""Perf-ledger suite (automerge_tpu/obs/ledger.py + the obs CLI modes).

The ledger is bench.py's regression memory: append-only normalized JSONL
records, a trajectory renderer and a record differ. Pinned here:
- normalize(): numpy scalars/arrays -> plain ints/floats/lists (the
  np.int64-under-default=str stringification bug), nested containers,
  unknown leaves stringified;
- append/load round trip, config hashing (equal configs -> equal hashes,
  the differ's comparability test), malformed-line tolerance;
- diff_records: ops/s ratio, per-program compile/dispatch deltas
  (zero-delta programs dropped), per-shard pipe deltas;
- the ``python -m automerge_tpu.obs --ledger [--diff]`` CLI contract.
"""
import json
import subprocess
import sys

import numpy as np
import pytest

from automerge_tpu.obs.ledger import (
    append_record,
    config_hash,
    diff_records,
    load_ledger,
    normalize,
    render_diff,
    render_trajectory,
)


def test_normalize_strips_numpy_scalars_and_arrays():
    record = {
        "a": np.int64(7),
        "b": np.float32(0.5),
        "c": np.arange(3, dtype=np.int64),
        "d": {"nested": (np.int32(1), 2)},
        "e": [True, None, "s"],
    }
    out = normalize(record)
    assert out == {"a": 7, "b": 0.5, "c": [0, 1, 2],
                   "d": {"nested": [1, 2]}, "e": [True, None, "s"]}
    # the bug this guards: json.dumps(..., default=str) silently writes
    # "7" instead of 7 for np.int64 — normalized records need no default
    assert '"7"' not in json.dumps(out)
    assert type(out["a"]) is int


def test_normalize_stringifies_unknown_leaves():
    class Opaque:
        def __repr__(self):
            return "<opaque>"

    assert normalize({"x": Opaque()}) == {"x": "<opaque>"}


def test_config_hash_is_order_independent_and_type_normalized():
    assert config_hash({"a": 1, "b": 2}) == config_hash({"b": 2, "a": 1})
    assert config_hash({"a": np.int64(1)}) == config_hash({"a": 1})
    assert config_hash({"a": 1}) != config_hash({"a": 2})


def test_append_and_load_round_trip(tmp_path):
    path = tmp_path / "ledger.jsonl"
    rec = append_record(path, {
        "kind": "quick",
        "config": {"docs": np.int64(128)},
        "ops_per_sec": np.float64(1234.5),
    })
    assert rec["config_hash"] == config_hash({"docs": 128})
    append_record(path, {"kind": "quick", "config": {"docs": 128},
                         "ops_per_sec": 1300})
    records = load_ledger(path)
    assert len(records) == 2
    assert records[0]["ops_per_sec"] == 1234.5
    assert records[0]["config_hash"] == records[1]["config_hash"]


def test_load_skips_malformed_lines(tmp_path):
    path = tmp_path / "ledger.jsonl"
    path.write_text('{"kind": "quick"}\nnot json\n\n{"kind": "mesh"}\n')
    assert [r["kind"] for r in load_ledger(path)] == ["quick", "mesh"]
    assert load_ledger(tmp_path / "missing.jsonl") == []


@pytest.fixture
def two_records():
    a = {
        "kind": "quick", "config_hash": "abc", "ops_per_sec": 1000,
        "programs": {
            "paging.apply_ops": {"compiles": 1, "dispatches": 6},
            "paging.visible_ranked": {"compiles": 0, "dispatches": 6},
        },
        "pipe": {"0": {"bytes_out": 100, "bytes_in": 3000,
                       "frames_out": 1, "frames_in": 2}},
    }
    b = {
        "kind": "quick", "config_hash": "abc", "ops_per_sec": 1100,
        "programs": {
            "paging.apply_ops": {"compiles": 4, "dispatches": 6},
            "paging.visible_ranked": {"compiles": 0, "dispatches": 6},
        },
        "pipe": {"0": {"bytes_out": 100, "bytes_in": 3600,
                       "frames_out": 1, "frames_in": 2}},
    }
    return a, b


def test_diff_records_reports_deltas_and_drops_noise(two_records):
    a, b = two_records
    diff = diff_records(a, b)
    assert diff["comparable"] is True
    assert diff["ops_per_sec"]["delta"] == 100
    assert diff["ops_per_sec"]["ratio"] == pytest.approx(1.1)
    # only the program that actually moved appears
    assert list(diff["programs"]) == ["paging.apply_ops"]
    assert diff["programs"]["paging.apply_ops"]["compiles"] == 3
    assert diff["pipe"]["0"]["bytes_in"] == 600
    assert diff["pipe"]["0"]["bytes_out"] == 0


def test_diff_flags_incomparable_configs(two_records):
    a, b = two_records
    b = dict(b, config_hash="zzz")
    diff = diff_records(a, b)
    assert diff["comparable"] is False
    assert "[configs differ]" in render_diff(a, b)


def test_render_trajectory_totals(two_records):
    a, b = two_records
    text = render_trajectory([a, b])
    lines = text.splitlines()
    assert len(lines) == 4  # header, rule, two rows
    assert "1,000" in lines[2] and "1,100" in lines[3]
    assert "3100" in lines[2]  # pipe bytes total of record 0
    assert render_trajectory([]) == "ledger is empty"


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "automerge_tpu.obs", *argv],
        capture_output=True, text=True,
    )


def test_cli_trajectory_diff_and_bounds(tmp_path, two_records):
    path = tmp_path / "ledger.jsonl"
    a, b = two_records
    append_record(path, a)
    append_record(path, b)

    out = _run_cli("--ledger", str(path))
    assert out.returncode == 0
    assert "quick" in out.stdout and "1,100" in out.stdout

    out = _run_cli("--ledger", str(path), "--diff", "-2", "-1")
    assert out.returncode == 0
    assert "paging.apply_ops: compiles +3" in out.stdout

    out = _run_cli("--ledger", str(path), "--diff", "0", "9")
    assert out.returncode == 1
    assert "out of range" in out.stderr

    out = _run_cli("--ledger", str(path), "--json")
    assert out.returncode == 0
    assert len(json.loads(out.stdout)) == 2
