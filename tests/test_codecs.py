"""L0 codec tests with byte-exact expectations from the reference suite
(/root/reference/test/encoding_test.js)."""
import pytest

from automerge_tpu.codecs import (
    BooleanDecoder,
    BooleanEncoder,
    Decoder,
    DeltaDecoder,
    DeltaEncoder,
    Encoder,
    RLEDecoder,
    RLEEncoder,
)


def enc_uint(value):
    e = Encoder()
    e.append_uint53(value)
    return list(e.buffer)


def enc_int(value):
    e = Encoder()
    e.append_int53(value)
    return list(e.buffer)


class TestLEB128:
    def test_uint_encodings(self):
        cases = {
            0: [0], 1: [1], 0x42: [0x42], 0x7F: [0x7F],
            0x80: [0x80, 0x01], 0xFF: [0xFF, 0x01],
            0x1234: [0xB4, 0x24], 0x3FFF: [0xFF, 0x7F],
            0x4000: [0x80, 0x80, 0x01], 0x5678: [0xF8, 0xAC, 0x01],
            0xFFFFF: [0xFF, 0xFF, 0x3F], 0x1FFFFF: [0xFF, 0xFF, 0x7F],
            0x200000: [0x80, 0x80, 0x80, 0x01],
            0xFFFFFFF: [0xFF, 0xFF, 0xFF, 0x7F],
            0x10000000: [0x80, 0x80, 0x80, 0x80, 0x01],
            0x7FFFFFFF: [0xFF, 0xFF, 0xFF, 0xFF, 0x07],
            0x87654321: [0xA1, 0x86, 0x95, 0xBB, 0x08],
            0xFFFFFFFF: [0xFF, 0xFF, 0xFF, 0xFF, 0x0F],
        }
        for value, expected in cases.items():
            assert enc_uint(value) == expected, hex(value)
            d = Decoder(bytes(expected))
            assert d.read_uint53() == value
            assert d.done

    def test_int_encodings(self):
        cases = {
            0: [0], 1: [1], -1: [0x7F],
            0x3F: [0x3F], 0x40: [0xC0, 0x00],
            -0x3F: [0x41], -0x40: [0x40], -0x41: [0xBF, 0x7F],
            0x1FFF: [0xFF, 0x3F], 0x2000: [0x80, 0xC0, 0x00],
            -0x2000: [0x80, 0x40], -0x2001: [0xFF, 0xBF, 0x7F],
            0xFFFFF: [0xFF, 0xFF, 0x3F], 0x100000: [0x80, 0x80, 0xC0, 0x00],
            -0x100000: [0x80, 0x80, 0x40], -0x100001: [0xFF, 0xFF, 0xBF, 0x7F],
        }
        for value, expected in cases.items():
            assert enc_int(value) == expected, hex(value)
            d = Decoder(bytes(expected))
            assert d.read_int53() == value
            assert d.done

    def test_uint53_bounds(self):
        enc_uint(2**53 - 1)  # max safe
        with pytest.raises(ValueError):
            enc_uint(2**53)
        with pytest.raises(ValueError):
            enc_uint(-1)

    def test_int53_bounds(self):
        enc_int(2**53 - 1)
        enc_int(-(2**53 - 1))
        with pytest.raises(ValueError):
            enc_int(2**53)
        with pytest.raises(ValueError):
            enc_int(-(2**53))

    def test_uint32_range_check(self):
        e = Encoder()
        e.append_uint32(0xFFFFFFFF)
        with pytest.raises(ValueError):
            Encoder().append_uint32(0x100000000)

    def test_incomplete_number(self):
        with pytest.raises(ValueError, match="incomplete number"):
            Decoder(bytes([0x80])).read_uint53()

    def test_prefixed_strings(self):
        e = Encoder()
        e.append_prefixed_string("hello")
        assert list(e.buffer) == [5, 0x68, 0x65, 0x6C, 0x6C, 0x6F]
        d = Decoder(e.buffer)
        assert d.read_prefixed_string() == "hello"

    def test_utf8_multibyte(self):
        e = Encoder()
        e.append_prefixed_string("çäö")
        d = Decoder(e.buffer)
        assert d.read_prefixed_string() == "çäö"


class TestRLE:
    def rle(self, type_, values):
        e = RLEEncoder(type_)
        for v in values:
            e.append_value(v)
        return e.buffer

    def test_repetition_run(self):
        # 5x the same value: repetition record (count, value)
        assert list(self.rle("uint", [7, 7, 7, 7, 7])) == [5, 7]

    def test_literal_run(self):
        # distinct values: literal record (-count, values...)
        assert list(self.rle("uint", [1, 2, 3])) == [0x7D, 1, 2, 3]

    def test_null_runs(self):
        assert list(self.rle("uint", [None, None, None, 4])) == [0, 3, 0x7F, 4]

    def test_only_nulls_encodes_empty(self):
        assert self.rle("uint", [None, None]) == b""

    def test_trailing_nulls_after_values_kept(self):
        assert list(self.rle("uint", [1, None, None])) == [0x7F, 1, 0, 2]

    def test_mixed_runs(self):
        values = [1, 1, 1, 2, 3, 3, 3]
        assert list(self.rle("uint", values)) == [3, 1, 0x7F, 2, 3, 3]

    def test_round_trip(self):
        values = [1, 1, 1, None, None, 2, 3, 4, 4, None, 5]
        d = RLEDecoder("uint", self.rle("uint", values))
        assert [d.read_value() for _ in values] == values
        assert d.done

    def test_string_round_trip(self):
        values = ["a", "a", None, "b", "c", "c"]
        d = RLEDecoder("utf8", self.rle("utf8", values))
        assert [d.read_value() for _ in values] == values

    def test_skip_values(self):
        values = [1, 1, 1, None, None, 2, 3, 4]
        d = RLEDecoder("uint", self.rle("uint", values))
        d.skip_values(4)
        assert [d.read_value() for _ in range(4)] == values[4:]

    def test_append_with_repetitions(self):
        e = RLEEncoder("uint")
        e.append_value(3, 4)
        e.append_value(3, 2)
        assert list(e.buffer) == [6, 3]


class TestDelta:
    def delta(self, values):
        e = DeltaEncoder()
        for v in values:
            e.append_value(v)
        return e.buffer

    def test_ascending_run_compresses(self):
        # 1..5: every delta (including the first, from absolute 0) is 1,
        # so the whole sequence is one repetition record
        assert list(self.delta([1, 2, 3, 4, 5])) == [5, 1]

    def test_round_trip(self):
        values = [10, 15, 13, None, 13, 20]
        d = DeltaDecoder(self.delta(values))
        assert [d.read_value() for _ in values] == values

    def test_skip_values(self):
        values = [3, 4, 5, 6, 10, 2]
        d = DeltaDecoder(self.delta(values))
        d.skip_values(3)
        assert [d.read_value() for _ in range(3)] == values[3:]


class TestBoolean:
    def boolean(self, values):
        e = BooleanEncoder()
        for v in values:
            e.append_value(v)
        return e.buffer

    def test_alternating_runs(self):
        # starts with false-count
        assert list(self.boolean([False, False, True, True, True])) == [2, 3]

    def test_starting_with_true(self):
        assert list(self.boolean([True, True])) == [0, 2]

    def test_round_trip(self):
        values = [True, False, False, True, True, True, False]
        d = BooleanDecoder(self.boolean(values))
        assert [d.read_value() for _ in values] == values
        assert d.done

    def test_skip(self):
        values = [False, False, True, True, False]
        d = BooleanDecoder(self.boolean(values))
        d.skip_values(3)
        assert [d.read_value() for _ in range(2)] == values[3:]

    def test_rejects_non_boolean(self):
        with pytest.raises(ValueError):
            BooleanEncoder().append_value(1)


class TestColumnarRoundTrips:
    def test_change_encode_decode(self):
        from automerge_tpu.columnar import decode_change, encode_change

        change = {
            "actor": "0123456789abcdef", "seq": 1, "startOp": 1, "time": 12345,
            "message": "hello", "deps": [], "ops": [
                {"action": "set", "obj": "_root", "key": "s", "value": "str", "pred": []},
                {"action": "set", "obj": "_root", "key": "i", "datatype": "int", "value": -7, "pred": []},
                {"action": "set", "obj": "_root", "key": "u", "datatype": "uint", "value": 7, "pred": []},
                {"action": "set", "obj": "_root", "key": "f", "datatype": "float64", "value": 1.5, "pred": []},
                {"action": "set", "obj": "_root", "key": "b", "value": True, "pred": []},
                {"action": "set", "obj": "_root", "key": "n", "value": None, "pred": []},
                {"action": "set", "obj": "_root", "key": "t", "datatype": "timestamp", "value": 1700000000000, "pred": []},
                {"action": "set", "obj": "_root", "key": "c", "datatype": "counter", "value": 5, "pred": []},
            ],
        }
        decoded = decode_change(encode_change(change))
        for field in ("actor", "seq", "startOp", "time", "message"):
            assert decoded[field] == change[field]
        by_key = {op["key"]: op for op in decoded["ops"]}
        assert by_key["s"]["value"] == "str"
        assert by_key["i"]["value"] == -7 and by_key["i"]["datatype"] == "int"
        assert by_key["u"]["value"] == 7 and by_key["u"]["datatype"] == "uint"
        assert by_key["f"]["value"] == 1.5 and by_key["f"]["datatype"] == "float64"
        assert by_key["b"]["value"] is True
        assert by_key["n"]["value"] is None
        assert by_key["t"]["datatype"] == "timestamp"
        assert by_key["c"]["datatype"] == "counter"

    def test_large_change_deflates(self):
        from automerge_tpu.columnar import CHUNK_TYPE_DEFLATE, decode_change, encode_change

        change = {
            "actor": "aabbccdd", "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
                {"action": "set", "obj": "_root", "key": f"key-{i:04d}", "value": f"val{i}", "pred": []}
                for i in range(50)
            ],
        }
        encoded = encode_change(change)
        assert encoded[8] == CHUNK_TYPE_DEFLATE
        decoded = decode_change(encoded)
        assert len(decoded["ops"]) == 50

    def test_corrupted_checksum_rejected(self):
        from automerge_tpu.columnar import decode_change, encode_change

        change = {"actor": "aabbccdd", "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "x", "value": 1, "pred": []},
        ]}
        data = bytearray(encode_change(change))
        data[4] ^= 0xFF  # corrupt checksum
        with pytest.raises(ValueError, match="checksum does not match"):
            decode_change(bytes(data))

    def test_split_containers(self):
        from automerge_tpu.columnar import encode_change, split_containers

        c1 = encode_change({"actor": "aabbccdd", "seq": 1, "startOp": 1, "time": 0,
                            "deps": [], "ops": []})
        c2 = encode_change({"actor": "bbccddee", "seq": 1, "startOp": 1, "time": 0,
                            "deps": [], "ops": []})
        chunks = split_containers(c1 + c2)
        assert chunks == [c1, c2]
