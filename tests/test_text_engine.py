"""Differential tests: the batched Text engine vs the sequential engine on
concurrent insert/update/delete workloads (benchmark config 2 shape)."""
import random

import automerge_tpu.tpu.text_engine as te
from automerge_tpu.columnar import encode_change
from automerge_tpu.opset import OpSet


def opset_visible_text(opset, list_obj):
    patch = opset.get_patch()
    prop = patch["diffs"]["props"].get("text", {})
    for obj_patch in prop.values():
        if obj_patch.get("objectId") == list_obj:
            values = []
            for edit in obj_patch["edits"]:
                if edit["action"] == "insert":
                    values.insert(edit["index"], edit["value"].get("value"))
                elif edit["action"] == "multi-insert":
                    values[edit["index"]:edit["index"]] = edit["values"]
                elif edit["action"] == "update":
                    values[edit["index"]] = edit["value"].get("value")
                elif edit["action"] == "remove":
                    del values[edit["index"]:edit["index"] + edit["count"]]
            return values
    return []


class TestBatchedTextEngine:
    def test_sequential_typing(self):
        eng = te.BatchedTextEngine(2, capacity=32)
        a = "aaaaaaaa"
        eng.apply_batch([
            [({"action": "set", "insert": True, "elemId": "_head", "value": "h"}, 1, a),
             ({"action": "set", "insert": True, "elemId": f"1@{a}", "value": "i"}, 2, a)],
            [({"action": "set", "insert": True, "elemId": "_head", "value": "x"}, 1, a)],
        ])
        assert eng.visible_texts() == [["h", "i"], ["x"]]

    def test_concurrent_inserts_rga_order(self):
        # two actors insert concurrently after the same element:
        # higher opId goes first (RGA convergence)
        eng = te.BatchedTextEngine(1, capacity=32)
        a, b = "aaaaaaaa", "bbbbbbbb"
        eng.apply_batch([[({"action": "set", "insert": True, "elemId": "_head", "value": "a"}, 1, a)]])
        eng.apply_batch([[
            ({"action": "set", "insert": True, "elemId": f"1@{a}", "value": "x"}, 2, a),
            ({"action": "set", "insert": True, "elemId": f"1@{a}", "value": "y"}, 2, b),
        ]])
        # 2@b > 2@a, so y precedes x
        assert eng.visible_texts() == [["a", "y", "x"]]

    def test_delete_and_update(self):
        eng = te.BatchedTextEngine(1, capacity=32)
        a = "aaaaaaaa"
        eng.apply_batch([[
            ({"action": "set", "insert": True, "elemId": "_head", "value": "a"}, 1, a),
            ({"action": "set", "insert": True, "elemId": f"1@{a}", "value": "b"}, 2, a),
            ({"action": "set", "insert": True, "elemId": f"2@{a}", "value": "c"}, 3, a),
        ]])
        eng.apply_batch([[
            ({"action": "del", "elemId": f"2@{a}", "pred": [f"2@{a}"]}, 4, a),
            ({"action": "set", "insert": False, "elemId": f"3@{a}", "value": "C", "pred": [f"3@{a}"]}, 5, a),
        ]])
        assert eng.visible_texts() == [["a", "C"]]

    def test_concurrent_delete_vs_update(self):
        # concurrent delete and update of the same element: update survives
        eng = te.BatchedTextEngine(1, capacity=32)
        a, b = "aaaaaaaa", "bbbbbbbb"
        eng.apply_batch([[({"action": "set", "insert": True, "elemId": "_head", "value": "v"}, 1, a)]])
        eng.apply_batch([[
            ({"action": "del", "elemId": f"1@{a}", "pred": [f"1@{a}"]}, 2, a),
            ({"action": "set", "insert": False, "elemId": f"1@{a}", "value": "V", "pred": [f"1@{a}"]}, 2, b),
        ]])
        assert eng.visible_texts() == [["V"]]

    def test_differential_vs_opset(self):
        rng = random.Random(11)
        actors = ["aaaaaaaa", "bbbbbbbb"]
        num_docs = 3
        opsets = [OpSet() for _ in range(num_docs)]
        eng = te.BatchedTextEngine(num_docs, capacity=128)
        list_objs = []
        views = []

        # bootstrap: each doc gets a text object with one seed element
        boot_rows = []
        for d in range(num_docs):
            a = actors[0]
            change = {"actor": a, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
                {"action": "makeText", "obj": "_root", "key": "text", "pred": []},
                {"action": "set", "obj": f"1@{a}", "elemId": "_head", "insert": True,
                 "value": "s", "pred": []},
            ]}
            opsets[d].apply_changes([encode_change(change)])
            list_objs.append(f"1@{a}")
            boot_rows.append([
                ({"action": "set", "insert": True, "elemId": "_head", "value": "s"}, 2, a)
            ])
            views.append({
                "elems": [(f"2@{a}", f"2@{a}")], "deleted": set(),
                "seqs": {actors[0]: 1, actors[1]: 0}, "max_op": 2,
            })
        eng.apply_batch(boot_rows)

        for _round in range(8):
            per_doc = []
            for d in range(num_docs):
                view = views[d]
                actor = rng.choice(actors)
                view["seqs"][actor] += 1
                start = view["max_op"] + 1
                ctr = start
                ops = []
                rows = []
                for _ in range(rng.randrange(1, 4)):
                    kind = rng.random()
                    live = [(e, v) for e, v in view["elems"] if e not in view["deleted"]]
                    if kind < 0.55 or not live:
                        ref = rng.choice([e for e, _ in view["elems"]] + ["_head"])
                        op = {"action": "set", "obj": list_objs[d], "elemId": ref,
                              "insert": True, "value": f"c{ctr}", "pred": []}
                        view["elems"].append((f"{ctr}@{actor}", f"{ctr}@{actor}"))
                    elif kind < 0.8:
                        elem, val_id = rng.choice(live)
                        op = {"action": "set", "obj": list_objs[d], "elemId": elem,
                              "insert": False, "value": f"u{ctr}", "pred": [val_id]}
                        view["elems"] = [
                            (e, f"{ctr}@{actor}" if e == elem else v) for e, v in view["elems"]
                        ]
                    else:
                        elem, val_id = rng.choice(live)
                        op = {"action": "del", "obj": list_objs[d], "elemId": elem,
                              "insert": False, "pred": [val_id]}
                        view["deleted"].add(elem)
                    ops.append(op)
                    rows.append((dict(op), ctr, actor))
                    ctr += 1
                view["max_op"] = ctr - 1
                change = {"actor": actor, "seq": view["seqs"][actor], "startOp": start,
                          "time": 0, "deps": opsets[d].heads, "ops": ops}
                opsets[d].apply_changes([encode_change(change)])
                per_doc.append(rows)
            eng.apply_batch(per_doc)

        texts = eng.visible_texts()
        for d in range(num_docs):
            expected = opset_visible_text(opsets[d], list_objs[d])
            assert texts[d] == expected, f"doc {d}: {texts[d]} != {expected}"
