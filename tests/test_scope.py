"""amscope request-flow tracing suite (automerge_tpu/obs/scope.py +
serve-stack integration).

Covers the ISSUE 8 tentpole contract:
- trace contexts attach at AmServer.receive, ride the batching window and
  commit/ack fan-out, and price every lifecycle segment on the injected
  clock;
- ONE DispatchSpan links the N request traces a batched dispatch served
  and carries the shared farm phase breakdown;
- histogram exemplars connect a p99 bucket to a concrete recent trace;
- per-tenant accounting accumulates requests/changes/bytes/sheds/
  backpressure with latency percentiles;
- disabled cost: attach/propagate/record are one attribute (or identity)
  test when the stack is off — asserted with poisoned internals, the
  same convention as the amtrace disabled-cost tests;
- the live telemetry pipeline: exposition text, snapshot records and the
  per-request phase-share math.
"""
import json

import pytest

from automerge_tpu.obs.export import (
    render_exposition,
    request_breakdown,
    snapshot_record,
)
from automerge_tpu.obs.flight import get_flight
from automerge_tpu.obs.metrics import get_metrics
from automerge_tpu.obs.scope import (
    Amscope,
    PHASE_HISTOGRAMS,
    current_exemplar,
    dispatch_context,
    get_amscope,
)
from automerge_tpu.serve.loadgen import LoadConfig, LoadGen
from automerge_tpu.tpu.farm import TpuDocFarm


# ---------------------------------------------------------------------- #
# unit: scope lifecycle

def test_attach_marks_and_breakdown():
    tracer = Amscope()
    tracer.enabled = True
    scope = tracer.attach("t0", doc=3, client_id="c1", t=10.0, nbytes=42)
    assert scope is not None and scope.tenant == "t0" and scope.doc == 3
    scope.mark("flush", 10.05)
    scope.mark("committed", 10.07)
    scope.mark("sent", 10.08)
    scope.changes = 2
    tracer.finish(scope)
    bd = scope.breakdown()
    assert bd["queue_wait_ms"] == pytest.approx(50.0)
    assert bd["dispatch_ms"] == pytest.approx(20.0)
    assert bd["ack_ms"] == pytest.approx(10.0)
    assert bd["e2e_ms"] == pytest.approx(80.0)
    assert scope in tracer.recent
    stats = tracer.tenant_stats()["t0"]
    assert stats["requests"] == 1 and stats["bytes_in"] == 42
    assert stats["changes"] == 2
    assert stats["latency_ms"]["samples"] == 1


def test_drop_counts_per_tenant_without_latency_samples():
    tracer = Amscope()
    tracer.enabled = True
    for reason in ("shed", "backpressure", "rejected", "shed"):
        scope = tracer.attach("t1", 0, "c", t=0.0)
        tracer.drop(scope, reason)
    stats = tracer.tenant_stats()["t1"]
    assert stats["shed"] == 2
    assert stats["backpressure"] == 1
    assert stats["rejected"] == 1
    assert stats["latency_ms"]["samples"] == 0
    table = tracer.tenant_table()
    assert "t1" in table and "backpr" in table


def test_dispatch_span_links_traces_and_observes_phases():
    reg = get_metrics()
    reg.reset()
    tracer = Amscope()
    tracer.enabled = True
    scopes = [tracer.attach("t0", d, f"c{d}", t=0.0) for d in range(3)]
    span = tracer.begin_dispatch([s.trace_id for s in scopes], 1.0)
    assert len(span.trace_ids) == 3
    with dispatch_context(span):
        assert current_exemplar() == span.dispatch_id
    assert current_exemplar() is None
    reg.enable()
    tracer.end_dispatch(
        span, 1.5,
        phases={"device_dispatch": 0.004, "visibility": 0.002,
                "patch_assembly": 0.001, "walk": 0.0005},
        docs=3, changes=6,
    )
    reg.disable()
    assert span in tracer.dispatches
    # mapped phases observed with the span id as exemplar; unmapped
    # phases (walk) are carried on the span but not histogrammed
    hist = PHASE_HISTOGRAMS["device_dispatch"]
    assert hist.count == 1
    assert hist.exemplar_for(0.99) == span.dispatch_id
    assert "walk" in span.phases
    reg.reset()


def test_find_recent_trace_by_id():
    tracer = Amscope()
    tracer.enabled = True
    scope = tracer.attach("t0", 0, "c", t=0.0)
    tracer.finish(scope)
    assert tracer.find(scope.trace_id) is scope
    assert tracer.find("t-missing") is None


# ---------------------------------------------------------------------- #
# disabled cost (satellite: attach/propagate/record <= one attribute test)

class _Boom:
    def append(self, *_):
        raise AssertionError("disabled path touched internal state")

    def __bool__(self):
        raise AssertionError("disabled path inspected internal state")


def test_disabled_attach_is_attribute_test_only():
    tracer = Amscope()
    # poison everything attach would touch if it did any work
    tracer.recent = _Boom()
    tracer.tenants = None
    assert tracer.attach("t0", 0, "c", t=0.0, nbytes=9) is None


def test_disabled_flight_record_is_attribute_test_only():
    from automerge_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder()
    rec._ring = _Boom()
    rec.record("batcher.flush", t=0.0, reason="timer")  # no-op, no raise
    assert rec.trigger("anything") is None


def test_disabled_serve_path_creates_no_scopes():
    """Propagation cost when off: a full serving run with the stack
    disabled leaves no scopes, no dispatch spans, no flight events, and
    no pending scopes on any channel."""
    scope, flight = get_amscope(), get_flight()
    scope.reset()
    flight.clear()
    farm = TpuDocFarm(4, capacity=64)
    gen = LoadGen(farm, LoadConfig(
        clients=8, docs=4, edits_per_client=1, ops_per_edit=2,
        spread=0.2, observability="off",
    ))
    report = gen.run()
    assert report["converged"]
    assert len(scope.recent) == 0 and len(scope.dispatches) == 0
    assert len(flight) == 0
    assert all(
        not ch.pending_scopes for ch in gen.server.channels.values()
    )
    assert "breakdown" not in report


# ---------------------------------------------------------------------- #
# integration: the serving stack under full tracing

@pytest.fixture(scope="module")
def full_run():
    farm = TpuDocFarm(6, capacity=128)
    gen = LoadGen(farm, LoadConfig(
        clients=24, docs=6, edits_per_client=2, ops_per_edit=3,
        spread=0.5, tenants=3, observability="full", seed=3,
    ))
    report = gen.run()
    # snapshot the process-wide tracer state before other tests reset it
    tracer = get_amscope()
    return {
        "report": report,
        "dispatches": list(tracer.dispatches),
        "recent": list(tracer.recent),
        "tenant_table": tracer.tenant_table(),
        "metrics": get_metrics().as_dict(),
    }


def test_full_run_converges_with_breakdown(full_run):
    report = full_run["report"]
    assert report["converged"]
    bd = report["breakdown"]
    assert bd["requests"] > 0
    for phase in ("queue_wait", "dispatch", "readback", "assembly", "ack"):
        assert phase in bd["shares"], phase
    assert sum(bd["shares"].values()) == pytest.approx(1.0, abs=0.01)


def test_one_dispatch_span_links_many_request_traces(full_run):
    """The tentpole claim: a batched dispatch is ONE span owning the N
    member traces, and members share its phase breakdown."""
    spans = full_run["dispatches"]
    assert spans, "no dispatch spans recorded"
    linked = max(spans, key=lambda s: len(s.trace_ids))
    assert len(linked.trace_ids) >= 2
    assert "device_dispatch" in linked.phases
    members = [
        s for s in full_run["recent"] if s.dispatch_id == linked.dispatch_id
    ]
    assert len(members) >= 2
    assert all(m.phases == linked.phases for m in members)


def test_p99_exemplar_names_a_recorded_trace(full_run):
    bd = full_run["report"]["breakdown"]
    assert "p99_exemplar" in bd
    trace_id = bd["p99_exemplar"]["trace_id"]
    assert trace_id is not None
    assert any(s.trace_id == trace_id for s in full_run["recent"])


def test_request_histograms_carry_exemplars(full_run):
    e2e = full_run["metrics"]["serve.request.e2e_ms"]
    assert e2e["count"] > 0
    assert e2e.get("exemplars"), "request histogram recorded no exemplars"


def test_tenant_accounting_covers_every_tenant(full_run):
    tenants = full_run["report"]["tenants"]
    assert sorted(tenants) == ["t0", "t1", "t2"]
    for stats in tenants.values():
        assert stats["requests"] > 0
        assert stats["bytes_in"] > 0
        assert stats["latency_ms"]["samples"] > 0
    assert "p99ms" in full_run["tenant_table"]


def test_farm_latency_histograms_carry_dispatch_exemplars(full_run):
    """The farm-side hook: dispatch/readback latency histograms stamp the
    owning serve DispatchSpan id into their buckets."""
    snap = full_run["metrics"]["farm.dispatch.latency_ms"]
    assert snap["count"] > 0
    exemplars = set(snap.get("exemplars", {}).values())
    span_ids = {s.dispatch_id for s in full_run["dispatches"]}
    assert exemplars & span_ids


# ---------------------------------------------------------------------- #
# live telemetry pipeline

def test_exposition_renders_metrics_and_tenants(full_run):
    text = render_exposition()
    assert "# TYPE" in text
    # names are sanitized for the exposition format
    assert "serve_request_e2e_ms_count" in text
    assert "# EXEMPLAR" in text


def test_snapshot_record_is_json_round_trippable(full_run):
    record = snapshot_record(t=1.5)
    blob = json.dumps(record, sort_keys=True, default=str)
    back = json.loads(blob)
    assert back["t"] == 1.5
    assert "metrics" in back and "tenants" in back
    assert back["breakdown"]["requests"] >= 0


def test_request_breakdown_empty_metrics():
    assert request_breakdown({}) == {
        "requests": 0, "mean_ms": {}, "shares": {}
    }


def test_telemetry_endpoint_serves_exposition():
    """The asyncio side-car: a GET against the telemetry listener returns
    the exposition page."""
    import asyncio

    from automerge_tpu.obs.export import serve_exposition

    async def drive():
        server = await serve_exposition("127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
        await writer.drain()
        payload = await reader.read()
        writer.close()
        server.close()
        await server.wait_closed()
        return payload

    payload = asyncio.run(drive())
    assert payload.startswith(b"HTTP/1.0 200 OK")
    assert b"# TYPE" in payload
