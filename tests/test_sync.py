"""Sync protocol tests, ported from the reference suite
(/root/reference/test/sync_test.js): two simulated peers exchanging
messages until convergence."""
import pytest

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu import sync as Sync
from automerge_tpu.columnar import encode_change


def set_key(key, value):
    return lambda d: d.__setitem__(key, value)


def sync_drive(a, b, a_sync_state=None, b_sync_state=None, max_rounds=10):
    """Message-shuttling driver loop (sync_test.js:15-35)."""
    a_sync_state = a_sync_state or am.init_sync_state()
    b_sync_state = b_sync_state or am.init_sync_state()
    a_to_b = b_to_a = None
    for _ in range(max_rounds):
        a_sync_state, a_to_b = am.generate_sync_message(a, a_sync_state)
        b_sync_state, b_to_a = am.generate_sync_message(b, b_sync_state)
        if a_to_b is None and b_to_a is None:
            break
        if a_to_b is not None:
            b, b_sync_state, _ = am.receive_sync_message(b, b_sync_state, a_to_b)
        if b_to_a is not None:
            a, a_sync_state, _ = am.receive_sync_message(a, a_sync_state, b_to_a)
    else:
        raise AssertionError("Did not synchronize within max_rounds")
    return a, b, a_sync_state, b_sync_state


class TestSyncProtocol:
    def test_empty_docs_converge_quickly(self):
        a = am.init("aaaaaaaa")
        b = am.init("bbbbbbbb")
        a, b, *_ = sync_drive(a, b)
        assert dict(a) == dict(b) == {}

    def test_one_way_sync(self):
        a = am.init("aaaaaaaa")
        b = am.init("bbbbbbbb")
        for i in range(5):
            a = am.change(a, set_key("x", i))
        a, b, *_ = sync_drive(a, b)
        assert b["x"] == 4

    def test_bidirectional_sync(self):
        a = am.change(am.init("aaaaaaaa"), set_key("from_a", 1))
        b = am.change(am.init("bbbbbbbb"), set_key("from_b", 2))
        a, b, *_ = sync_drive(a, b)
        assert dict(a) == dict(b) == {"from_a": 1, "from_b": 2}

    def test_incremental_sync_after_initial(self):
        a = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        a = am.change(a, set_key("y", 2))
        a, b, sa, sb = sync_drive(a, b, sa, sb)
        assert dict(b) == {"x": 1, "y": 2}

    def test_concurrent_changes_converge(self):
        a = am.change(am.init("aaaaaaaa"), set_key("base", 0))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        a = am.change(a, set_key("a_key", "a"))
        b = am.change(b, set_key("b_key", "b"))
        a, b, sa, sb = sync_drive(a, b, sa, sb)
        assert dict(a) == dict(b) == {"base": 0, "a_key": "a", "b_key": "b"}

    def test_sync_message_round_trip(self):
        msg = {
            "heads": [],
            "need": [],
            "have": [{"lastSync": [], "bloom": b""}],
            "changes": [b"fake-change-bytes"],
        }
        assert Sync.decode_sync_message(Sync.encode_sync_message(msg)) == msg

    def test_sync_state_round_trip(self):
        a = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        encoded = Sync.encode_sync_state(sa)
        decoded = Sync.decode_sync_state(encoded)
        assert decoded["sharedHeads"] == sa["sharedHeads"]
        assert decoded["lastSentHeads"] == []

    def test_peer_reset_triggers_full_resync(self):
        a = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        # b loses all state; fresh doc and sync state
        b2 = am.init("cccccccc")
        a, b2, sa2, sb2 = sync_drive(a, b2, am.init_sync_state(), am.init_sync_state())
        assert dict(b2) == {"x": 1}


class TestBloomFilter:
    def test_contains_added_hashes(self):
        hashes = [("%02x" % i) * 32 for i in range(10)]
        bloom = Sync.BloomFilter(hashes)
        for h in hashes:
            assert bloom.contains_hash(h)

    def test_serialization_round_trip(self):
        hashes = [("%02x" % i) * 32 for i in range(10)]
        bloom = Sync.BloomFilter(hashes)
        bloom2 = Sync.BloomFilter(bloom.bytes)
        assert bloom2.num_entries == 10
        assert bloom2.num_bits_per_entry == 10
        assert bloom2.num_probes == 7
        for h in hashes:
            assert bloom2.contains_hash(h)

    def test_empty_filter(self):
        bloom = Sync.BloomFilter([])
        assert bloom.bytes == b""
        assert not bloom.contains_hash("00" * 32)

    def test_false_positive_rate_reasonable(self):
        from hashlib import sha256

        hashes = [sha256(str(i).encode()).hexdigest() for i in range(1000)]
        bloom = Sync.BloomFilter(hashes[:500])
        false_positives = sum(1 for h in hashes[500:] if bloom.contains_hash(h))
        assert false_positives <= 15  # ~1% expected rate on 500 probes

class TestSyncStateCodec:
    """ISSUE 5 satellites: decode_sync_state must reject damaged blobs with
    SyncProtocolError (never a raw IndexError/DecodeError) and construct no
    partial state; encode->decode round-trips, with and without the session
    extension."""

    def _encoded_state(self):
        a = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        return Sync.encode_sync_state(sa), sa

    def test_truncated_blob_raises_sync_protocol_error(self):
        blob, _sa = self._encoded_state()
        for keep in range(len(blob)):
            try:
                Sync.decode_sync_state(blob[:keep])
            except am.SyncProtocolError:
                continue
            except Exception as exc:  # noqa: BLE001 - the regression under test
                raise AssertionError(
                    f"truncation at {keep} leaked {type(exc).__name__}: {exc}"
                )
            # decoding a truncation to a shorter-but-valid record is fine
            # only when the hash list boundary happens to align
            assert keep == len(blob)

    def test_garbage_blob_raises_sync_protocol_error(self):
        import random as _random

        rng = _random.Random(0)
        for length in (0, 1, 7, 40, 200):
            blob = bytes(rng.randrange(256) for _ in range(length))
            with pytest.raises(am.SyncProtocolError):
                Sync.decode_sync_state(blob)

    def test_wrong_record_type_raises(self):
        with pytest.raises(am.SyncProtocolError):
            Sync.decode_sync_state(b"\x42" + b"\x00" * 8)

    def test_round_trip_property(self):
        """encode->decode restores sharedHeads for arbitrary sorted unique
        hash lists (the durable field); ephemeral fields reset."""
        import random as _random

        rng = _random.Random(1)
        for _ in range(25):
            n = rng.randrange(0, 6)
            heads = sorted({
                "".join(rng.choice("0123456789abcdef") for _ in range(64))
                for _ in range(n)
            })
            state = Sync.init_sync_state()
            state["sharedHeads"] = heads
            state["lastSentHeads"] = heads  # dropped by design
            decoded = Sync.decode_sync_state(Sync.encode_sync_state(state))
            assert decoded["sharedHeads"] == heads
            assert decoded["lastSentHeads"] == []
            assert decoded["sentHashes"] == {}
            assert "session" not in decoded

    WD_AT_REST = {"wdRounds": 0, "wdStage": 0, "wdStalls": 0,
                  "wdEscalations": 0, "wdResets": 0}

    def test_session_extension_round_trips(self):
        state = Sync.init_sync_state()
        session = {"epoch": 0xDEADBEEF, "seqOut": 12, "lastSeen": 9,
                   "peerEpoch": 77}
        blob = Sync.encode_sync_state(state, session=session)
        decoded = Sync.decode_sync_state(blob)
        assert decoded["session"] == {**session, **self.WD_AT_REST}
        session_none_peer = dict(session, peerEpoch=None)
        decoded2 = Sync.decode_sync_state(
            Sync.encode_sync_state(state, session=session_none_peer)
        )
        assert decoded2["session"] == {**session_none_peer, **self.WD_AT_REST}

    def test_watchdog_counters_round_trip(self):
        """ISSUE 18 satellite (bugfix): the watchdog/backoff ladder rides
        the session extension, so a restart no longer re-arms a stalled
        channel's escalation state from zero."""
        state = Sync.init_sync_state()
        session = {"epoch": 5, "seqOut": 2, "lastSeen": 1, "peerEpoch": 9,
                   "wdRounds": 3, "wdStage": 1, "wdStalls": 4,
                   "wdEscalations": 5, "wdResets": 2}
        decoded = Sync.decode_sync_state(
            Sync.encode_sync_state(state, session=session)
        )
        assert decoded["session"] == session

    def test_pre_watchdog_blobs_decode_with_ladder_at_rest(self):
        """Backward direction: a blob written before the watchdog tail
        existed (extension stops after peerEpoch) still decodes — the
        counters come back zero, not as a decode error."""
        from automerge_tpu.codecs import Encoder
        from automerge_tpu.sync import (
            PEER_STATE_TYPE,
            SESSION_EXT_VERSION,
            _encode_hashes,
        )

        enc = Encoder()
        enc.append_byte(PEER_STATE_TYPE)
        _encode_hashes(enc, [])
        enc.append_byte(SESSION_EXT_VERSION)
        enc.append_uint32(5)
        enc.append_uint53(2)
        enc.append_uint53(1)
        enc.append_byte(1)
        enc.append_uint32(9)
        decoded = Sync.decode_sync_state(enc.buffer)
        assert decoded["session"] == {
            "epoch": 5, "seqOut": 2, "lastSeen": 1, "peerEpoch": 9,
            **self.WD_AT_REST,
        }

    def test_watchdog_tail_is_prefix_compatible(self):
        """Forward direction: the new blob's prefix up to the old format's
        length is byte-identical, so pre-watchdog decoders (which stop
        after peerEpoch and tolerate trailing bytes) read it unchanged."""
        from automerge_tpu.codecs import Encoder
        from automerge_tpu.sync import (
            PEER_STATE_TYPE,
            SESSION_EXT_VERSION,
            _encode_hashes,
        )

        state = Sync.init_sync_state()
        session = {"epoch": 5, "seqOut": 2, "lastSeen": 1, "peerEpoch": 9,
                   "wdRounds": 3, "wdStage": 1, "wdStalls": 4,
                   "wdEscalations": 5, "wdResets": 2}
        new_blob = Sync.encode_sync_state(state, session=session)
        enc = Encoder()
        enc.append_byte(PEER_STATE_TYPE)
        _encode_hashes(enc, [])
        enc.append_byte(SESSION_EXT_VERSION)
        enc.append_uint32(5)
        enc.append_uint53(2)
        enc.append_uint53(1)
        enc.append_byte(1)
        enc.append_uint32(9)
        old_blob = enc.buffer
        assert new_blob[: len(old_blob)] == old_blob

    def test_pre_extension_blobs_still_decode(self):
        """Wire compatibility: blobs from the pre-session encoder (type
        byte + hash list, nothing after) decode unchanged."""
        blob, sa = self._encoded_state()
        decoded = Sync.decode_sync_state(blob)
        assert decoded["sharedHeads"] == sa["sharedHeads"]
        assert "session" not in decoded

    def test_extension_is_invisible_to_trailing_byte_tolerant_readers(self):
        """The extension rides after the legacy payload: a reader that
        stops at the hash list (the old decoder's behaviour) sees an
        identical prefix."""
        state = Sync.init_sync_state()
        legacy = Sync.encode_sync_state(state)
        extended = Sync.encode_sync_state(
            state, session={"epoch": 1, "seqOut": 0, "lastSeen": 0,
                            "peerEpoch": None}
        )
        assert extended[: len(legacy)] == legacy


class TestReceiveIdempotency:
    """ISSUE 5 satellite: double-delivery of the same change batch must be
    a no-op on heads AND on backend state (sequential layer)."""

    def test_double_receive_same_message_is_noop(self):
        a = am.init("aaaaaaaa")
        for i in range(3):
            a = am.change(a, set_key("x", i))
        b = am.init("bbbbbbbb")
        sa = am.init_sync_state()
        sb = am.init_sync_state()
        sa, msg = am.generate_sync_message(a, sa)
        # force changes onto the wire: tell a what b needs
        from automerge_tpu import Frontend
        b_state = Frontend.get_backend_state(b, "test")
        sb, reply = am.generate_sync_message(b, sb)
        a, sa, _ = am.receive_sync_message(a, sa, reply)
        sa, msg = am.generate_sync_message(a, sa)
        assert Sync.decode_sync_message(msg)["changes"]
        b, sb, patch1 = am.receive_sync_message(b, sb, msg)
        heads_after = Backend.get_heads(Frontend.get_backend_state(b, "t"))
        saved_after = am.save(b)
        state_after = dict(sb)
        # identical bytes delivered again (e.g. a retransmission the
        # envelope layer missed): heads and document state unchanged
        b2, sb2, patch2 = am.receive_sync_message(b, sb, msg)
        assert Backend.get_heads(Frontend.get_backend_state(b2, "t")) == heads_after
        assert am.save(b2) == saved_after
        assert dict(b2) == dict(b)
        assert sb2["sharedHeads"] == state_after["sharedHeads"]
