"""Sync protocol tests, ported from the reference suite
(/root/reference/test/sync_test.js): two simulated peers exchanging
messages until convergence."""
import pytest

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu import sync as Sync
from automerge_tpu.columnar import encode_change


def set_key(key, value):
    return lambda d: d.__setitem__(key, value)


def sync_drive(a, b, a_sync_state=None, b_sync_state=None, max_rounds=10):
    """Message-shuttling driver loop (sync_test.js:15-35)."""
    a_sync_state = a_sync_state or am.init_sync_state()
    b_sync_state = b_sync_state or am.init_sync_state()
    a_to_b = b_to_a = None
    for _ in range(max_rounds):
        a_sync_state, a_to_b = am.generate_sync_message(a, a_sync_state)
        b_sync_state, b_to_a = am.generate_sync_message(b, b_sync_state)
        if a_to_b is None and b_to_a is None:
            break
        if a_to_b is not None:
            b, b_sync_state, _ = am.receive_sync_message(b, b_sync_state, a_to_b)
        if b_to_a is not None:
            a, a_sync_state, _ = am.receive_sync_message(a, a_sync_state, b_to_a)
    else:
        raise AssertionError("Did not synchronize within max_rounds")
    return a, b, a_sync_state, b_sync_state


class TestSyncProtocol:
    def test_empty_docs_converge_quickly(self):
        a = am.init("aaaaaaaa")
        b = am.init("bbbbbbbb")
        a, b, *_ = sync_drive(a, b)
        assert dict(a) == dict(b) == {}

    def test_one_way_sync(self):
        a = am.init("aaaaaaaa")
        b = am.init("bbbbbbbb")
        for i in range(5):
            a = am.change(a, set_key("x", i))
        a, b, *_ = sync_drive(a, b)
        assert b["x"] == 4

    def test_bidirectional_sync(self):
        a = am.change(am.init("aaaaaaaa"), set_key("from_a", 1))
        b = am.change(am.init("bbbbbbbb"), set_key("from_b", 2))
        a, b, *_ = sync_drive(a, b)
        assert dict(a) == dict(b) == {"from_a": 1, "from_b": 2}

    def test_incremental_sync_after_initial(self):
        a = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        a = am.change(a, set_key("y", 2))
        a, b, sa, sb = sync_drive(a, b, sa, sb)
        assert dict(b) == {"x": 1, "y": 2}

    def test_concurrent_changes_converge(self):
        a = am.change(am.init("aaaaaaaa"), set_key("base", 0))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        a = am.change(a, set_key("a_key", "a"))
        b = am.change(b, set_key("b_key", "b"))
        a, b, sa, sb = sync_drive(a, b, sa, sb)
        assert dict(a) == dict(b) == {"base": 0, "a_key": "a", "b_key": "b"}

    def test_sync_message_round_trip(self):
        msg = {
            "heads": [],
            "need": [],
            "have": [{"lastSync": [], "bloom": b""}],
            "changes": [b"fake-change-bytes"],
        }
        assert Sync.decode_sync_message(Sync.encode_sync_message(msg)) == msg

    def test_sync_state_round_trip(self):
        a = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        encoded = Sync.encode_sync_state(sa)
        decoded = Sync.decode_sync_state(encoded)
        assert decoded["sharedHeads"] == sa["sharedHeads"]
        assert decoded["lastSentHeads"] == []

    def test_peer_reset_triggers_full_resync(self):
        a = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        b = am.init("bbbbbbbb")
        a, b, sa, sb = sync_drive(a, b)
        # b loses all state; fresh doc and sync state
        b2 = am.init("cccccccc")
        a, b2, sa2, sb2 = sync_drive(a, b2, am.init_sync_state(), am.init_sync_state())
        assert dict(b2) == {"x": 1}


class TestBloomFilter:
    def test_contains_added_hashes(self):
        hashes = [("%02x" % i) * 32 for i in range(10)]
        bloom = Sync.BloomFilter(hashes)
        for h in hashes:
            assert bloom.contains_hash(h)

    def test_serialization_round_trip(self):
        hashes = [("%02x" % i) * 32 for i in range(10)]
        bloom = Sync.BloomFilter(hashes)
        bloom2 = Sync.BloomFilter(bloom.bytes)
        assert bloom2.num_entries == 10
        assert bloom2.num_bits_per_entry == 10
        assert bloom2.num_probes == 7
        for h in hashes:
            assert bloom2.contains_hash(h)

    def test_empty_filter(self):
        bloom = Sync.BloomFilter([])
        assert bloom.bytes == b""
        assert not bloom.contains_hash("00" * 32)

    def test_false_positive_rate_reasonable(self):
        from hashlib import sha256

        hashes = [sha256(str(i).encode()).hexdigest() for i in range(1000)]
        bloom = Sync.BloomFilter(hashes[:500])
        false_positives = sum(1 for h in hashes[500:] if bloom.contains_hash(h))
        assert false_positives <= 15  # ~1% expected rate on 500 probes
