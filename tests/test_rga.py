"""Differential tests: the device RGA rank kernel vs the host sequential
scan oracle (the reference rule, new.js:144-163)."""
import random

import numpy as np

from automerge_tpu.tpu.rga import batched_rga_rank
from automerge_tpu.tpu.text_engine import BatchedTextEngine, HostDocOrder


def rank_via_device(host_orders, actors):
    """Packs a list of HostDocOrder element sets into the kernel's inputs
    (slots in arrival order per doc) and returns elemIds sorted by the
    device rank, per doc."""
    num_docs = len(host_orders)
    actor_index = {a: i for i, a in enumerate(actors)}
    arrival = []  # per doc: elemIds in insertion-arrival order
    for order in host_orders:
        ids = sorted(
            order.elems,
            key=lambda e: (int(e.split("@")[0]), e.split("@")[1]),
        )
        arrival.append(ids)

    cap = max((len(a) for a in arrival), default=1) or 1
    parent = np.full((num_docs, cap), -1, np.int32)
    opid = np.zeros((num_docs, cap), np.int64)
    valid = np.zeros((num_docs, cap), bool)
    for d, ids in enumerate(arrival):
        slot_of = {e: i for i, e in enumerate(ids)}
        for i, e in enumerate(ids):
            ctr, actor = e.split("@")
            opid[d, i] = (int(ctr) << 20) | actor_index[actor]
            valid[d, i] = True
            ref = host_orders[d].parents[e]
            parent[d, i] = -1 if ref == "_head" else slot_of[ref]

    ranks = np.zeros(max(len(actors), 1), np.int32)
    for r, i in enumerate(sorted(range(len(actors)), key=lambda i: actors[i])):
        ranks[i] = r
    out = np.asarray(batched_rga_rank(parent, opid, valid, ranks))
    result = []
    for d, ids in enumerate(arrival):
        by_rank = sorted(range(len(ids)), key=lambda i: out[d, i])
        result.append([ids[i] for i in by_rank])
    return result


class TrackedOrder(HostDocOrder):
    """HostDocOrder that also records each element's insertion reference."""

    __slots__ = ("parents",)

    def __init__(self):
        super().__init__()
        self.parents = {}

    def insert(self, elem_id, ref):
        self.parents[elem_id] = ref
        super().insert(elem_id, ref)


def test_rank_simple_chain():
    order = TrackedOrder()
    a = "aaaaaaaa"
    order.insert(f"1@{a}", "_head")
    order.insert(f"2@{a}", f"1@{a}")
    order.insert(f"3@{a}", f"2@{a}")
    assert rank_via_device([order], [a]) == [order.elems]


def test_rank_concurrent_head_inserts_tie_on_actor_string():
    # both actors use counter 1: order must break on the actor *string*,
    # regardless of intern order (b interned before a here).
    order = TrackedOrder()
    a, b = "aaaaaaaa", "bbbbbbbb"
    order.insert(f"1@{b}", "_head")
    order.insert(f"1@{a}", "_head")
    assert order.elems == [f"1@{b}", f"1@{a}"]
    assert rank_via_device([order], [b, a]) == [order.elems]


def test_rank_interleaved_subtrees():
    # concurrent runs after the same ref: each actor's run stays contiguous,
    # higher-opId run first (the classic RGA non-interleaving example)
    order = TrackedOrder()
    a, b = "aaaaaaaa", "bbbbbbbb"
    order.insert(f"1@{a}", "_head")
    # actor a types "xy" after 1@a; actor b concurrently types "pq" after 1@a
    order.insert(f"2@{a}", f"1@{a}")
    order.insert(f"3@{a}", f"2@{a}")
    order.insert(f"2@{b}", f"1@{a}")
    order.insert(f"3@{b}", f"2@{b}")
    assert rank_via_device([order], [a, b]) == [order.elems]


def test_rank_randomized_batches_vs_host_oracle():
    rng = random.Random(7)
    actors = [f"{c:08x}" for c in (0xB0, 0x0A, 0xFF, 0x11, 0x2C)]
    num_docs = 6
    orders = [TrackedOrder() for _ in range(num_docs)]
    # per-actor Lamport counters per doc, advanced past everything seen
    counters = [dict.fromkeys(actors, 0) for _ in range(num_docs)]
    for step in range(120):
        d = rng.randrange(num_docs)
        actor = rng.choice(actors)
        order = orders[d]
        # causal constraint: new opId must exceed the ref's counter; model a
        # replica that has seen everything currently in the doc
        top = max([counters[d][x] for x in actors] + [0])
        ctr = top + rng.randrange(1, 3)
        counters[d][actor] = ctr
        ref = "_head" if not order.elems or rng.random() < 0.2 else rng.choice(order.elems)
        order.insert(f"{ctr}@{actor}", ref)
    got = rank_via_device(orders, actors)
    for d in range(num_docs):
        assert got[d] == orders[d].elems, f"doc {d} diverged"


def test_rank_concurrent_same_counter_multi_actor():
    # several actors insert at the same ref with identical counters: pure
    # actor-string ordering, interleaved with deeper descendants
    order = TrackedOrder()
    actors = ["cccccccc", "aaaaaaaa", "dddddddd", "bbbbbbbb"]
    order.insert("1@aaaaaaaa", "_head")
    for actor in actors:
        order.insert(f"2@{actor}", "1@aaaaaaaa")
    # descendants of one of the middle siblings
    order.insert("3@aaaaaaaa", "2@bbbbbbbb")
    order.insert("4@dddddddd", "3@aaaaaaaa")
    assert rank_via_device([order], actors) == [order.elems]


class TestEngineIntegration:
    def test_visible_texts_uses_device_ranks(self):
        eng = BatchedTextEngine(2, capacity=32)
        a, b = "aaaaaaaa", "bbbbbbbb"
        eng.apply_batch([
            [({"action": "set", "insert": True, "elemId": "_head", "value": "h"}, 1, a)],
            [({"action": "set", "insert": True, "elemId": "_head", "value": "x"}, 1, b)],
        ])
        eng.apply_batch([
            [({"action": "set", "insert": True, "elemId": f"1@{a}", "value": "i"}, 2, a),
             ({"action": "set", "insert": True, "elemId": f"1@{a}", "value": "j"}, 2, b)],
            [({"action": "del", "elemId": f"1@{b}", "pred": [f"1@{b}"]}, 2, b)],
        ])
        # 2@b > 2@a lexicographically on actor: j precedes i
        assert eng.visible_texts() == [["h", "j", "i"], []]

    def test_counter_tie_conflict_winner_by_actor_string(self):
        # two concurrent overwrites of the same element with equal counters:
        # winner must be the greater actor *string* even though the engine
        # interned the other actor first
        eng = BatchedTextEngine(1, capacity=32)
        a, z = "aaaaaaaa", "zzzzzzzz"
        eng.apply_batch([[({"action": "set", "insert": True, "elemId": "_head", "value": "v"}, 1, a)]])
        eng.apply_batch([[
            ({"action": "set", "elemId": f"1@{a}", "value": "A", "pred": [f"1@{a}"]}, 2, a),
            ({"action": "set", "elemId": f"1@{a}", "value": "Z", "pred": [f"1@{a}"]}, 2, z),
        ]])
        assert eng.visible_texts() == [["Z"]]
