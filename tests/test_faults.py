"""Fault-isolation suite: the farm, sync layer and codecs under poisoned
traffic (automerge_tpu/testing/faults.py is the corpus + injection harness).

The contract under test (ISSUE 3):
- one poisoned document must not fail its batch neighbours
  (isolation="doc", the default) — per-sequence isolation as in batched
  TPU serving;
- a quarantined delivery leaves the target document's state byte-for-byte
  untouched (save/load round-trip, heads, and a subsequent clean apply all
  match a farm that never saw the poison);
- the batched device path failing mid-dispatch degrades to the sequential
  reference walk instead of failing the call;
- sync peers survive malformed messages with local state untouched.
"""
import pytest

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu import sync as Sync
from automerge_tpu.errors import (
    AutomergeError,
    CausalityError,
    ChecksumError,
    DecodeError,
    DeviceFaultError,
    EncodeError,
    PackingLimitError,
    QuarantinedError,
    SyncProtocolError,
    error_kind,
)
from automerge_tpu.columnar import decode_change, decode_change_columns
from automerge_tpu.obs.metrics import enabled_metrics, get_metrics
from automerge_tpu.opset import OpSet
from automerge_tpu.testing import faults
from automerge_tpu.tpu import rga
from automerge_tpu.tpu.farm import TpuDocFarm


def healthy_change(actor, seq, start_op, deps=(), key="k", value=1):
    return faults.make_change(actor, seq, start_op, deps,
                              [faults.set_op(key, value)])


def change_hash(buf):
    return decode_change_columns(buf)["hash"]


# ---------------------------------------------------------------------- #
# taxonomy


class TestTaxonomy:
    def test_hierarchy_keeps_stdlib_bases(self):
        # existing callers catch ValueError; the taxonomy must not break them
        for cls in (DecodeError, ChecksumError, EncodeError, CausalityError,
                    PackingLimitError, SyncProtocolError):
            assert issubclass(cls, AutomergeError)
            assert issubclass(cls, ValueError)
        assert issubclass(ChecksumError, DecodeError)
        assert issubclass(QuarantinedError, AutomergeError)
        assert issubclass(DeviceFaultError, AutomergeError)

    def test_error_kind_dimension(self):
        assert error_kind(DecodeError("x")) == "decode"
        assert error_kind(ChecksumError("x")) == "checksum"
        assert error_kind(CausalityError("x")) == "causality"
        assert error_kind(PackingLimitError("x")) == "packing"
        assert error_kind(SyncProtocolError("x")) == "sync"
        assert error_kind(DeviceFaultError("x")) == "device"
        assert error_kind(ValueError("x")) == "other"
        assert error_kind(RuntimeError("x")) == "other"


# ---------------------------------------------------------------------- #
# corrupters


class TestCorrupters:
    @pytest.mark.parametrize("name,corrupt,kind", faults.BYTE_CORPUS,
                             ids=[c[0] for c in faults.BYTE_CORPUS])
    def test_byte_corpus_error_kinds(self, name, corrupt, kind):
        buf = healthy_change("aaaaaaaa", 1, 1)
        poisoned = corrupt(buf)
        assert poisoned != buf
        with pytest.raises(DecodeError) as exc_info:
            decode_change(poisoned)
        assert error_kind(exc_info.value) == kind

    def test_bad_chunk_type_preserves_checksum(self):
        """The chunk-type rewrite is a checksum-preserving field mutation:
        the container verifies, the *content* is wrong."""
        buf = faults.bad_chunk_type(healthy_change("aaaaaaaa", 1, 1))
        with pytest.raises(DecodeError, match="chunk type"):
            decode_change(buf)

    def test_seq_poisons_raise_causality(self):
        opset = OpSet()
        opset.apply_changes([healthy_change("aaaaaaaa", 1, 1)])
        with pytest.raises(CausalityError, match="Reuse of sequence number"):
            opset.apply_changes([faults.seq_reused("aaaaaaaa", 1, 2)])
        with pytest.raises(CausalityError, match="Skipped sequence number"):
            opset.apply_changes([faults.seq_skipped("aaaaaaaa", 5, 2)])

    def test_missing_dep_queues_forever_without_error(self):
        opset = OpSet()
        patch = opset.apply_changes([faults.missing_dep("bbbbbbbb", 1, 1)])
        assert patch["pendingChanges"] == 1
        assert opset.get_missing_deps() == [faults.MISSING_DEP]


# ---------------------------------------------------------------------- #
# the acceptance batch: 64 docs, 8 poisoned, one call


class TestFarmIsolation:
    N = 64

    def _setup_farms(self, monkeypatch, threshold=None):
        monkeypatch.setattr(rga, "MAX_ELEMS", 4)
        farm = TpuDocFarm(self.N, capacity=64, quarantine_threshold=threshold)
        control = TpuDocFarm(self.N, capacity=64, quarantine_threshold=threshold)
        seeds = [healthy_change(f"{d:08x}", 1, 1, value=d) for d in range(self.N)]
        farm.apply_changes([[b] for b in seeds])
        control.apply_changes([[b] for b in seeds])
        heads = [farm.get_heads(d) for d in range(self.N)]
        return farm, control, seeds, heads

    def _poison_delivery(self, heads):
        """Second-round delivery: 8 poisoned docs spanning every taxonomy
        bucket, 56 healthy. Returns (delivery, poison: doc -> expected)."""
        delivery = []
        poison = {
            1: ChecksumError, 9: ChecksumError,     # corrupt checksum
            17: DecodeError, 25: DecodeError,       # truncated buffer
            33: CausalityError,                     # seq reuse
            41: PackingLimitError, 49: PackingLimitError,  # counter overflow
            57: PackingLimitError,                  # MAX_ELEMS overflow
        }
        for d in range(self.N):
            actor = f"{d:08x}"
            good = healthy_change(actor, 2, 2, heads[d], key="r2", value=d)
            if d in (1, 9):
                delivery.append([faults.corrupt_checksum(good)])
            elif d in (17, 25):
                delivery.append([faults.truncated(good)])
            elif d == 33:
                delivery.append([faults.seq_reused(actor, 1, 2, heads[d])])
            elif d in (41, 49):
                delivery.append([faults.counter_overflow(
                    actor, 2, rga.MAX_COUNTER, heads[d])])
            elif d == 57:
                make_list = faults.make_change(
                    actor, 2, 2, heads[d],
                    [{"action": "makeList", "obj": "_root", "key": "l",
                      "pred": []}])
                flood = faults.insert_flood(
                    actor, 3, 3, f"2@{actor}", rga.MAX_ELEMS + 1,
                    [change_hash(make_list)])
                delivery.append([make_list, flood])
            else:
                delivery.append([good])
        return delivery, poison

    def test_64_doc_batch_with_8_poisoned(self, monkeypatch):
        farm, control, _seeds, heads = self._setup_farms(monkeypatch)
        delivery, poison = self._poison_delivery(heads)

        result = farm.apply_changes(delivery)

        # the 56 healthy docs all applied, byte-equal to a farm that never
        # saw the poison
        control_delivery = [
            [] if d in poison else delivery[d] for d in range(self.N)
        ]
        expected = control.apply_changes(control_delivery)
        for d in range(self.N):
            if d in poison:
                continue
            assert result.outcomes[d].status == "applied"
            assert result[d] == expected[d]

        # quarantined docs report the right taxonomy error, state untouched
        assert set(result.quarantined) == set(poison)
        for d, expected_cls in poison.items():
            outcome = result.outcomes[d]
            assert outcome.status == "quarantined"
            assert isinstance(outcome.error, expected_cls), (d, outcome.error)
            assert outcome.error_kind == error_kind(outcome.error)
            assert len(farm.get_all_changes(d)) == 1  # only the seed
            assert farm.get_heads(d) == heads[d]
            assert farm.get_patch(d) == control.get_patch(d)

        # packing/causality poisons carry the offending change hashes
        assert result.outcomes[33].offending_hashes
        assert result.outcomes[41].offending_hashes

    def test_batch_isolation_reproduces_all_or_nothing(self, monkeypatch):
        farm, _control, _seeds, heads = self._setup_farms(monkeypatch)
        delivery, _poison = self._poison_delivery(heads)
        committed = [len(farm.get_all_changes(d)) for d in range(self.N)]
        with pytest.raises(ValueError):
            farm.apply_changes(delivery, isolation="batch")
        # the decode-phase poison aborts the call before anything commits
        assert [len(farm.get_all_changes(d)) for d in range(self.N)] == committed

    def test_unknown_isolation_mode_rejected(self):
        farm = TpuDocFarm(1)
        with pytest.raises(ValueError, match="isolation"):
            farm.apply_changes([[]], isolation="nope")

    def test_quarantine_cause_counters(self, monkeypatch):
        reg = get_metrics()
        reg.reset()
        with enabled_metrics():
            farm, _control, _seeds, heads = self._setup_farms(monkeypatch)
            delivery, _poison = self._poison_delivery(heads)
            farm.apply_changes(delivery)
        snap = reg.as_dict()
        assert snap["farm.quarantine.causes.checksum"]["value"] == 2
        assert snap["farm.quarantine.causes.decode"]["value"] == 2
        assert snap["farm.quarantine.causes.causality"]["value"] == 1
        assert snap["farm.quarantine.causes.packing"]["value"] == 3
        # the batch-wide abort counter stays untouched in doc mode
        assert snap["farm.prevalidation.aborts"]["value"] == 0


# ---------------------------------------------------------------------- #
# error-path state invariance (property-style over the fault corpus)


def _fault_corpus_for(actor, seq, start_op, deps):
    """Poisoned second-round deliveries for one doc, spanning the corpus."""
    good = faults.make_change(actor, seq, start_op, deps,
                              [faults.set_op("r2", 7)])
    return [
        ("truncated", [faults.truncated(good)]),
        ("bit_flipped", [faults.bit_flipped(good, bit=13)]),
        ("corrupt_checksum", [faults.corrupt_checksum(good)]),
        ("bad_chunk_type", [faults.bad_chunk_type(good)]),
        ("garbage", [faults.garbage(40, seed=3)]),
        ("seq_reuse", [faults.seq_reused(actor, seq - 1, start_op, deps)]),
        ("seq_skip", [faults.seq_skipped(actor, seq + 5, start_op, deps)]),
        ("counter_overflow",
         [faults.counter_overflow(actor, seq, rga.MAX_COUNTER, deps)]),
        ("mixed_good_then_poison",
         [good, faults.corrupt_checksum(
             faults.make_change(actor, seq + 1, start_op + 1,
                                [change_hash(good)],
                                [faults.set_op("r3", 8)]))]),
    ]


class TestStateInvariance:
    def test_quarantine_leaves_state_equal_to_never_poisoned(self):
        """After ANY quarantined delivery: save()/load() round-trip,
        get_heads, and a subsequent clean apply on the same doc must match
        a farm that never saw the poison."""
        seed = healthy_change("bbbbbbbb", 1, 1, value=3)
        seed_hash = change_hash(seed)
        for name, poisoned in _fault_corpus_for("bbbbbbbb", 2, 2, [seed_hash]):
            farm = TpuDocFarm(2, capacity=32)
            control = TpuDocFarm(2, capacity=32)
            neighbour = healthy_change("aaaaaaaa", 1, 1, value=9)
            for f in (farm, control):
                f.apply_changes([[neighbour], [seed]])

            result = farm.apply_changes([[], poisoned])
            assert result.outcomes[1].status == "quarantined", name
            assert result.outcomes[0].status == "applied", name

            # state untouched: heads + committed log match the control
            assert farm.get_heads(1) == control.get_heads(1), name
            assert farm.get_all_changes(1) == control.get_all_changes(1), name

            # save/load round-trip through the binary document format
            replica = OpSet()
            replica.apply_changes(farm.get_all_changes(1))
            reloaded = OpSet(replica.save())
            assert reloaded.heads == farm.get_heads(1), name
            assert reloaded.get_patch() == control.get_patch(1), name

            # a subsequent clean apply behaves as if the poison never came
            clean = healthy_change("bbbbbbbb", 2, 2, [seed_hash],
                                   key="after", value=11)
            got = farm.apply_changes([[], [clean]])
            want = control.apply_changes([[], [clean]])
            assert got[1] == want[1], name
            assert got.outcomes[1].status == "applied", name
            assert farm.get_patch(1) == control.get_patch(1), name

    def test_poisoned_list_doc_rolls_back_element_tables(self, monkeypatch):
        """A rolled-back delivery must also restore the element forest the
        rank kernel reads (num_elems + index maps)."""
        monkeypatch.setattr(rga, "MAX_ELEMS", 8)
        farm = TpuDocFarm(1, capacity=32)
        control = TpuDocFarm(1, capacity=32)
        mk = faults.make_change(
            "aaaaaaaa", 1, 1, [],
            [{"action": "makeList", "obj": "_root", "key": "l", "pred": []}])
        ins = faults.insert_flood("aaaaaaaa", 2, 2, "1@aaaaaaaa", 2,
                                  [change_hash(mk)])
        for f in (farm, control):
            f.apply_changes([[mk]])
            f.apply_changes([[ins]])
        # a delivery that inserts 3 then overflows: rolled back atomically
        deps = farm.get_heads(0)
        more = faults.insert_flood("aaaaaaaa", 3, 4, "1@aaaaaaaa", 3, deps)
        flood = faults.insert_flood("aaaaaaaa", 4, 7, "1@aaaaaaaa", 20,
                                    [change_hash(more)])
        result = farm.apply_changes([[more, flood]])
        assert result.outcomes[0].status == "quarantined"
        assert result.outcomes[0].error_kind == "packing"
        assert int(farm.num_elems[0]) == int(control.num_elems[0]) == 2
        # the clean prefix alone still applies afterwards
        got = farm.apply_changes([[more]])
        want = control.apply_changes([[more]])
        assert got[0] == want[0]
        assert int(farm.num_elems[0]) == 5


# ---------------------------------------------------------------------- #
# quarantine lifecycle


class TestQuarantineLifecycle:
    def test_threshold_shedding_and_release(self):
        reg = get_metrics()
        reg.reset()
        with enabled_metrics():
            farm = TpuDocFarm(2, capacity=32, quarantine_threshold=2)
            good = healthy_change("aaaaaaaa", 1, 1)
            bad = faults.garbage(32)
            # two consecutive failures cross the threshold
            assert farm.apply_changes([[bad], []]).outcomes[0].status == "quarantined"
            assert 0 not in farm.quarantine
            assert farm.apply_changes([[bad], []]).outcomes[0].status == "quarantined"
            assert 0 in farm.quarantine

            # traffic is shed unprocessed — even healthy deliveries
            shed = farm.apply_changes([[good], []])
            assert isinstance(shed.outcomes[0].error, QuarantinedError)
            assert len(farm.get_all_changes(0)) == 0
            # the neighbour is unaffected throughout
            ok = farm.apply_changes([[], [good]])
            assert ok.outcomes[1].status == "applied"

            assert farm.release_quarantine(0) == [0]
            back = farm.apply_changes([[good], []])
            assert back.outcomes[0].status == "applied"
            assert len(farm.get_all_changes(0)) == 1
        snap = reg.as_dict()
        assert snap["farm.quarantine.entered"]["value"] == 1
        assert snap["farm.quarantine.shed"]["value"] == 1
        assert snap["farm.quarantine.released"]["value"] == 1
        assert snap["farm.quarantine.active"]["value"] == 0

    def test_clean_delivery_resets_failure_streak(self):
        farm = TpuDocFarm(1, capacity=32, quarantine_threshold=2)
        bad = faults.garbage(32)
        farm.apply_changes([[bad]])
        assert farm.fault_counts[0] == 1
        farm.apply_changes([[healthy_change("aaaaaaaa", 1, 1)]])
        assert farm.fault_counts[0] == 0
        farm.apply_changes([[bad]])
        assert 0 not in farm.quarantine  # streak restarted

    def test_release_all(self):
        farm = TpuDocFarm(3, capacity=32, quarantine_threshold=1)
        bad = faults.garbage(32)
        farm.apply_changes([[bad], [], [bad]])
        assert set(farm.quarantine) == {0, 2}
        assert sorted(farm.release_quarantine()) == [0, 2]
        assert farm.quarantine == {}


# ---------------------------------------------------------------------- #
# degraded mode: device-dispatch bisection + sequential fallback


class TestDeviceFallback:
    def _seeded(self, n=8):
        farm = TpuDocFarm(n, capacity=64, quarantine_threshold=None)
        control = TpuDocFarm(n, capacity=64, quarantine_threshold=None)
        seeds = [healthy_change(f"{d:08x}", 1, 1, value=d) for d in range(n)]
        farm.apply_changes([[b] for b in seeds])
        control.apply_changes([[b] for b in seeds])
        return farm, control, seeds

    def test_bisect_isolates_poison_doc_and_survivors_get_patches(self):
        reg = get_metrics()
        reg.reset()
        farm, control, _ = self._seeded(8)
        second = [
            healthy_change(f"{d:08x}", 2, 2, farm.get_heads(d), key="r2",
                           value=d * 10)
            for d in range(8)
        ]
        with enabled_metrics():
            with faults.inject("farm.device_dispatch", faults.fail_docs([3])):
                result = farm.apply_changes([[b] for b in second])

        assert result.outcomes[3].status == "quarantined"
        assert isinstance(result.outcomes[3].error, DeviceFaultError)
        assert result.outcomes[3].error_kind == "device"
        assert len(farm.get_all_changes(3)) == 1  # rolled back

        # survivors applied via the sequential walk, patches reference-equal
        expected = control.apply_changes(
            [[] if d == 3 else [second[d]] for d in range(8)]
        )
        for d in range(8):
            if d == 3:
                continue
            assert result.outcomes[d].status == "applied"
            assert result.outcomes[d].fallback
            assert result[d] == expected[d]

        snap = reg.as_dict()
        assert snap["farm.bisect.rounds"]["value"] > 0
        assert snap["farm.fallback.calls"]["value"] == 1
        assert snap["farm.fallback.docs"]["value"] == 7
        assert snap["farm.quarantine.causes.device"]["value"] == 1

    def test_degraded_docs_keep_working_after_fallback(self):
        farm, control, _ = self._seeded(4)
        second = [healthy_change(f"{d:08x}", 2, 2, farm.get_heads(d), key="r2")
                  for d in range(4)]
        with faults.inject("farm.device_dispatch", faults.fail_docs([2])):
            farm.apply_changes([[b] for b in second])
        control.apply_changes([[] if d == 2 else [second[d]] for d in range(4)])

        # next call has a healthy device again; degraded docs stay walk-served
        third = [healthy_change(f"{d:08x}", 3, 3, farm.get_heads(d), key="r3")
                 for d in range(4)]
        third[2] = healthy_change("00000002", 2, 2, farm.get_heads(2), key="r2")
        got = farm.apply_changes([[b] for b in third])
        want = control.apply_changes([[b] for b in third])
        for d in range(4):
            assert got.outcomes[d].status == "applied"
            assert got[d] == want[d]
            assert farm.get_patch(d) == control.get_patch(d)

    def test_wedged_device_serves_whole_batch_sequentially(self):
        farm, control, _ = self._seeded(4)
        second = [healthy_change(f"{d:08x}", 2, 2, farm.get_heads(d), key="r2")
                  for d in range(4)]
        with faults.inject("farm.device_dispatch", faults.fail_always()):
            result = farm.apply_changes([[b] for b in second])
        expected = control.apply_changes([[b] for b in second])
        for d in range(4):
            assert result.outcomes[d].status == "applied"
            assert result.outcomes[d].fallback
            assert result[d] == expected[d]


# ---------------------------------------------------------------------- #
# injection points in engine + opset atomicity


class TestInjectionPoints:
    def test_engine_apply_batch_point_fires(self):
        from automerge_tpu.tpu.engine import BatchedMapEngine
        from automerge_tpu.tpu.transcode import BatchTranscoder

        engine = BatchedMapEngine(1, 8)
        tr = BatchTranscoder()
        batch = tr.changes_to_batch(
            [[({"action": "set", "obj": "_root", "key": "k", "value": 1,
                "pred": []}, 1, "aaaaaaaa")]]
        )
        with faults.inject("engine.apply_batch", faults.fail_always()):
            with pytest.raises(RuntimeError, match="injected"):
                engine.apply_batch(batch)
        engine.apply_batch(batch)  # hook removed on exit

    def test_inject_is_scoped(self):
        fired = []
        with faults.inject("sync.receive_message", lambda **kw: fired.append(1)):
            assert "sync.receive_message" in faults._HOOKS
        assert "sync.receive_message" not in faults._HOOKS

    def test_opset_apply_is_atomic_on_gate_failure(self):
        """A mixed delivery that raises must leave no phantom hash-index
        entries behind (the sync layer's state-untouched guarantee rests
        on this)."""
        opset = OpSet()
        opset.apply_changes([healthy_change("aaaaaaaa", 1, 1)])
        good = healthy_change("aaaaaaaa", 2, 2, opset.heads)
        poison = faults.seq_reused("aaaaaaaa", 1, 3, [change_hash(good)])
        before_index = dict(opset.change_index_by_hash)
        before_heads = list(opset.heads)
        with pytest.raises(CausalityError):
            opset.apply_changes([good, poison])
        assert opset.change_index_by_hash == before_index
        assert opset.heads == before_heads
        # the clean prefix still applies on retry
        patch = opset.apply_changes([good])
        assert patch["clock"]["aaaaaaaa"] == 2


# ---------------------------------------------------------------------- #
# sync-layer survival


class TestSyncFaults:
    def _two_peers(self):
        a = Backend.init()
        a, _ = Backend.apply_changes(a, [healthy_change("aaaaaaaa", 1, 1)])
        return a, Sync.init_sync_state()

    def test_malformed_message_rejected_state_untouched(self):
        backend, state = self._two_peers()
        heads = Backend.get_heads(backend)
        valid = Sync.encode_sync_message(
            {"heads": heads, "need": [], "have": [], "changes": []}
        )
        for bad in (faults.truncated(valid, keep=3), b"\x00" + valid[1:],
                    faults.garbage(16)):
            with pytest.raises(SyncProtocolError):
                Sync.receive_sync_message(backend, state, bad)
            # the handle is still usable (not frozen) and state unchanged
            assert Backend.get_heads(backend) == heads
            assert state["theirHeads"] is None
        # and the same message minus corruption still processes
        backend, state, _ = Sync.receive_sync_message(backend, state, valid)
        assert state["theirHeads"] == heads

    def test_message_with_poisoned_changes_rejected(self):
        backend, state = self._two_peers()
        heads = Backend.get_heads(backend)
        poison = faults.seq_reused("aaaaaaaa", 1, 2, heads)
        msg = Sync.encode_sync_message(
            {"heads": heads, "need": [], "have": [], "changes": [poison]}
        )
        with pytest.raises(SyncProtocolError, match="inapplicable"):
            Sync.receive_sync_message(backend, state, msg)
        assert Backend.get_heads(backend) == heads
        # backend still usable for a clean message afterwards
        clean = healthy_change("bbbbbbbb", 1, 2, key="other")
        msg2 = Sync.encode_sync_message(
            {"heads": heads, "need": [], "have": [], "changes": [clean]}
        )
        backend, state, patch = Sync.receive_sync_message(backend, state, msg2)
        assert patch is not None

    def test_rejected_counter_increments(self):
        reg = get_metrics()
        reg.reset()
        backend, state = self._two_peers()
        with enabled_metrics():
            with pytest.raises(SyncProtocolError):
                Sync.receive_sync_message(backend, state, faults.garbage(16))
        assert reg.counter("sync.messages.rejected").value == 1

    def test_injection_point_rejects_like_a_wire_fault(self):
        backend, state = self._two_peers()
        valid = Sync.encode_sync_message(
            {"heads": Backend.get_heads(backend), "need": [], "have": [],
             "changes": []}
        )
        with faults.inject(
            "sync.receive_message",
            faults.fail_always(lambda: ValueError("line noise")),
        ):
            with pytest.raises(SyncProtocolError):
                Sync.receive_sync_message(backend, state, valid)

    def test_sync_farm_survives_one_bad_peer(self):
        from automerge_tpu.tpu.sync_farm import SyncFarm

        farm = TpuDocFarm(3, capacity=32)
        farm.apply_changes(
            [[healthy_change(f"{d:08x}", 1, 1, value=d)] for d in range(3)]
        )
        sf = SyncFarm(farm)
        heads = [farm.get_heads(d) for d in range(3)]

        def msg_for(d, changes=()):
            return Sync.encode_sync_message(
                {"heads": heads[d], "need": [], "have": [],
                 "changes": list(changes)}
            )

        good0 = msg_for(0)
        bad1 = faults.truncated(msg_for(1), keep=3)
        new2 = healthy_change("00000002", 2, 2, heads[2], key="r2")
        good2 = msg_for(2, [new2])
        states = [SyncFarm.init_state() for _ in range(3)]
        results = sf.receive_messages([
            (0, states[0], good0), (1, states[1], bad1), (2, states[2], good2),
        ])
        # bad channel: state unchanged, no patch, round not aborted
        assert results[1] == (states[1], None)
        assert results[0][0]["theirHeads"] == heads[0]
        assert results[2][1] is not None  # the healthy channel's patch
        assert len(farm.get_all_changes(2)) == 2

    def test_peers_converge_after_poisoned_interlude(self):
        """End-to-end: two api-level peers keep syncing to convergence even
        though one receives corrupt messages mid-conversation."""
        a = am.change(am.init("aaaaaaaa"), lambda d: d.__setitem__("x", 1))
        b = am.change(am.init("bbbbbbbb"), lambda d: d.__setitem__("y", 2))
        sa, sb = am.init_sync_state(), am.init_sync_state()
        for _ in range(10):
            sa, msg_ab = am.generate_sync_message(a, sa)
            sb, msg_ba = am.generate_sync_message(b, sb)
            if msg_ab is None and msg_ba is None:
                break
            if msg_ab is not None:
                # b sees a corrupted copy first, rejects it, then the real one
                with pytest.raises(SyncProtocolError):
                    am.receive_sync_message(b, sb, faults.truncated(msg_ab, keep=5))
                b, sb, _ = am.receive_sync_message(b, sb, msg_ab)
            if msg_ba is not None:
                a, sa, _ = am.receive_sync_message(a, sa, msg_ba)
        assert dict(a) == dict(b) == {"x": 1, "y": 2}
