"""Tier-1 gate for the crash-consistent persistence tier (automerge_tpu/store).

The store's contract, exercised end to end:

- **Durability at the ack boundary**: every commit a `TpuDocFarm` acked
  (apply_changes returned) is on disk after any crash; recovery always
  lands on a clean per-doc *prefix* of the committed history, bit-compatible
  with the reference wire format.
- **Torn writes are expected, corruption is quarantined**: a short frame at
  the active tail truncates non-fatally (`StoreTornWriteError`); a
  checksum-bad frame or footer-less sealed segment moves the whole segment
  to `corrupt/` and its docs into the PR-3 quarantine with a
  `StoreCorruptError` cause — never a crash, never silent loss.
- **Two-generation compaction**: a crash at ANY stage of
  rotate()/compact() leaves either the old or the new generation fully
  live (the crash-point sweep walks an injected failure across every
  `store.append`/`store.fsync`/`store.rotate`/`store.compact` firing).
- **Cold start**: `open_farm` hydrates via one batched delivery and
  restores persisted quarantine state (the save/load regression), and a
  process-mesh worker SIGKILLed mid-commit re-hydrates from its shard
  store on respawn and after a full controller cold restart.
"""
import json
import multiprocessing
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _make_change_stream

from automerge_tpu import StoreCorruptError, StoreTornWriteError
from automerge_tpu.errors import ChecksumError, DecodeError, error_from_kind
from automerge_tpu.store import (MANIFEST_NAME, QUARANTINE_NAME, ShardStore,
                                 StoreConfig, atomic_write, open_farm)
from automerge_tpu.store.wal import CORRUPT_DIR
from automerge_tpu.testing import faults
from automerge_tpu.tpu.farm import TpuDocFarm

NUM_DOCS = 4
ROUNDS = 3
OPS = 6
CAP = ROUNDS * OPS + 8


def _streams(num_docs=NUM_DOCS, rounds=ROUNDS, seed=0):
    return [
        _make_change_stream(rounds, OPS, seed=seed + 31 * d)
        for d in range(num_docs)
    ]


def _round_delivery(streams, r):
    return [[streams[d][r]] for d in range(len(streams))]


def _write_farm(root, streams, config=None, rounds=None):
    """A farm with an attached store, the workload committed round by
    round. Returns (farm, store) still open."""
    farm = TpuDocFarm(len(streams), capacity=CAP)
    store = ShardStore(root, config)
    farm.attach_store(store)
    for r in range(rounds if rounds is not None else len(streams[0])):
        farm.apply_changes(_round_delivery(streams, r))
    return farm, store


# ---------------------------------------------------------------------- #
# round-trip + bit compatibility


def test_wal_roundtrip_bit_compatible(tmp_path):
    """Reopening replays the WAL into a farm whose change log is
    byte-identical to the writer's — the persisted chunks ARE the
    reference-format buffers that were applied."""
    root = str(tmp_path / "shard")
    streams = _streams()
    farm, store = _write_farm(root, streams)
    store.close()

    farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
    assert store2.report.clean, vars(store2.report)
    assert [list(c) for c in farm2.changes] == [list(c) for c in farm.changes]
    assert farm2.heads == farm.heads
    assert farm2.quarantine == {}
    for d in range(NUM_DOCS):
        assert json.dumps(farm2.get_patch(d), sort_keys=True) == \
            json.dumps(farm.get_patch(d), sort_keys=True)
    store2.close()


def test_rotation_and_compaction_roundtrip(tmp_path):
    """rotate() seals the active segment (footer + rename), compact()
    folds sealed WAL into a verified cold generation and deletes the
    sources; the reopened farm is unchanged through both."""
    root = str(tmp_path / "shard")
    streams = _streams(rounds=ROUNDS + 2)
    farm = TpuDocFarm(NUM_DOCS, capacity=CAP + 2 * OPS)
    store = ShardStore(root)
    farm.attach_store(store)
    for r in range(ROUNDS):
        farm.apply_changes(_round_delivery(streams, r))
    store.rotate()
    for r in range(ROUNDS, ROUNDS + 2):
        farm.apply_changes(_round_delivery(streams, r))
    store.compact()
    names = set(os.listdir(root))
    assert MANIFEST_NAME in names
    assert any(n.startswith("cold-") for n in names)
    assert not any(n.endswith(".seg") and n.startswith("wal-") for n in names)
    store.close()

    farm2, store2 = open_farm(
        root, NUM_DOCS, capacity=CAP + 2 * OPS)
    assert store2.report.clean
    assert [list(c) for c in farm2.changes] == [list(c) for c in farm.changes]
    assert farm2.heads == farm.heads
    store2.close()


# ---------------------------------------------------------------------- #
# torn writes and corruption


def test_torn_tail_truncates_to_last_whole_frame(tmp_path):
    """A partial frame at the active tail (the power-loss signature) is
    truncated away; every acked commit before it survives."""
    root = str(tmp_path / "shard")
    streams = _streams()
    farm, store = _write_farm(root, streams)
    store.close()
    active = [n for n in os.listdir(root) if n.endswith(".open")]
    assert len(active) == 1
    path = os.path.join(root, active[0])
    with open(path, "ab") as fh:
        fh.write(b"\x99\x00\x00\x00" + b"torn!")  # length says 153, body 5

    farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
    assert not store2.report.clean
    assert store2.report.torn_bytes == 9
    assert store2.report.corrupt_segments == []
    assert [list(c) for c in farm2.changes] == [list(c) for c in farm.changes]
    store2.close()

    # and the truncated file appends cleanly again
    farm3, store3 = open_farm(root, NUM_DOCS, capacity=CAP)
    assert store3.report.clean
    store3.close()


def test_torn_mid_frame_recovers_strict_prefix(tmp_path):
    """Chopping bytes off the active tail loses exactly the last frames,
    never garbles the ones before them."""
    root = str(tmp_path / "shard")
    streams = _streams()
    farm, store = _write_farm(root, streams)
    store.close()
    active = [n for n in os.listdir(root) if n.endswith(".open")]
    path = os.path.join(root, active[0])
    os.truncate(path, os.path.getsize(path) - 11)

    farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
    assert store2.report.torn_bytes > 0
    total = sum(len(c) for c in farm2.changes)
    full = sum(len(c) for c in farm.changes)
    assert 0 < total < full
    for d in range(NUM_DOCS):
        k = len(farm2.changes[d])
        assert list(farm2.changes[d]) == list(farm.changes[d])[:k]
    store2.close()


def test_corrupt_segment_quarantines_only_its_docs(tmp_path):
    """A checksum-bad frame condemns its whole segment: the file moves to
    corrupt/, its docs enter quarantine with a StoreCorruptError cause,
    and docs whose history lives in OTHER segments hydrate untouched."""
    root = str(tmp_path / "shard")
    streams = _streams()
    farm = TpuDocFarm(NUM_DOCS, capacity=CAP)
    store = ShardStore(root)
    farm.attach_store(store)
    # segment 1: docs 0..1 only, sealed; segment 2: docs 2..3
    for r in range(ROUNDS):
        farm.apply_changes(
            [[streams[d][r]] if d < 2 else [] for d in range(NUM_DOCS)])
    store.rotate()
    for r in range(ROUNDS):
        farm.apply_changes(
            [[streams[d][r]] if d >= 2 else [] for d in range(NUM_DOCS)])
    store.close()

    sealed = [n for n in os.listdir(root) if n.endswith(".seg")]
    assert len(sealed) == 1
    path = os.path.join(root, sealed[0])
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0x40  # mid-payload bit flip
    with open(path, "wb") as fh:
        fh.write(data)

    farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
    assert not store2.report.clean
    assert sealed[0] in store2.report.corrupt_segments
    assert os.path.exists(os.path.join(root, CORRUPT_DIR, sealed[0]))
    assert set(farm2.quarantine) == {0, 1}
    for exc in farm2.quarantine.values():
        assert isinstance(exc, StoreCorruptError)
    # the untouched segment's docs hydrated fully
    for d in (2, 3):
        assert list(farm2.changes[d]) == list(farm.changes[d])
    store2.close()


def test_store_errors_are_decode_taxonomy(tmp_path):
    """Satellite: the store's failure modes are classifiable taxonomy
    errors, exported from the package root and rebuildable by kind."""
    import automerge_tpu

    assert automerge_tpu.StoreCorruptError is StoreCorruptError
    assert automerge_tpu.StoreTornWriteError is StoreTornWriteError
    assert issubclass(StoreCorruptError, DecodeError)
    assert issubclass(StoreTornWriteError, DecodeError)
    assert StoreCorruptError.kind == "store_corrupt"
    assert StoreTornWriteError.kind == "store_torn"
    rebuilt = error_from_kind("store_corrupt", "boom")
    assert isinstance(rebuilt, StoreCorruptError)
    assert str(rebuilt) == "boom"


# ---------------------------------------------------------------------- #
# group commit + the atomic writer


def test_group_commit_defers_fsync_not_consistency(tmp_path):
    """group_commit=N pays one fsync every N barriers (the documented
    durability window); the WAL content is flushed and prefix-consistent
    either way."""
    fsyncs = []

    def counter(**ctx):
        # only count syncs of the active WAL segment — the quarantine
        # sidecar's atomic_write fires the same point for its own file
        if ".open" in (ctx.get("path") or ""):
            fsyncs.append(ctx["path"])

    root = str(tmp_path / "shard")
    streams = _streams()
    with faults.inject("store.fsync", counter):
        farm = TpuDocFarm(NUM_DOCS, capacity=CAP)
        store = ShardStore(root, StoreConfig(group_commit=3))
        farm.attach_store(store)
        barrier_syncs = []
        for r in range(ROUNDS):
            before = len(fsyncs)
            farm.apply_changes(_round_delivery(streams, r))
            barrier_syncs.append(len(fsyncs) - before)
    # barriers 1 and 2 deferred, barrier 3 paid the fsync
    assert barrier_syncs == [0, 0, 1]
    store.close()

    farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
    assert [list(c) for c in farm2.changes] == [list(c) for c in farm.changes]
    store2.close()


def test_atomic_write_leaves_old_content_on_fsync_crash(tmp_path):
    """Satellite: the shared atomic writer (store manifests/sidecars AND
    the obs black box) is all-or-nothing — a crash in its fsync seam
    leaves the previous content untouched and no tmp litter."""
    path = str(tmp_path / "MANIFEST.json")
    atomic_write(path, '{"generation": 1}')
    with faults.inject("store.fsync", faults.fail_always(
            lambda: OSError("injected fsync failure"))):
        with pytest.raises(OSError):
            atomic_write(path, '{"generation": 2}')
    assert open(path).read() == '{"generation": 1}'
    assert os.listdir(tmp_path) == ["MANIFEST.json"]


def test_blackbox_rides_the_atomic_writer(tmp_path):
    """Satellite: obs/flight.py's black box goes through the shared
    atomic_write (tmp + rename), so a reader never observes a
    half-written file and no tmp litter survives."""
    from automerge_tpu.obs.flight import (FlightRecorder, read_blackbox,
                                          write_blackbox)

    rec = FlightRecorder(capacity=8)
    rec.enabled = True
    rec.record("mesh.worker.spawn", shard=0, pid=1)
    path = str(tmp_path / "bb.json")
    write_blackbox(path, rec)
    payload = read_blackbox(path)
    assert payload is not None
    assert payload["events"][-1]["event"] == "mesh.worker.spawn"
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]


# ---------------------------------------------------------------------- #
# quarantine state survives save/load (the satellite regression)


def test_quarantine_state_survives_cold_restart(tmp_path):
    """The PR-bugfix regression: a quarantined doc's cause and failure
    counts were silently reset by save/load. Now the sidecar persists
    them through the barrier and hydration restores them."""
    root = str(tmp_path / "shard")
    streams = _streams()
    farm = TpuDocFarm(NUM_DOCS, capacity=CAP, quarantine_threshold=1)
    store = ShardStore(root)
    farm.attach_store(store)
    farm.apply_changes(_round_delivery(streams, 0))
    # poison doc 1 into organic quarantine (checksum damage)
    delivery = [[] for _ in range(NUM_DOCS)]
    delivery[1] = [faults.bit_flipped(streams[1][1])]
    res = farm.apply_changes(delivery)
    assert res.outcomes[1].status == "quarantined"
    assert 1 in farm.quarantine
    counts = list(farm.fault_counts)
    store.close()

    farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP,
                              quarantine_threshold=1)
    assert 1 in farm2.quarantine
    assert isinstance(farm2.quarantine[1], ChecksumError)
    assert list(farm2.fault_counts) == counts
    # released docs stay released across the NEXT cold restart
    assert farm2.release_quarantine(1) == [1]
    store2.close()
    farm3, store3 = open_farm(root, NUM_DOCS, capacity=CAP,
                              quarantine_threshold=1)
    assert farm3.quarantine == {}
    # and the released doc accepts redelivery of the clean change
    delivery = [[] for _ in range(NUM_DOCS)]
    delivery[1] = [streams[1][1]]
    res = farm3.apply_changes(delivery)
    assert res.outcomes[1].status == "applied"
    store3.close()


def test_quarantine_sidecar_is_advisory(tmp_path):
    """An unreadable sidecar degrades to 'no persisted quarantine', never
    a failed open."""
    root = str(tmp_path / "shard")
    streams = _streams()
    farm, store = _write_farm(root, streams)
    store.close()
    with open(os.path.join(root, QUARANTINE_NAME), "w") as fh:
        fh.write("not json {")
    farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
    assert farm2.quarantine == {}
    store2.close()


# ---------------------------------------------------------------------- #
# the crash-point sweep


def _crash_workload(root, streams, point, n):
    """One scripted run with fail_at(n) armed at `point`: ROUNDS commits,
    then a rotation, then a compaction. Returns (acked_rounds, hook,
    refs) where refs pins the abandoned farm/store so their buffered
    handles stay un-flushed (the in-process stand-in for a killed
    process) until the caller's reopen has happened."""
    hook = faults.fail_at(n, lambda: OSError(f"injected crash at {point}#{n}"))
    farm = store = None
    acked = 0
    try:
        with faults.inject(point, hook):
            farm = TpuDocFarm(len(streams), capacity=CAP)
            store = ShardStore(root, StoreConfig())
            farm.attach_store(store)
            for r in range(ROUNDS):
                farm.apply_changes(_round_delivery(streams, r))
                acked = r + 1
            store.rotate()
            store.compact()
            store.close()
            store = None
    except OSError:
        pass
    return acked, hook, (farm, store)


@pytest.mark.parametrize(
    "point", ["store.append", "store.fsync", "store.rotate", "store.compact"])
def test_crash_point_sweep(tmp_path, point):
    """Walks an injected crash across EVERY firing of one durability
    boundary over a commit+rotate+compact workload. After each crash the
    reopened farm must hold, per doc, an exact prefix of the intended
    history that covers every acked commit, with no corrupt segments —
    the store never trades consistency for the crash, only the unacked
    tail."""
    streams = _streams()
    n = 1
    while True:
        root = str(tmp_path / f"{point.replace('.', '-')}-{n}")
        acked, hook, refs = _crash_workload(root, streams, point, n)
        if hook.fired < n:
            # walked off the end: the whole workload ran fault-free
            assert acked == ROUNDS
            break
        farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
        assert store2.report.corrupt_segments == [], (point, n)
        for d in range(NUM_DOCS):
            got = list(farm2.changes[d])
            assert got == list(streams[d])[:len(got)], (point, n, d)
            assert len(got) >= acked, (point, n, d, acked)
        assert farm2.quarantine == {}, (point, n)
        store2.close()
        del refs
        n += 1
    assert n > 1, f"{point} never fired"


def test_compact_crash_leaves_one_generation_live(tmp_path):
    """Pin the two-generation invariant at each named compaction stage:
    whatever stage dies, reopening serves the complete history exactly
    once."""
    streams = _streams()
    for stage in ("write", "verify", "swap", "cleanup"):
        root = str(tmp_path / stage)
        farm, store = _write_farm(root, streams)
        store.rotate()
        hook = faults.fail_at(1, lambda: OSError("injected"), stage=stage)
        with faults.inject("store.compact", hook):
            with pytest.raises(OSError):
                store.compact()
        assert hook.fired == 1, stage
        store.close()
        farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
        assert store2.report.clean, (stage, vars(store2.report))
        assert [list(c) for c in farm2.changes] == \
            [list(c) for c in farm.changes], stage
        store2.close()


def test_rotate_crash_recovery_finishes_or_resumes(tmp_path):
    """A crash between the footer write and the rename leaves a footer-
    stamped .open file; recovery finishes the seal instead of calling it
    corrupt. A crash before the footer leaves the segment active."""
    streams = _streams()
    for stage in ("footer", "rename"):
        root = str(tmp_path / stage)
        farm, store = _write_farm(root, streams)
        hook = faults.fail_at(1, lambda: OSError("injected"), stage=stage)
        with faults.inject("store.rotate", hook):
            with pytest.raises(OSError):
                store.rotate()
        store.close()
        farm2, store2 = open_farm(root, NUM_DOCS, capacity=CAP)
        assert store2.report.corrupt_segments == [], stage
        assert [list(c) for c in farm2.changes] == \
            [list(c) for c in farm.changes], stage
        if stage == "rename":
            # footer made it down: recovery completed the rotation
            assert store2.report.sealed_on_open >= 1
        store2.close()


# ---------------------------------------------------------------------- #
# the process mesh: SIGKILL mid-commit + controller cold restart


@pytest.mark.skipif(sys.platform == "win32", reason="POSIX process mesh")
def test_mesh_worker_sigkill_mid_commit_then_cold_restart(tmp_path):
    """The acceptance crash: a shard worker SIGKILLs itself mid-delivery.
    The controller quarantines the in-flight docs, the respawned worker
    re-hydrates from its shard store (plus the delivery-log replay), a
    release+redelivery completes the round — and a brand-new MeshFarm
    over the same store_dir serves identical patches after close()."""
    from automerge_tpu.parallel.meshfarm import MeshFarm

    store_dir = str(tmp_path / "mesh-store")
    num_docs, rounds = 6, 2
    streams = _streams(num_docs=num_docs, rounds=rounds + 1, seed=100)
    mesh = MeshFarm(num_docs, num_shards=2, capacity=CAP,
                    mesh_backend="process", store_dir=store_dir)
    try:
        for r in range(rounds):
            mesh.apply_changes(_round_delivery(streams, r))

        mesh.inject_worker_fault(1, when="next_apply")
        res = mesh.apply_changes(_round_delivery(streams, rounds))
        crashed = [d for d in range(num_docs)
                   if res.outcomes[d].status == "quarantined"]
        assert crashed, "the SIGKILL round should quarantine in-flight docs"
        for d in crashed:
            mesh.release_quarantine(d)
        delivery = [[] for _ in range(num_docs)]
        for d in crashed:
            delivery[d] = [streams[d][rounds]]
        res = mesh.apply_changes(delivery)
        assert all(res.outcomes[d].status == "applied" for d in crashed)
        before = [json.dumps(mesh.get_patch(d), sort_keys=True)
                  for d in range(num_docs)]
    finally:
        mesh.close()
    assert multiprocessing.active_children() == []

    cold = MeshFarm(num_docs, num_shards=2, capacity=CAP,
                    mesh_backend="process", store_dir=store_dir)
    try:
        after = [json.dumps(cold.get_patch(d), sort_keys=True)
                 for d in range(num_docs)]
        assert after == before
    finally:
        cold.close()


def test_mesh_store_dir_vs_rebalance_is_an_error(tmp_path):
    from automerge_tpu.parallel.meshfarm import MeshFarm

    with pytest.raises(ValueError, match="rebalanc"):
        MeshFarm(4, num_shards=2, store_dir=str(tmp_path / "s"),
                 rebalance_interval=2)


def test_mesh_inline_backend_persists_too(tmp_path):
    """store_dir is backend-agnostic: the inline mesh writes the same
    per-shard stores and cold-restarts from them."""
    from automerge_tpu.parallel.meshfarm import MeshFarm

    store_dir = str(tmp_path / "mesh-store")
    num_docs = 6
    streams = _streams(num_docs=num_docs, seed=200)
    mesh = MeshFarm(num_docs, num_shards=2, capacity=CAP,
                    mesh_backend="inline", store_dir=store_dir)
    try:
        for r in range(ROUNDS):
            mesh.apply_changes(_round_delivery(streams, r))
        before = [json.dumps(mesh.get_patch(d), sort_keys=True)
                  for d in range(num_docs)]
    finally:
        mesh.close()
    assert sorted(os.listdir(store_dir)) == ["shard-000", "shard-001"]

    cold = MeshFarm(num_docs, num_shards=2, capacity=CAP,
                    mesh_backend="inline", store_dir=store_dir)
    try:
        after = [json.dumps(cold.get_patch(d), sort_keys=True)
                 for d in range(num_docs)]
        assert after == before
    finally:
        cold.close()
