"""Backend facade tests ported from the reference suite
(/root/reference/test/backend_test.js): exact patch assertions through
applyChanges/applyLocalChange/getPatch."""
import pytest

from automerge_tpu import backend as B
from automerge_tpu.columnar import encode_change

from helpers import hash_of

A1 = "0123456789abcdef"
A2 = "89abcdef01234567"


def apply_one(backend, change):
    return B.apply_changes(backend, [encode_change(change)])


class TestMaps:
    def test_conflict_on_same_key(self):
        c1 = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "bird", "value": "magpie", "pred": []}]}
        c2 = {"actor": A2, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "bird", "value": "blackbird", "pred": []}]}
        s, _ = apply_one(B.init(), c1)
        s, patch = apply_one(s, c2)
        assert patch["diffs"]["props"]["bird"] == {
            f"1@{A1}": {"type": "value", "value": "magpie"},
            f"1@{A2}": {"type": "value", "value": "blackbird"},
        }

    def test_updates_inside_deleted_map(self):
        c1 = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeMap", "obj": "_root", "key": "m", "pred": []},
            {"action": "set", "obj": f"1@{A1}", "key": "x", "value": 1, "pred": []}]}
        c2 = {"actor": A1, "seq": 2, "startOp": 3, "time": 0, "deps": [hash_of(c1)], "ops": [
            {"action": "del", "obj": "_root", "key": "m", "pred": [f"1@{A1}"]}]}
        # concurrent update inside the deleted map
        c3 = {"actor": A2, "seq": 1, "startOp": 3, "time": 0, "deps": [hash_of(c1)], "ops": [
            {"action": "set", "obj": f"1@{A1}", "key": "x", "value": 2, "pred": [f"2@{A1}"]}]}
        s, _ = apply_one(B.init(), c1)
        s, _ = apply_one(s, c2)
        s, patch = apply_one(s, c3)
        # the map is deleted; the update produces a patch but the root
        # contains no 'm' reference (the object is unreachable)
        final = B.get_patch(s)
        assert "m" not in final["diffs"]["props"]

    def test_date_at_root(self):
        now_ms = 1700000000123
        c = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "now", "value": now_ms,
             "datatype": "timestamp", "pred": []}]}
        _s, patch = apply_one(B.init(), c)
        assert patch == {
            "clock": {A1: 1}, "deps": [hash_of(c)], "maxOp": 1, "pendingChanges": 0,
            "diffs": {"objectId": "_root", "type": "map", "props": {
                "now": {f"1@{A1}": {"type": "value", "value": now_ms, "datatype": "timestamp"}}}},
        }


class TestLists:
    def test_multi_insert_int(self):
        c = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeList", "obj": "_root", "key": "todos", "pred": []},
            {"action": "set", "obj": f"1@{A1}", "insert": True, "elemId": "_head",
             "pred": [], "datatype": "int", "values": [1, 2, 3, 4, 5]}]}
        _s, patch = apply_one(B.init(), c)
        assert patch == {
            "clock": {A1: 1}, "deps": [hash_of(c)], "maxOp": 6, "pendingChanges": 0,
            "diffs": {"objectId": "_root", "type": "map", "props": {"todos": {f"1@{A1}": {
                "objectId": f"1@{A1}", "type": "list", "edits": [
                    {"action": "multi-insert", "index": 0, "elemId": f"2@{A1}",
                     "datatype": "int", "values": [1, 2, 3, 4, 5]}]}}}},
        }

    def test_multi_insert_strings_without_datatype(self):
        c = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeList", "obj": "_root", "key": "l", "pred": []},
            {"action": "set", "obj": f"1@{A1}", "insert": True, "elemId": "_head",
             "pred": [], "values": ["a", "b", "c"]}]}
        _s, patch = apply_one(B.init(), c)
        edits = patch["diffs"]["props"]["l"][f"1@{A1}"]["edits"]
        assert edits == [
            {"action": "multi-insert", "index": 0, "elemId": f"2@{A1}", "values": ["a", "b", "c"]},
        ]

    def test_update_object_in_list(self):
        c1 = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeList", "obj": "_root", "key": "l", "pred": []},
            {"action": "makeMap", "obj": f"1@{A1}", "elemId": "_head", "insert": True, "pred": []},
            {"action": "set", "obj": f"2@{A1}", "key": "title", "value": "w", "pred": []}]}
        c2 = {"actor": A1, "seq": 2, "startOp": 4, "time": 0, "deps": [hash_of(c1)], "ops": [
            {"action": "set", "obj": f"2@{A1}", "key": "done", "value": True, "pred": []}]}
        s, _ = apply_one(B.init(), c1)
        s, patch = apply_one(s, c2)
        assert patch["diffs"]["props"]["l"][f"1@{A1}"]["edits"] == [
            {"action": "update", "index": 0, "opId": f"2@{A1}", "value": {
                "objectId": f"2@{A1}", "type": "map", "props": {
                    "done": {f"4@{A1}": {"type": "value", "value": True}}}}},
        ]

    def test_concurrent_insertion_at_head(self):
        c1 = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeList", "obj": "_root", "key": "l", "pred": []}]}
        c2 = {"actor": A1, "seq": 2, "startOp": 2, "time": 0, "deps": [hash_of(c1)], "ops": [
            {"action": "set", "obj": f"1@{A1}", "elemId": "_head", "insert": True,
             "value": "one", "pred": []}]}
        c3 = {"actor": A2, "seq": 1, "startOp": 2, "time": 0, "deps": [hash_of(c1)], "ops": [
            {"action": "set", "obj": f"1@{A1}", "elemId": "_head", "insert": True,
             "value": "two", "pred": []}]}
        s, _ = apply_one(B.init(), c1)
        s, _ = apply_one(s, c2)
        s, patch = apply_one(s, c3)
        # 2@A2 > 2@A1, so 'two' goes first (index 0)
        assert patch["diffs"]["props"]["l"][f"1@{A1}"]["edits"] == [
            {"action": "insert", "index": 0, "elemId": f"2@{A2}", "opId": f"2@{A2}",
             "value": {"type": "value", "value": "two"}},
        ]


class TestApplyLocalChange:
    def test_sequence_and_deps(self):
        s = B.init()
        c1 = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "a", "value": 1, "pred": []}]}
        s, p1, b1 = B.apply_local_change(s, c1)
        c2 = {"actor": A1, "seq": 2, "startOp": 2, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "b", "value": 2, "pred": []}]}
        s, p2, b2 = B.apply_local_change(s, c2)
        # the backend adds the local actor's previous hash to deps, and strips
        # it from the outgoing patch
        assert p2["deps"] == []
        from automerge_tpu.columnar import decode_change

        decoded = decode_change(b2)
        assert decoded["deps"] == [decode_change(b1)["hash"]]

    def test_rejects_replayed_seq(self):
        s = B.init()
        c1 = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "a", "value": 1, "pred": []}]}
        s, _, _ = B.apply_local_change(s, c1)
        with pytest.raises(ValueError, match="already been applied"):
            B.apply_local_change(s, dict(c1))


class TestChangeGraph:
    def _two_branches(self):
        c1 = {"actor": A1, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "set", "obj": "_root", "key": "a", "value": 1, "pred": []}]}
        c2 = {"actor": A1, "seq": 2, "startOp": 2, "time": 0, "deps": [hash_of(c1)], "ops": [
            {"action": "set", "obj": "_root", "key": "b", "value": 2, "pred": []}]}
        c3 = {"actor": A2, "seq": 1, "startOp": 2, "time": 0, "deps": [hash_of(c1)], "ops": [
            {"action": "set", "obj": "_root", "key": "c", "value": 3, "pred": []}]}
        return c1, c2, c3

    def test_get_changes_since_deps(self):
        c1, c2, c3 = self._two_branches()
        s = B.init()
        for c in (c1, c2, c3):
            s, _ = apply_one(s, c)
        since_c1 = B.get_changes(s, [hash_of(c1)])
        assert sorted(len(c) for c in since_c1) == sorted(
            [len(encode_change(c2)), len(encode_change(c3))]
        )
        assert B.get_changes(s, [hash_of(c2), hash_of(c3)]) == []

    def test_get_changes_unknown_hash(self):
        s, _ = apply_one(B.init(), self._two_branches()[0])
        with pytest.raises(ValueError, match="hash not found"):
            B.get_changes(s, ["ab" * 32])

    def test_get_changes_added(self):
        c1, c2, c3 = self._two_branches()
        s1 = B.init()
        s1, _ = apply_one(s1, c1)
        s2 = B.clone(s1)
        s1, _ = apply_one(s1, c2)
        s2, _ = apply_one(s2, c3)
        added = B.get_changes_added(s1, s2)
        assert added == [encode_change(c3)]

    def test_get_change_by_hash(self):
        c1, c2, _ = self._two_branches()
        s = B.init()
        s, _ = apply_one(s, c1)
        s, _ = apply_one(s, c2)
        assert B.get_change_by_hash(s, hash_of(c1)) == encode_change(c1)
        assert B.get_change_by_hash(s, "ab" * 32) is None

    def test_heads_after_merge_of_branches(self):
        c1, c2, c3 = self._two_branches()
        s = B.init()
        for c in (c1, c2, c3):
            s, _ = apply_one(s, c)
        assert B.get_heads(s) == sorted([hash_of(c2), hash_of(c3)])

    def test_load_changes_then_patch(self):
        c1, c2, c3 = self._two_branches()
        s = B.load_changes(B.init(), [encode_change(c) for c in (c1, c2, c3)])
        patch = B.get_patch(s)
        props = patch["diffs"]["props"]
        assert props["a"][f"1@{A1}"]["value"] == 1
        assert props["b"][f"2@{A1}"]["value"] == 2
        assert props["c"][f"2@{A2}"]["value"] == 3
