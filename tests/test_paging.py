"""Ragged paged op storage (automerge_tpu/tpu/paging.py + engine driver):
allocator invariants, slab occupancy on mixed-size farms, patch parity
with the reference walk, and page rollback under per-doc fault isolation.
"""
import numpy as np

from automerge_tpu.obs.metrics import enabled_metrics, get_metrics
from automerge_tpu.opset import OpSet
from automerge_tpu.testing import faults
from automerge_tpu.tpu.farm import TpuDocFarm
from automerge_tpu.tpu.paging import PageAllocator


def _stream(rounds, ops_per_round, actor="aaaaaaaa", seed=0):
    from bench import _make_change_stream

    return _make_change_stream(rounds, ops_per_round, seed)


def _pages_consistent(farm):
    """The allocator's view must match the per-doc page tables exactly:
    every allocated page is owned by exactly one document."""
    owned = [p for d in range(farm.num_docs) for p in farm.engine.page_table[d]]
    assert len(owned) == len(set(owned)), "page owned twice"
    assert 0 not in owned, "PAD page handed out"
    assert len(owned) == farm.engine.pages.allocated
    for d in range(farm.num_docs):
        need = farm.engine.pages.pages_for(int(farm.engine.lengths[d]))
        assert len(farm.engine.page_table[d]) == need, (
            f"doc {d}: {len(farm.engine.page_table[d])} pages for "
            f"{farm.engine.lengths[d]} rows"
        )


class TestPageAllocator:
    def test_pad_page_reserved(self):
        alloc = PageAllocator(page_size=8, initial_pages=4)
        pages = alloc.alloc(3)
        assert 0 not in pages
        assert alloc.free_count == 0
        assert alloc.allocated == 3

    def test_ensure_doubles(self):
        alloc = PageAllocator(page_size=8, initial_pages=4)
        assert not alloc.ensure(3)
        assert alloc.ensure(10)
        assert alloc.num_pages >= 11
        got = alloc.alloc(10)
        assert len(set(got)) == 10

    def test_free_recycles(self):
        alloc = PageAllocator(page_size=8, initial_pages=8)
        pages = alloc.alloc(5)
        alloc.free(pages[:3])
        assert alloc.free_count == 2 + 3
        assert alloc.allocated == 2

    def test_pages_for(self):
        alloc = PageAllocator(page_size=64)
        assert alloc.pages_for(0) == 0
        assert alloc.pages_for(1) == 1
        assert alloc.pages_for(64) == 1
        assert alloc.pages_for(65) == 2


class TestMixedSizeFarm:
    def test_occupancy_and_patch_parity(self):
        """The acceptance shape: a farm of wildly different doc sizes must
        pack the slab at >= 80% page occupancy, with patches byte-identical
        to the sequential reference walk."""
        num_docs = 16
        # wildly different doc sizes: 16 .. 256 ops per doc
        streams = [
            _stream(d // 4 + 1, 16 * (d % 4 + 1), seed=d)
            for d in range(num_docs)
        ]
        reg = get_metrics()
        reg.reset()
        with enabled_metrics():
            farm = TpuDocFarm(num_docs, capacity=64, page_size=16)
            opsets = [OpSet() for _ in range(num_docs)]
            rounds = max(len(s) for s in streams)
            for r in range(rounds):
                delivery = [
                    [streams[d][r]] if r < len(streams[d]) else []
                    for d in range(num_docs)
                ]
                patches = farm.apply_changes(delivery)
                for d in range(num_docs):
                    if delivery[d]:
                        expected = opsets[d].apply_changes(delivery[d])
                        assert patches[d] == expected, f"doc {d} round {r}"
            for d in range(num_docs):
                assert farm.get_patch(d) == opsets[d].get_patch()
        _pages_consistent(farm)
        occ = reg.gauge("farm.pages.occupancy").value
        assert occ >= 0.8, f"page occupancy {occ:.2f} < 0.8"
        # the dense-era alternative for comparison: pow2(max doc) per doc
        lens = np.asarray(farm.engine.lengths)
        dense_cells = num_docs * (1 << int(lens.max() - 1).bit_length())
        paged_cells = farm.engine.pages.allocated * farm.engine.pages.page_size
        assert paged_cells < dense_cells

    def test_active_only_dispatch(self):
        """Delivering to one doc must not rewrite other docs' pages."""
        farm = TpuDocFarm(8, capacity=32)
        stream = _stream(3, 8)
        farm.apply_changes([[stream[0]]] * 8)
        tables_before = [list(farm.engine.page_table[d]) for d in range(8)]
        farm.apply_changes([[stream[1]]] + [[]] * 7)
        for d in range(1, 8):
            assert farm.engine.page_table[d] == tables_before[d]
        assert farm.engine.lengths[0] > farm.engine.lengths[1]


class TestPageRollback:
    def test_quarantined_delivery_leaks_no_pages(self):
        farm = TpuDocFarm(4, capacity=32, quarantine_threshold=None)
        stream = _stream(2, 8)
        farm.apply_changes([[stream[0]]] * 4)
        _pages_consistent(farm)
        before_alloc = farm.engine.pages.allocated
        before_tables = [list(farm.engine.page_table[d]) for d in range(4)]
        # doc 2's delivery is poisoned: decode fails, state rolls back
        bad = faults.truncated(stream[1])
        result = farm.apply_changes(
            [[stream[1]], [stream[1]], [bytes(bad)], [stream[1]]]
        )
        assert 2 in result.quarantined
        assert farm.engine.page_table[2] == before_tables[2]
        _pages_consistent(farm)
        # healthy docs grew, the quarantined one did not
        assert farm.engine.pages.allocated >= before_alloc
        assert farm.engine.lengths[2] < farm.engine.lengths[1]

    def test_counter_overflow_rollback_restores_pages(self):
        """A packing-limit failure mid-call (gate/transcode phase) restores
        the doc's page allocation via the snapshot."""
        farm = TpuDocFarm(2, capacity=32, quarantine_threshold=None)
        stream = _stream(1, 8)
        farm.apply_changes([[stream[0]]] * 2)
        _pages_consistent(farm)
        snap_pages = list(farm.engine.page_table[0])
        big = faults.make_change(
            "cccccccc", 1, 1 << 24, [],
            [faults.set_op("k", 1)],
        )
        result = farm.apply_changes([[big], []])
        assert 0 in result.quarantined
        assert farm.engine.page_table[0] == snap_pages
        _pages_consistent(farm)

    def test_release_quarantine_and_recover(self):
        farm = TpuDocFarm(2, capacity=32, quarantine_threshold=1)
        stream = _stream(2, 8)
        farm.apply_changes([[stream[0]]] * 2)
        bad = bytes(faults.garbage(40))
        farm.apply_changes([[bad], []])
        assert 0 in farm.quarantine
        farm.release_quarantine(0)
        patches = farm.apply_changes([[stream[1]], [stream[1]]])
        assert patches.outcomes[0].status == "applied"
        _pages_consistent(farm)
        assert farm.engine.lengths[0] == farm.engine.lengths[1]

    def test_device_fault_frees_delta_pages(self):
        """A failing device dispatch must hand the just-allocated delta
        pages back (engine.apply_batch's exception path)."""
        farm = TpuDocFarm(2, capacity=32, quarantine_threshold=None)
        stream = _stream(2, 8)
        farm.apply_changes([[stream[0]]] * 2)
        _pages_consistent(farm)
        with faults.inject("engine.apply_batch", faults.fail_always()):
            farm.apply_changes([[stream[1]]] * 2)
        # bisect blames nobody (the injected fault fails every probe too),
        # both docs are served by the fallback walk; no pages leaked
        _pages_consistent(farm)


class TestVisibilitySubset:
    def test_get_patch_after_partial_delivery(self):
        farm = TpuDocFarm(4, capacity=32)
        stream = _stream(2, 8)
        farm.apply_changes([[stream[0]]] * 4)
        farm.apply_changes([[stream[1]], [], [], []])
        ref = OpSet()
        ref.apply_changes([stream[0], stream[1]])
        ref_short = OpSet()
        ref_short.apply_changes([stream[0]])
        assert farm.get_patch(0) == ref.get_patch()
        assert farm.get_patch(3) == ref_short.get_patch()
