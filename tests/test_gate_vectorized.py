"""Parity suite for the columnar causal gate + device-emitted patch
columns (ISSUE: retire the last host-Python hot phases).

The columnar gate computes whole-delivery commit verdicts from dep-index
columns (`transcode.gate_verdicts`), commits changes from cached column
blocks, and takes patch-emit verdicts from the device readback
(`rga.patch_emit_columns`). The scalar gate + sequential OpSet walk stay
in-tree as the parity oracle — `gate_mode="oracle"` pins every doc to
them. This suite asserts the two chains are indistinguishable: every
patch BYTE-IDENTICAL (canonical JSON, stricter than dict equality)
across fuzz workloads, the poisoned-byte corpus with quarantine/rollback
interleavings, mid-gate deferrals, device-fault fallback, and anomaly
re-routes — and that a re-routed doc leaves metrics and host caches in
the same state as a scalar-only run.
"""
import json

import numpy as np
import pytest

from automerge_tpu.obs.metrics import enabled_metrics, get_metrics
from automerge_tpu.opset import OpSet
from automerge_tpu.testing import faults
from automerge_tpu.tpu.farm import TpuDocFarm

from test_farm import Workload, make_change

SEEDS = [11, 23, 47]
ROUNDS = 10


def canon(patch):
    return json.dumps(patch, sort_keys=True)


def make_farms(num_docs, capacity=64):
    return (
        TpuDocFarm(num_docs, capacity=capacity, quarantine_threshold=None,
                   gate_mode="columnar"),
        TpuDocFarm(num_docs, capacity=capacity, quarantine_threshold=None,
                   gate_mode="oracle"),
    )


def set_change(actor, seq, start_op, deps, key, value, pred=()):
    ops = [{"action": "set", "obj": "_root", "key": key,
            "datatype": "uint", "value": value, "pred": list(pred)}]
    return make_change(actor, seq, start_op, deps, ops)


def assert_farm_state_equal(columnar, oracle, context=""):
    """The observable state the two gate chains must agree on."""
    for d in range(columnar.num_docs):
        assert columnar.get_heads(d) == oracle.get_heads(d), (context, d)
        assert columnar.get_missing_deps(d) == oracle.get_missing_deps(d), (
            context, d,
        )
        assert canon(columnar.get_patch(d)) == canon(oracle.get_patch(d)), (
            f"{context}: whole-doc patch diverged for doc {d}"
        )


def run_differential(seed, num_docs=3, rounds=ROUNDS, deliver=None,
                     with_oracle_walk=True):
    """One workload through a columnar farm, an oracle farm and per-doc
    OpSet walks, asserting canonical patch equality per delivery."""
    columnar, oracle = make_farms(num_docs)
    walks = [OpSet() for _ in range(num_docs)]
    workload = Workload(seed)
    for r in range(rounds):
        buffers = workload.next_round(walks[0])
        if not buffers:
            continue
        per_doc = [list(buffers) for _ in range(num_docs)]
        if deliver is not None:
            per_doc = deliver(r, per_doc)
        got_c = columnar.apply_changes([list(b) for b in per_doc])
        got_o = oracle.apply_changes([list(b) for b in per_doc])
        for d in range(num_docs):
            assert canon(got_c[d]) == canon(got_o[d]), (
                f"seed={seed} round={r} doc={d}: columnar diverged from "
                f"the scalar gate\ngot:  {canon(got_c[d])}\n"
                f"want: {canon(got_o[d])}"
            )
            if with_oracle_walk:
                want = walks[d].apply_changes(list(per_doc[d]))
                assert canon(got_c[d]) == canon(want), (
                    f"seed={seed} round={r} doc={d}: diverged from OpSet"
                )
    assert_farm_state_equal(columnar, oracle, f"seed={seed}")
    if with_oracle_walk:
        for d in range(num_docs):
            assert canon(columnar.get_patch(d)) == canon(
                walks[d].get_patch()
            )
    return columnar, oracle


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_corpus_gate_parity(seed):
    """Random map-family workloads (concurrent actors, counters, nesting,
    deletes, delayed delivery): columnar gate ≡ scalar gate ≡ OpSet."""
    run_differential(seed)


@pytest.mark.parametrize("name,corrupt,kind", faults.BYTE_CORPUS,
                         ids=[c[0] for c in faults.BYTE_CORPUS])
def test_byte_corpus_quarantine_parity(name, corrupt, kind):
    """A poisoned delivery mid-stream quarantines/rolls back identically
    on both gate chains, and every subsequent clean delivery stays
    byte-identical (a stale mirror after a columnar rollback would
    diverge here)."""
    poison_round, poison_doc = 3, 1

    def deliver(r, per_doc):
        if r == poison_round and per_doc[poison_doc]:
            per_doc[poison_doc] = [
                bytes(corrupt(buf)) for buf in per_doc[poison_doc]
            ]
        return per_doc

    # corrupted buffers diverge from the OpSet contract (the walk raises
    # where the farm quarantines), so compare the two farms only
    run_differential(7, deliver=deliver, with_oracle_walk=False)


def test_mid_gate_deferral_ready_next_delivery():
    """A change whose dep is still unknown gets verdict 0 (deferred,
    queued); the next delivery carrying the dep commits BOTH in causal
    order — on each chain, with identical patches at each step."""
    buf_a, h_a = set_change("aaaaaaaa", 1, 1, [], "x", 1)
    buf_b, _h_b = set_change("aaaaaaaa", 2, 2, [h_a], "x", 2,
                             pred=["1@aaaaaaaa"])
    columnar, oracle = make_farms(1)
    walk = OpSet()

    want_defer = walk.apply_changes([buf_b])
    (got_c,) = columnar.apply_changes([[buf_b]])
    (got_o,) = oracle.apply_changes([[buf_b]])
    assert canon(got_c) == canon(got_o) == canon(want_defer)
    assert columnar.get_missing_deps(0) == [h_a]

    want_both = walk.apply_changes([buf_a])
    (got_c,) = columnar.apply_changes([[buf_a]])
    (got_o,) = oracle.apply_changes([[buf_a]])
    assert canon(got_c) == canon(got_o) == canon(want_both)
    assert columnar.get_missing_deps(0) == []
    assert_farm_state_equal(columnar, oracle, "deferral")


def test_deferral_across_interleaved_deliveries():
    """Partial deferral: one ready change commits while its delivery-mate
    stays queued; parity holds through the delivery that releases it."""
    buf_a, h_a = set_change("aaaaaaaa", 1, 1, [], "x", 1)
    buf_b, h_b = set_change("bbbbbbbb", 1, 2, [h_a], "y", 2)
    buf_c, _ = set_change("bbbbbbbb", 2, 3, [h_b], "y", 3,
                          pred=["2@bbbbbbbb"])
    columnar, oracle = make_farms(1)
    walk = OpSet()
    for delivery in ([buf_b, buf_c], [buf_a]):
        want = walk.apply_changes(list(delivery))
        (got_c,) = columnar.apply_changes([list(delivery)])
        (got_o,) = oracle.apply_changes([list(delivery)])
        assert canon(got_c) == canon(got_o) == canon(want)
    assert_farm_state_equal(columnar, oracle, "partial deferral")


def test_device_fault_fallback_parity():
    """The device path failing for one doc mid-dispatch must degrade to
    the sequential walk with identical patches on both chains, and the
    doc must rejoin the device path cleanly afterwards."""
    seed = 13

    def run(mode):
        farm = TpuDocFarm(3, capacity=64, quarantine_threshold=None,
                          gate_mode=mode)
        walks = [OpSet() for _ in range(3)]
        workload = Workload(seed)
        out = []
        for r in range(ROUNDS):
            buffers = workload.next_round(walks[0])
            if not buffers:
                continue
            per_doc = [list(buffers) for _ in range(3)]
            if r == 4:
                with faults.inject("farm.device_dispatch",
                                   faults.fail_docs([2])):
                    patches = farm.apply_changes(per_doc)
            else:
                patches = farm.apply_changes(per_doc)
            out.append([canon(p) for p in patches])
        out.append([canon(farm.get_patch(d)) for d in range(3)])
        return out

    assert run("columnar") == run("oracle")


def _metric_state(reg):
    """Metric snapshot minus the chain-routing counters themselves (the
    columnar run legitimately counts its own re-routes) and the counters
    that track process-global caches (decode LRU, jit cache), whose
    hit/miss split depends on which run went first."""
    skip = {
        "farm.gate.vector_changes", "farm.gate.oracle_docs",
        "farm.transcode.oracle_docs", "farm.patch.device_columns",
    }
    out = {}
    for name, snap in reg.as_dict().items():
        if name in skip or snap["type"] == "histogram":
            continue
        if "decode" in name or "jit" in name or name.startswith("codecs."):
            continue
        out[name] = snap["value"]
    return out


def _cache_state(farm):
    """The host caches whose staleness would silently corrupt later
    patches: the row mirror and the visibility cache."""
    state = []
    for d in range(farm.num_docs):
        state.append((
            farm._vis_mkey[d].tolist(),
            farm._vis_visible[d].tolist(),
            farm._vis_total[d].tolist(),
            sorted(farm._vis_stale[d]),
            bool(farm._vis_all_stale[d]),
            [c["hash"] for c in farm.queue[d]],
        ))
    return state


def test_oracle_reroute_matches_scalar_only_run():
    """An in-delivery duplicate hash re-routes the doc through the scalar
    chain pre-verdict; the re-routed run must leave patches, metrics and
    host caches in the SAME state as a farm pinned to the scalar chain
    for the whole run."""
    buf_a, h_a = set_change("aaaaaaaa", 1, 1, [], "x", 1)
    buf_b, _ = set_change("aaaaaaaa", 2, 2, [h_a], "y", 2)

    def run(mode):
        reg = get_metrics()
        reg.reset()
        with enabled_metrics():
            farm = TpuDocFarm(1, capacity=32, quarantine_threshold=None,
                              gate_mode=mode)
            (p1,) = farm.apply_changes([[buf_a]])
            # duplicate within ONE delivery: the oracle owns dedup order
            (p2,) = farm.apply_changes([[buf_b, buf_b]])
        return farm, [canon(p1), canon(p2)], _metric_state(reg)

    farm_c, patches_c, metrics_c = run("columnar")
    farm_o, patches_o, metrics_o = run("oracle")
    assert patches_c == patches_o
    assert metrics_c == metrics_o
    assert _cache_state(farm_c) == _cache_state(farm_o)
    assert_farm_state_equal(farm_c, farm_o, "dup re-route")


def test_seq_anomaly_reroutes_to_canonical_error():
    """A seq-contiguity violation fails columnar commit validation and
    re-routes pre-mutation: the scalar chain raises the canonical
    CausalityError, and both chains quarantine identically."""
    buf_a, h_a = set_change("aaaaaaaa", 1, 1, [], "x", 1)
    # seq jumps 1 -> 3: causally impossible, deps satisfied
    buf_bad, _ = set_change("aaaaaaaa", 3, 2, [h_a], "y", 2)
    columnar, oracle = make_farms(1)
    for farm in (columnar, oracle):
        farm.apply_changes([[buf_a]])
        result = farm.apply_changes([[buf_bad]])
        (outcome,) = result.outcomes
        assert outcome.status == "quarantined"
        assert outcome.error_kind == "causality"
    assert_farm_state_equal(columnar, oracle, "seq anomaly")


def test_reroute_then_columnar_again():
    """A doc that re-routed through the oracle one delivery must ride the
    columnar path again on the next clean delivery, with parity."""
    buf_a, h_a = set_change("aaaaaaaa", 1, 1, [], "x", 1)
    buf_b, h_b = set_change("aaaaaaaa", 2, 2, [h_a], "y", 2)
    buf_c, _ = set_change("aaaaaaaa", 3, 3, [h_b], "z", 3)
    columnar, oracle = make_farms(1)
    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        for delivery in ([buf_a, buf_a], [buf_b], [buf_c]):
            (got_c,) = columnar.apply_changes([list(delivery)])
            (got_o,) = oracle.apply_changes([list(delivery)])
            assert canon(got_c) == canon(got_o)
    snap = reg.as_dict()
    assert snap["farm.gate.oracle_docs"]["value"] == 1  # the dup delivery
    assert snap["farm.gate.vector_changes"]["value"] == 2  # b and c
    assert_farm_state_equal(columnar, oracle, "re-route recovery")


def test_rollback_scopes_mirror_invalidation():
    """Regression (satellite): `_restore_doc` must invalidate only the
    spans the failed delivery actually touched — not the whole doc. The
    recovery delivery's scoped readback transfers rows for the touched
    slots only, pinned via farm.readback.rows."""
    # doc 1 rides along healthy so the dispatch-fault bisect convicts doc
    # 0 instead of declaring the device itself down (which would serve
    # everyone through the fallback walk, no rollback)
    farm = TpuDocFarm(2, capacity=64, quarantine_threshold=None)
    walk = OpSet()
    # six committed rounds -> six live single-row slots, mirror warm
    deps, seq, start = [], 1, 1
    for r in range(6):
        buf, h = set_change("aaaaaaaa", seq, start, deps, f"k{r}", r)
        farm.apply_changes([[buf], [buf]])
        walk.apply_changes([buf])
        deps, seq, start = [h], seq + 1, start + 1
    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        # a delivery that transcodes k6 rows, then dies at dispatch:
        # the quarantine rollback must mark ONLY k6's slot stale
        buf_bad, _ = set_change("aaaaaaaa", seq, start, deps, "k6", 99)
        with faults.inject("farm.device_dispatch", faults.fail_docs([0])):
            result = farm.apply_changes([[buf_bad], [buf_bad]])
        assert result.outcomes[0].status == "quarantined"
        reg.reset()  # count the RECOVERY delivery's readback only
        # recovery: a clean delivery touching one NEW slot (k7)
        buf_ok, _ = set_change("aaaaaaaa", seq, start, deps, "k7", 7)
        got = farm.apply_changes([[buf_ok], []])[0]
    want = walk.apply_changes([buf_ok])
    assert canon(got) == canon(want)
    rows = reg.as_dict()["farm.readback.rows"]["value"]
    # k6's slot re-reads empty (rolled back), k7 contributes its one new
    # row: whole-doc invalidation would re-read all seven live rows here
    assert rows <= 2, (
        f"scoped rollback invalidation regressed: the recovery readback "
        f"transferred {rows} rows (whole-doc would be ~7)"
    )
    # the untouched slots' cached visibility still serves get_patch
    assert canon(farm.get_patch(0)) == canon(walk.get_patch())


def test_gate_verdict_columns_order_matches_append_order():
    """Commit order from the verdict columns (stable argsort of batch
    numbers) must equal the scalar gate's append order for a dep chain
    delivered shuffled in one delivery."""
    bufs, deps, hashes = [], [], []
    seq, start = 1, 1
    for i in range(5):
        buf, h = set_change("aaaaaaaa", seq, start, deps, "x", i,
                            pred=[f"{start - 1}@aaaaaaaa"] if i else ())
        bufs.append(buf)
        deps, seq, start = [h], seq + 1, start + 1
        hashes.append(h)
    shuffled = [bufs[3], bufs[0], bufs[4], bufs[2], bufs[1]]
    columnar, oracle = make_farms(1)
    walk = OpSet()
    want = walk.apply_changes(list(shuffled))
    (got_c,) = columnar.apply_changes([list(shuffled)])
    (got_o,) = oracle.apply_changes([list(shuffled)])
    assert canon(got_c) == canon(got_o) == canon(want)
    assert columnar.get_heads(0) == oracle.get_heads(0) == [hashes[-1]]
