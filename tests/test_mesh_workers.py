"""Worker supervision, crash recovery and controller-policy tests for
``mesh_backend="process"`` (parallel/workers.py + meshfarm.py).

The crash tests use ``inject_worker_fault`` — the chaos hook that makes
one worker SIGKILL itself, indistinguishable from an external kill -9 —
and pin the full recovery contract: the mesh keeps serving, survivors'
patches stay byte-identical to the inline oracle, the in-flight docs
land in quarantine under ``WorkerCrashError`` (kind "worker_crash"),
and after ``release_quarantine`` + re-delivery the recovered docs
converge to the oracle too (the respawned worker was re-hydrated from
the controller's delivery log).

The PR 19 additions pin the zero-copy shm data plane end-to-end:
transport patch parity (shm byte-identical to the pickle oracle and the
inline farm, including a mid-delivery migration), SIGKILL while slots
are held (generation-counter reclaim, remap metering, convergence), the
payload/control pipe-accounting split, and zero leaked ``/dev/shm``
segments after clean shutdown AND after crash-respawn cycles.
"""
import json
import multiprocessing
import os
import time

import pytest

from automerge_tpu.errors import WorkerCrashError, error_kind
from automerge_tpu.opset import OpSet
from automerge_tpu.parallel.meshfarm import MeshFarm
from test_farm import Workload

NUM_DOCS = 8
NUM_SHARDS = 2
ROUNDS = 6
CRASH_ROUND = 2


def _rounds(seed=3, rounds=ROUNDS):
    gen = OpSet()
    w = Workload(seed)
    return [r for r in (w.next_round(gen) for _ in range(rounds)) if r]


def _final_patches(mesh):
    return [
        json.dumps(mesh.get_patch(d), sort_keys=True)
        for d in range(NUM_DOCS)
    ]


def _drive_inline(deliveries):
    mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                    mesh_backend="inline")
    try:
        for buffers in deliveries:
            mesh.apply_changes(
                [list(buffers) for _ in range(NUM_DOCS)], isolation="doc"
            )
        return _final_patches(mesh)
    finally:
        mesh.close()


def test_worker_crash_mid_delivery_recovers_to_oracle():
    deliveries = _rounds()
    oracle = _drive_inline(deliveries)
    mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                    mesh_backend="process")
    try:
        for r, buffers in enumerate(deliveries):
            per_doc = [list(buffers) for _ in range(NUM_DOCS)]
            if r == CRASH_ROUND:
                mesh.inject_worker_fault(1, when="next_apply")
            res = mesh.apply_changes(per_doc, isolation="doc")
            if r != CRASH_ROUND:
                assert not res.quarantined
                continue
            # the delivery the worker died under: every shard-1 doc was
            # in flight and is quarantined under the crash taxonomy...
            q = res.quarantined
            assert sorted(q) == sorted(
                d for d in range(NUM_DOCS) if mesh.shard_of(d) == 1
            )
            for outcome in q.values():
                assert isinstance(outcome.error, WorkerCrashError)
                assert error_kind(outcome.error) == "worker_crash"
            assert set(q) == set(mesh.quarantine)
            # ...while shard 0's docs applied as if nothing happened
            for d in range(NUM_DOCS):
                if d not in q:
                    assert res.outcomes[d].status == "applied"
            # release + re-deliver the lost round: the respawned worker
            # was re-hydrated from the delivery log, so this converges
            assert sorted(mesh.release_quarantine()) == sorted(q)
            redo = [per_doc[d] if d in q else [] for d in range(NUM_DOCS)]
            redo_res = mesh.apply_changes(redo, isolation="doc")
            assert all(o.status == "applied" for o in redo_res.outcomes)
        assert _final_patches(mesh) == oracle
        mesh.audit()
    finally:
        mesh.close()
    assert multiprocessing.active_children() == []


def test_heartbeat_detects_and_respawns_dead_worker():
    mesh = MeshFarm(4, num_shards=NUM_SHARDS, capacity=16,
                    mesh_backend="process")
    try:
        assert mesh.heartbeat() == {0: "ok", 1: "ok"}
        mesh.inject_worker_fault(0, when="now")
        deadline = time.monotonic() + 10.0
        while mesh._handles[0].alive and time.monotonic() < deadline:
            time.sleep(0.05)
        assert mesh.heartbeat() == {0: "respawned", 1: "ok"}
        assert mesh.heartbeat() == {0: "ok", 1: "ok"}
    finally:
        mesh.close()
    assert multiprocessing.active_children() == []


def test_migration_and_rebalance_over_the_pipe_match_inline():
    def drive(backend):
        mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                        mesh_backend=backend)
        try:
            for r, buffers in enumerate(_rounds(seed=5)):
                mesh.apply_changes(
                    [list(buffers) for _ in range(NUM_DOCS)],
                    isolation="doc",
                )
                if r == 2:
                    d = next(x for x in range(NUM_DOCS)
                             if mesh.shard_of(x) == 0)
                    mesh.migrate_doc(d, 1)
                    mesh.audit()
            mid = _final_patches(mesh)
            mesh.rebalance(max_moves=1, min_gain_pages=0)
            mesh.audit()
            return mid, _final_patches(mesh)
        finally:
            mesh.close()

    assert drive("inline") == drive("process")
    assert multiprocessing.active_children() == []


def test_dispatch_shards_reraises_first_shard_error_after_draining():
    """The satellite regression: a mid-dispatch shard exception must
    neither deadlock the pool nor abandon other shards' results, and the
    FIRST failing shard (lowest id) surfaces with its id attached."""
    import os
    os.environ["AM_MESH_CONCURRENCY"] = "4"
    try:
        mesh = MeshFarm(9, num_shards=3, capacity=16, mesh_backend="inline")
    finally:
        del os.environ["AM_MESH_CONCURRENCY"]
    try:
        assert mesh._executor is not None
        done = []

        def fn(s):
            done.append(s)
            if s in (1, 2):
                raise RuntimeError(f"boom shard {s}")
            return s * 10

        with pytest.raises(RuntimeError) as ei:
            mesh._dispatch_shards([0, 1, 2], fn)
        assert ei.value.shard == 1
        assert ei.value.args[0].startswith("[shard 1]")
        assert sorted(done) == [0, 1, 2]  # every future drained

        # serial path (no pool): same drain-and-attribute contract
        mesh._executor.shutdown(wait=True)
        mesh._executor = None
        done.clear()
        with pytest.raises(RuntimeError) as ei:
            mesh._dispatch_shards([0, 1, 2], fn)
        assert ei.value.shard == 1
        assert ei.value.args[0].startswith("[shard 1]")
        assert sorted(done) == [0, 1, 2]
    finally:
        mesh.close()


def test_quarantine_reads_are_rpc_free_on_process_backend():
    """The serve batcher checks ``farm.quarantine`` on EVERY submit
    (serve/batcher.py admission), so the process controller must answer
    from its local mirror without a worker round trip."""
    mesh = MeshFarm(4, num_shards=NUM_SHARDS, capacity=16,
                    mesh_backend="process")
    try:
        calls = []
        for h in mesh._handles:
            orig = h.call
            h.call = (lambda orig: lambda *a, **k: (
                calls.append(a[0]), orig(*a, **k))[1])(orig)
        for _ in range(50):
            assert mesh.quarantine == {}
        assert calls == []
    finally:
        mesh.close()
    assert multiprocessing.active_children() == []


def test_worker_crash_flight_dump_contains_blackbox_forensics(tmp_path):
    """The ISSUE 13 acceptance shape: SIGKILL a worker mid-delivery with
    the flight plane on — the controller's ``mesh.worker.crash`` auto-dump
    must contain the dead worker's shard-tagged pre-crash events (live
    shipped over the pipe, topped up from its black-box file) alongside
    the crash entry with its forensic fields."""
    from automerge_tpu.obs.flight import enabled_flight, load_jsonl

    deliveries = _rounds(rounds=2)
    with enabled_flight(dump_dir=str(tmp_path)) as rec:
        rec.clear()
        mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                        mesh_backend="process")
        try:
            # round 0 runs clean: the workers compile, record shard-tagged
            # flight events and ship them live with the result frame
            mesh.apply_changes(
                [list(deliveries[0]) for _ in range(NUM_DOCS)],
                isolation="doc",
            )
            assert any(e.get("shard") == 1 for e in rec.snapshot()), \
                "round 0 shipped no shard-1 worker events"
            # the worker flushes its black box AFTER sending the result
            # frame; a heartbeat round trip sequences behind that flush
            # (the worker is single-threaded)
            assert mesh.heartbeat() == {0: "ok", 1: "ok"}
            bb_path = mesh._handles[1].spec["blackbox_path"]
            assert os.path.exists(bb_path), "worker wrote no black box"
            mesh.inject_worker_fault(1, when="next_apply")
            res = mesh.apply_changes(
                [list(deliveries[1]) for _ in range(NUM_DOCS)],
                isolation="doc",
            )
            assert res.quarantined
        finally:
            mesh.close()
    assert multiprocessing.active_children() == []
    assert rec.dump_paths, "the crash did not auto-dump the timeline"
    events = load_jsonl(open(rec.dump_paths[-1], encoding="utf-8").read())
    crashes = [e for e in events if e["event"] == "mesh.worker.crash"]
    assert crashes, [e["event"] for e in events]
    fields = crashes[-1]["fields"]
    assert fields["shard"] == 1
    assert isinstance(fields["pid"], int) and fields["pid"] > 0
    assert fields["phase"] == "apply"
    assert "heartbeat_age_s" in fields
    assert fields["blackbox"] == bb_path      # S2: recovered file path
    assert fields["blackbox_events"] >= 0
    # the dead worker's own events sit in the same dump, shard-tagged and
    # ordered before the crash entry
    worker_events = [e for e in events
                     if e.get("shard") == 1
                     and e["event"] != "mesh.worker.crash"]
    assert worker_events, "no shard-1 pre-crash events in the crash dump"
    crash_idx = events.index(crashes[-1])
    assert events.index(worker_events[0]) < crash_idx
    # the inline backend, fed the same rounds, produces an untagged
    # single-process dump: byte-identical to the pre-mesh shape
    with enabled_flight() as rec2:
        rec2.clear()
        _drive_inline(deliveries)
        assert all("shard" not in e for e in rec2.snapshot())
    assert multiprocessing.active_children() == []


def test_worker_exemplar_resolves_to_controller_span():
    """The ISSUE 13 trace-propagation acceptance: a latency exemplar
    recorded inside a process-mode worker (``farm.dispatch.latency_ms``)
    resolves to the controller-side dispatch span id in ONE lookup — the
    span id travels in the fan-out payload, the worker stamps it, and the
    shipped metric delta carries it back."""
    from automerge_tpu.obs.metrics import enabled_metrics
    from automerge_tpu.obs.scope import dispatch_context, get_amscope

    deliveries = _rounds(rounds=1)
    with enabled_metrics() as reg:
        reg.reset()
        mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                        mesh_backend="process")
        try:
            span = get_amscope().begin_dispatch([], 0.0)
            with dispatch_context(span):
                mesh.apply_changes(
                    [list(deliveries[0]) for _ in range(NUM_DOCS)],
                    isolation="doc",
                )
            hist = reg.find("farm.dispatch.latency_ms")
            assert hist is not None and hist.count > 0, \
                "no worker-side dispatch observations merged back"
            # one lookup: the p99 bucket's exemplar IS the controller span
            assert hist.exemplar_for(0.99) == span.dispatch_id
        finally:
            mesh.close()
    assert multiprocessing.active_children() == []


def _shm_segments():
    import glob
    return glob.glob("/dev/shm/am-*")


def test_shm_patch_parity_with_pickle_oracle_and_inline():
    """PR 19 acceptance: shm-transport patches are byte-for-byte the
    pickle oracle's (and the inline farm's), including a mid-delivery
    migration — the rings change how bytes move, never what they say."""
    deliveries = _rounds(seed=7)
    inline = _drive_inline(deliveries)

    def drive(transport):
        mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                        mesh_backend="process", mesh_transport=transport)
        try:
            assert mesh.transport == transport
            for r, buffers in enumerate(_rounds(seed=7)):
                mesh.apply_changes(
                    [list(buffers) for _ in range(NUM_DOCS)],
                    isolation="doc",
                )
                if r == 1:
                    d = next(x for x in range(NUM_DOCS)
                             if mesh.shard_of(x) == 0)
                    mesh.migrate_doc(d, 1)
                    mesh.audit()
            return _final_patches(mesh)
        finally:
            mesh.close()

    shm_patches = drive("shm")
    assert shm_patches == drive("pickle")
    assert shm_patches == inline
    assert _shm_segments() == []
    assert multiprocessing.active_children() == []
    assert deliveries  # the workload generator produced real rounds


def test_worker_sigkill_while_holding_slot_reclaims_and_remaps():
    """The PR 19 satellite: SIGKILL a worker mid-apply under the shm
    transport — the dead worker's held ring slots reclaim via the
    generation counter (no deadlock on later acquires), the in-flight
    docs quarantine, the respawned worker remaps the SAME segments
    (``mesh.shm.remaps`` + a ``mesh.shm.remap`` flight event with plain
    int fields — the PR 14 np.int64 pin), and re-delivery converges to
    the inline oracle."""
    from automerge_tpu.obs.flight import enabled_flight
    from automerge_tpu.obs.metrics import enabled_metrics

    deliveries = _rounds()
    oracle = _drive_inline(deliveries)
    with enabled_metrics() as reg, enabled_flight() as rec:
        reg.reset()
        rec.clear()
        mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                        mesh_backend="process", mesh_transport="shm")
        try:
            assert mesh.transport == "shm"
            assert len(_shm_segments()) == 2 * NUM_SHARDS
            assert reg.as_dict()["mesh.shm.segments"]["value"] \
                == 2 * NUM_SHARDS
            for r, buffers in enumerate(deliveries):
                per_doc = [list(buffers) for _ in range(NUM_DOCS)]
                if r == CRASH_ROUND:
                    mesh.inject_worker_fault(1, when="next_apply")
                res = mesh.apply_changes(per_doc, isolation="doc")
                if r != CRASH_ROUND:
                    assert not res.quarantined
                    continue
                q = res.quarantined
                assert sorted(q) == sorted(
                    d for d in range(NUM_DOCS) if mesh.shard_of(d) == 1
                )
                for outcome in q.values():
                    assert isinstance(outcome.error, WorkerCrashError)
                    assert error_kind(outcome.error) == "worker_crash"
                # the crash-reclaim freed the dead worker's send-ring
                # slots — nothing held, nothing deadlocked
                send_ring, _result_ring = mesh._rings[1]
                assert send_ring.slots_in_use() == 0
                assert sorted(mesh.release_quarantine()) == sorted(q)
                redo = [per_doc[d] if d in q else []
                        for d in range(NUM_DOCS)]
                redo_res = mesh.apply_changes(redo, isolation="doc")
                assert all(o.status == "applied"
                           for o in redo_res.outcomes)
            assert _final_patches(mesh) == oracle
            snap = reg.as_dict()
            assert snap["mesh.shm.remaps"]["value"] >= 1
            remaps = [e for e in rec.snapshot()
                      if e["event"] == "mesh.shm.remap"]
            assert remaps, "respawn recorded no mesh.shm.remap event"
            fields = remaps[-1]["fields"]
            assert fields["shard"] == 1
            for key in ("shard", "epoch", "freed_slots"):
                assert type(fields[key]) is int, (key, fields[key])
            json.dumps(fields)  # JSONL-safe: no np.int64 leaks
        finally:
            mesh.close()
        # clean shutdown unlinked every segment, gauge agrees
        assert reg.as_dict()["mesh.shm.segments"]["value"] == 0
    assert _shm_segments() == []
    assert multiprocessing.active_children() == []


def test_pipe_payload_control_split_by_transport():
    """The PR 19 satellite: ``mesh.pipe.<s>.serialize_ms`` aggregate
    gets a payload/control breakdown. Under the pickle oracle the apply
    batches and result frames classify as payload; under shm the payload
    legs sit at exactly zero — every remaining pipe frame is control."""
    from automerge_tpu.obs.metrics import enabled_metrics

    deliveries = _rounds(rounds=2)

    def split(transport):
        with enabled_metrics() as reg:
            reg.reset()
            mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                            mesh_backend="process",
                            mesh_transport=transport)
            try:
                for buffers in deliveries:
                    mesh.apply_changes(
                        [list(buffers) for _ in range(NUM_DOCS)],
                        isolation="doc",
                    )
                snap = reg.as_dict()
            finally:
                mesh.close()

        def total(suffix, field):
            return sum(
                snap.get(f"mesh.pipe.{s}.{suffix}", {}).get(field, 0)
                for s in range(NUM_SHARDS)
            )

        return {
            "payload_frames": total("payload_ms", "count"),
            "payload_bytes": total("payload_bytes", "value"),
            "control_frames": total("control_ms", "count"),
            "control_bytes": total("control_bytes", "value"),
        }

    p = split("pickle")
    assert p["payload_frames"] > 0 and p["payload_bytes"] > 0
    assert p["control_frames"] > 0 and p["control_bytes"] > 0
    s = split("shm")
    assert s["payload_frames"] == 0 and s["payload_bytes"] == 0
    assert s["control_frames"] > 0 and s["control_bytes"] > 0
    assert _shm_segments() == []
    assert multiprocessing.active_children() == []


def test_mesh_transport_resolution():
    """``mesh_transport=None`` reads AM_MESH_TRANSPORT; non-process
    backends always resolve to pickle (there are no rings to map); an
    unknown value is an API-usage error."""
    old = os.environ.get("AM_MESH_TRANSPORT")
    os.environ["AM_MESH_TRANSPORT"] = "pickle"
    try:
        mesh = MeshFarm(4, num_shards=NUM_SHARDS, capacity=16,
                        mesh_backend="process")
        try:
            assert mesh.transport == "pickle"
            assert _shm_segments() == []  # pickle mode maps no rings
        finally:
            mesh.close()
    finally:
        if old is None:
            os.environ.pop("AM_MESH_TRANSPORT", None)
        else:
            os.environ["AM_MESH_TRANSPORT"] = old
    inline = MeshFarm(4, num_shards=NUM_SHARDS, capacity=16,
                      mesh_backend="inline", mesh_transport="shm")
    try:
        assert inline.transport == "pickle"
    finally:
        inline.close()
    with pytest.raises(ValueError):
        MeshFarm(4, num_shards=NUM_SHARDS, capacity=16,
                 mesh_backend="inline", mesh_transport="bogus")
    assert multiprocessing.active_children() == []


def test_rebalance_policy_hook_is_called_on_interval():
    calls = []
    mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                    mesh_backend="inline",
                    rebalance_policy=calls.append, rebalance_interval=2)
    try:
        gen = OpSet()
        w = Workload(9)
        applied = 0
        while applied < 4:
            buffers = w.next_round(gen)
            if not buffers:
                continue
            mesh.apply_changes(
                [list(buffers) for _ in range(NUM_DOCS)], isolation="doc"
            )
            applied += 1
        assert calls == [mesh, mesh]
    finally:
        mesh.close()
