"""Shared test helpers, modelled on the reference test suite's helpers
(/root/reference/test/helpers.js) and checkColumns
(/root/reference/test/new_backend_test.js:7)."""
from automerge_tpu.columnar import DOC_OPS_COLUMNS, decode_change, encode_change


def hash_of(change):
    return decode_change(encode_change(change))["hash"]


def check_columns(opset, expected):
    """Asserts that the document op columns of `opset` re-encode to exactly
    the expected bytes (column-name -> list of byte values)."""
    actual = {}
    for (name, _cid), (_cid2, buf) in zip(DOC_OPS_COLUMNS, opset._encode_ops_columns()):
        actual[name] = list(buf)
    for name, expected_bytes in expected.items():
        assert actual[name] == expected_bytes, (
            f"{name} column: got {actual[name]}, expected {expected_bytes}"
        )


def assert_equals_one_of(actual, *candidates):
    """The CRDT picks an arbitrary-but-consistent winner among conflicts;
    assert the actual value is one of the permitted outcomes
    (helpers.js:6-16)."""
    for candidate in candidates:
        if actual == candidate:
            return
    raise AssertionError(f"{actual!r} is not one of {candidates!r}")
