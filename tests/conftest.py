import os
import sys

# Force JAX onto a virtual 8-device CPU mesh for sharding tests; the real TPU
# is used only by bench.py. Must be set before jax is imported anywhere, and
# must OVERRIDE any externally-set platform (the driver environment points
# JAX_PLATFORMS at the tunnelled TPU, whose per-shape compiles are far too
# slow for a test suite and whose device lock serialises concurrent runs).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# A sitecustomize hook may have already imported jax AND called
# jax.config.update("jax_platforms", "<tpu>,cpu") during interpreter startup,
# which takes precedence over the env var. Re-update the config so the first
# backend initialisation in this process is CPU-only; otherwise every jnp call
# blocks on the tunnelled-TPU handshake.
if "jax" in sys.modules:
    sys.modules["jax"].config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soak configurations (chaos convergence sweeps); "
        "excluded from the tier-1 run (-m 'not slow')",
    )
