"""Mesh parity suite (ISSUE 10): the doc-sharded MeshFarm must be
OBSERVATIONALLY IDENTICAL to a single TpuDocFarm — byte-for-byte patch
parity (canonical JSON, stricter than dict equality) across the fuzz
corpus, across quarantine/rollback interleavings from the byte-fault
corpus, across mid-delivery page-granular migrations, and with the
periodic actor-table reconcile running mid-workload. The decode-cache
ownership audit rides along: the process-global decode LRUs are shared
by every shard on purpose (they hold actor strings and immutable op
lists, never interner ids), so shards with divergent interner states
must decode a fanned-out buffer once and still produce identical
patches.
"""
import json

import pytest

from automerge_tpu.opset import OpSet
from automerge_tpu.parallel import MeshFarm
from automerge_tpu.testing import faults
from automerge_tpu.tpu.farm import TpuDocFarm

from test_farm import Workload

SEEDS = [11, 23, 47]
ROUNDS = 10
NUM_DOCS = 8
NUM_SHARDS = 3


def canon(patch):
    return json.dumps(patch, sort_keys=True)


def assert_patch_equal(got, want, context=""):
    assert canon(got) == canon(want), (
        f"{context}: mesh patch diverged from the single farm\n"
        f"got:  {canon(got)}\nwant: {canon(want)}"
    )


def run_pair(seed, num_docs=NUM_DOCS, num_shards=NUM_SHARDS, rounds=ROUNDS,
             deliver=None, between_rounds=None, quarantine_threshold=None,
             reconcile_interval=None):
    """Drives one random workload through a MeshFarm and a single
    TpuDocFarm side by side, asserting per-call outcome + patch parity.
    `deliver` rewrites deliveries (fault interleavings); `between_rounds`
    runs controller actions (migration) mid-stream."""
    mesh = MeshFarm(num_docs, num_shards=num_shards, capacity=64,
                    quarantine_threshold=quarantine_threshold,
                    reconcile_interval=reconcile_interval)
    solo = TpuDocFarm(num_docs, capacity=64,
                      quarantine_threshold=quarantine_threshold)
    gen = OpSet()
    workload = Workload(seed)
    for r in range(rounds):
        buffers = workload.next_round(gen)
        if buffers:
            per_doc = [list(buffers) for _ in range(num_docs)]
            if deliver is not None:
                per_doc = deliver(r, per_doc)
            got = mesh.apply_changes(per_doc)
            want = solo.apply_changes(per_doc)
            for d in range(num_docs):
                assert got.outcomes[d].status == want.outcomes[d].status, (
                    f"seed={seed} round={r} doc={d}: outcome diverged "
                    f"({got.outcomes[d]} vs {want.outcomes[d]})"
                )
                assert_patch_equal(
                    got[d], want[d], f"seed={seed} round={r} doc={d}"
                )
            gen.apply_changes(list(buffers))
        if between_rounds is not None:
            between_rounds(r, mesh)
    for d in range(num_docs):
        assert_patch_equal(
            mesh.get_patch(d), solo.get_patch(d),
            f"seed={seed} whole-doc doc={d}",
        )
    return mesh, solo


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_corpus_mesh_matches_single_farm(seed):
    """Random map-family workloads (concurrent actors, counters, nesting,
    deletes, delayed delivery) land byte-identically whether the docs
    live in one farm or are hash-routed across three shard farms."""
    run_pair(seed)


@pytest.mark.parametrize("seed", SEEDS)
def test_mid_delivery_migration_keeps_parity(seed):
    """A doc migrated between shards mid-workload (snapshot -> page-table
    transplant -> release) keeps merging the remaining rounds with
    byte-identical patches: the id translation into the destination
    interners must be lossless."""
    moved = []

    def between_rounds(r, mesh):
        if r == rounds_split:
            src = mesh.shard_of(doc)
            dest = (src + 1) % mesh.num_shards
            mesh.migrate_doc(doc, dest)
            assert mesh.shard_of(doc) == dest != src
            mesh.audit()
            moved.append((src, dest))

    doc, rounds_split = 2, 4
    run_pair(seed, between_rounds=between_rounds)
    assert moved, "the migration round never ran"


@pytest.mark.parametrize("name,corrupt,kind", faults.BYTE_CORPUS)
def test_quarantine_rollback_parity(name, corrupt, kind):
    """A poisoned delivery must quarantine the same doc in the same round
    on both sides, roll its state back identically, and leave every
    later clean delivery byte-identical."""
    poison_round, poison_doc = 3, 1

    def deliver(r, per_doc):
        if r == poison_round and per_doc[poison_doc]:
            per_doc[poison_doc] = [
                bytes(corrupt(buf)) for buf in per_doc[poison_doc]
            ]
        return per_doc

    run_pair(7, deliver=deliver)


def test_quarantined_doc_migrates_with_its_quarantine():
    """Migration must carry the quarantine entry: a shed doc stays shed
    on its new shard, release (run on BOTH farms at the same round
    boundary) returns it to service there, and everything stays
    byte-identical to the single farm through the whole interleaving."""
    poison_doc = 1
    corrupt = faults.BYTE_CORPUS[1][1]  # bit_flipped
    mesh = MeshFarm(NUM_DOCS, num_shards=NUM_SHARDS, capacity=64,
                    quarantine_threshold=1)
    solo = TpuDocFarm(NUM_DOCS, capacity=64, quarantine_threshold=1)
    gen = OpSet()
    workload = Workload(7)
    # poison the first non-empty round >= 2, migrate two non-empty rounds
    # later, release two after that (Workload rounds can be empty)
    stage, stage_round = 0, 0
    for r in range(ROUNDS + 4):
        buffers = workload.next_round(gen)
        if not buffers:
            continue
        stage_round += 1
        per_doc = [list(buffers) for _ in range(NUM_DOCS)]
        if stage == 0 and stage_round >= 2:
            per_doc[poison_doc] = [
                bytes(corrupt(buf)) for buf in per_doc[poison_doc]
            ]
            stage, stage_round = 1, 0
        got = mesh.apply_changes(per_doc)
        want = solo.apply_changes(per_doc)
        for d in range(NUM_DOCS):
            assert got.outcomes[d].status == want.outcomes[d].status, (
                f"round={r} doc={d}: {got.outcomes[d]} vs {want.outcomes[d]}"
            )
            assert_patch_equal(got[d], want[d], f"round={r} doc={d}")
        gen.apply_changes(list(buffers))
        if stage == 1 and stage_round >= 2:
            assert poison_doc in mesh.quarantine
            assert poison_doc in solo.quarantine
            dest = (mesh.shard_of(poison_doc) + 1) % mesh.num_shards
            mesh.migrate_doc(poison_doc, dest)
            assert mesh.shard_of(poison_doc) == dest
            assert poison_doc in mesh.quarantine, (
                "quarantine entry lost in migration"
            )
            mesh.audit()
            stage, stage_round = 2, 0
        elif stage == 2 and stage_round >= 2:
            assert mesh.release_quarantine(doc=poison_doc) == [poison_doc]
            solo.release_quarantine(poison_doc)
            assert poison_doc not in mesh.quarantine
            stage, stage_round = 3, 0
    assert stage == 3, f"interleaving never completed (stage={stage})"
    for d in range(NUM_DOCS):
        assert_patch_equal(
            mesh.get_patch(d), solo.get_patch(d), f"whole-doc doc={d}"
        )


@pytest.mark.parametrize("seed", SEEDS[:2])
def test_reconcile_during_workload_keeps_parity(seed):
    """With reconcile_interval=2 the actor-table reconcile runs every
    other apply — interning foreign actors into every shard mid-stream
    must never change any patch, and the tables converge (a second
    explicit pass syncs zero)."""
    mesh, _ = run_pair(seed, reconcile_interval=2)
    mesh.reconcile_actors()
    assert mesh.reconcile_actors() == 0


def test_decode_cache_shared_across_shards_without_state():
    """The ownership audit pinned as a regression test: shards share the
    process-global decode caches (parses), never interner state. Two
    shards whose interner tables have DIVERGED (different private actors
    interned first) decode one fanned-out buffer once, intern its actor
    at different indices, and still emit byte-identical patches."""
    from automerge_tpu.obs.metrics import enabled_metrics, get_metrics

    mesh = MeshFarm(6, num_shards=2, capacity=32, quarantine_threshold=None)
    by_shard = {}
    for d in range(6):
        by_shard.setdefault(mesh.shard_of(d), []).append(d)
    assert len(by_shard) == 2, "routing degenerated to one shard"
    (s0, docs0), (s1, docs1) = sorted(by_shard.items())

    # diverge the shard interners in CONTENT and SIZE: two private actors
    # delivered to one shard only, a different single one to the other
    priv0a = faults.make_change("dd" * 4, 1, 1, [], [faults.set_op("p", 1)])
    priv0b = faults.make_change("cc" * 4, 1, 1, [], [faults.set_op("q", 3)])
    priv1 = faults.make_change("ee" * 4, 1, 1, [], [faults.set_op("p", 2)])
    delivery = [[] for _ in range(6)]
    delivery[docs0[0]] = [priv0a, priv0b]
    delivery[docs1[0]] = [priv1]
    mesh.apply_changes(delivery)
    f0, f1 = mesh.shards[s0], mesh.shards[s1]
    assert f0.actors.find("dd" * 4) is not None
    assert f1.actors.find("dd" * 4) is None  # tables have genuinely diverged

    # fan ONE buffer to every doc on both shards, decode-counted
    shared = faults.make_change("ff" * 4, 1, 1, [], [faults.set_op("x", 9)])
    reg = get_metrics()
    reg.reset()
    with enabled_metrics():
        result = mesh.apply_changes([[shared]] * 6)
    misses = reg.counter("codecs.decode_cache.misses").value
    hits = reg.counter("codecs.decode_cache.hits").value
    assert misses <= 1, "shards must share the decode parse, not re-miss"
    assert hits >= 5 - misses
    # the shared actor landed at DIFFERENT interner indices per shard
    # (each table already held a different private actor) ...
    assert f0.actors.find("ff" * 4) != f1.actors.find("ff" * 4)
    # ... and the cached entry was not mutated by either shard's intern:
    # the patches are identical across shards for the identical stream
    assert canon(result[docs0[1]]) == canon(result[docs1[1]])
    oracle = OpSet()
    want = oracle.apply_changes([shared])
    for d in (docs0[1], docs1[1]):
        assert_patch_equal(result[d], want, f"shared-buffer doc={d}")
