"""Native C++ codec tests: byte-identity against the pure-Python codecs on
randomized columns (differential, both directions)."""
import random

import numpy as np
import pytest

from automerge_tpu import native
from automerge_tpu.codecs import (
    BooleanDecoder,
    BooleanEncoder,
    DeltaDecoder,
    DeltaEncoder,
    RLEDecoder,
    RLEEncoder,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built (make -C native)"
)


def random_column(rng, n, null_prob=0.3, value_range=1000):
    vals = []
    while len(vals) < n:
        run = rng.randrange(1, 6)
        if rng.random() < null_prob:
            vals += [None] * run
        else:
            vals += [rng.randrange(value_range)] * run
    return vals[:n]


def to_arr(vals):
    return np.array(
        [native.NULL_SENTINEL if v is None else v for v in vals], np.int64
    )


class TestNativeCodecs:
    def test_rle_differential(self):
        rng = random.Random(1)
        for _ in range(100):
            vals = random_column(rng, rng.randrange(0, 60))
            e = RLEEncoder("uint")
            for v in vals:
                e.append_value(v)
            py_bytes = e.buffer
            assert native.rle_encode(to_arr(vals)) == py_bytes
            if py_bytes:
                decoded = native.rle_decode(py_bytes)
                assert list(decoded) == list(to_arr(vals))

    def test_delta_differential(self):
        rng = random.Random(2)
        for _ in range(100):
            vals = random_column(rng, rng.randrange(0, 60), value_range=10**6)
            e = DeltaEncoder()
            for v in vals:
                e.append_value(v)
            py_bytes = e.buffer
            assert native.delta_encode(to_arr(vals)) == py_bytes
            if py_bytes:
                assert list(native.delta_decode(py_bytes)) == list(to_arr(vals))

    def test_bool_differential(self):
        rng = random.Random(3)
        for _ in range(100):
            vals = [rng.random() < 0.5 for _ in range(rng.randrange(0, 60))]
            e = BooleanEncoder()
            for v in vals:
                e.append_value(v)
            py_bytes = e.buffer
            assert native.bool_encode(np.array(vals, np.uint8)) == py_bytes
            assert list(native.bool_decode(py_bytes)) == vals

    def test_signed_rle(self):
        vals = [-5, -5, None, 3, -100000, 7]
        arr = to_arr(vals)
        e = RLEEncoder("int")
        for v in vals:
            e.append_value(v)
        assert native.rle_encode(arr, signed=True) == e.buffer
        assert list(native.rle_decode(e.buffer, signed=True)) == list(arr)

    def test_decode_detects_truncation(self):
        e = RLEEncoder("uint")
        for v in [1, 2, 3, 4, 5]:
            e.append_value(v)
        with pytest.raises(ValueError):
            native.rle_decode(e.buffer[:-1])

    def test_document_save_via_native_matches(self):
        """The full document op-column encode gives identical bytes whether
        the numeric columns are encoded natively or in Python."""
        from automerge_tpu.columnar import encode_change
        from automerge_tpu.opset import OpSet

        actor = "0123456789abcdef"
        change = {"actor": actor, "seq": 1, "startOp": 1, "time": 0, "deps": [], "ops": [
            {"action": "makeList", "obj": "_root", "key": "list", "pred": []},
            {"action": "set", "obj": f"1@{actor}", "elemId": "_head", "insert": True,
             "values": [1, 2, 3, 4], "datatype": "uint", "pred": []},
            {"action": "set", "obj": "_root", "key": "title", "value": "hi", "pred": []},
        ]}
        opset = OpSet()
        opset.apply_changes([encode_change(change)])
        python_cols = opset._encode_ops_columns(force_python=True)
        native_cols = opset._encode_ops_columns()
        assert [(cid, bytes(buf)) for cid, buf in python_cols] == [
            (cid, bytes(buf)) for cid, buf in native_cols
        ]
