"""Differential tests: the batched TPU engine must produce the same visible
document state as the sequential reference-parity OpSet engine (the pattern
of the reference's cross-backend suite, /root/reference/test/wasm.js)."""
import random

import numpy as np
import pytest

import automerge_tpu.tpu as tpu
from automerge_tpu.columnar import encode_change
from automerge_tpu.opset import OpSet


def opset_visible_tree(patch_diff):
    """Materialises the visible tree (winner per prop = max Lamport opId,
    apply_patch.js:33) from an OpSet patch diff — the single oracle for both
    the flat and nested differential suites."""
    def lamport(op_id):
        ctr, actor = op_id.split("@")
        return (int(ctr), actor)

    result = {}
    for key, values in patch_diff.get("props", {}).items():
        if not values:
            continue
        winner = max(values.keys(), key=lamport)
        diff = values[winner]
        if "objectId" in diff:
            result[key] = opset_visible_tree(diff)
        else:
            result[key] = diff.get("value")
    return result


def opset_visible_map(opset):
    """Visible root-map state of the sequential engine."""
    return opset_visible_tree(opset.get_patch()["diffs"])


def run_differential(num_docs, num_rounds, ops_per_round, seed, with_counters=False):
    rng = random.Random(seed)
    actors = ["aaaaaaaa", "bbbbbbbb", "cccccccc"]
    keys = [f"k{i}" for i in range(8)]

    opsets = [OpSet() for _ in range(num_docs)]
    engine = tpu.BatchedMapEngine(num_docs, capacity=64)
    tr = tpu.BatchTranscoder()
    # per-doc bookkeeping: last op per key -> (opId string, counter?) and seq per actor
    last_op = [{} for _ in range(num_docs)]
    seqs = [dict.fromkeys(actors, 0) for _ in range(num_docs)]
    max_ops = [0] * num_docs
    counter_keys = [set() for _ in range(num_docs)]

    for _ in range(num_rounds):
        per_doc_rows = []
        for d in range(num_docs):
            actor = rng.choice(actors)
            seqs[d][actor] += 1
            start_op = max_ops[d] + 1
            ops = []
            for i in range(rng.randrange(1, ops_per_round + 1)):
                key = rng.choice(keys)
                prev = last_op[d].get(key)
                if with_counters and prev and prev[1] == "counter" and rng.random() < 0.5:
                    op = {"action": "inc", "obj": "_root", "key": key,
                          "value": rng.randrange(1, 10), "pred": [prev[0]]}
                elif with_counters and prev is None and rng.random() < 0.3:
                    op = {"action": "set", "obj": "_root", "key": key, "datatype": "counter",
                          "value": rng.randrange(100), "pred": []}
                else:
                    if prev and prev[1] == "counter":
                        continue  # counters cannot be overwritten by plain sets here
                    op = {"action": "set", "obj": "_root", "key": key,
                          "datatype": "uint", "value": rng.randrange(1000),
                          "pred": [prev[0]] if prev else []}
                ops.append(op)
            # fix op ids and bookkeeping
            change = {"actor": actor, "seq": seqs[d][actor], "startOp": start_op,
                      "time": 0, "deps": opsets[d].heads, "ops": ops}
            rows = []
            ctr = start_op
            for op in ops:
                if op["action"] == "set":
                    datatype = op.get("datatype")
                    last_op[d][op["key"]] = (f"{ctr}@{actor}", "counter" if datatype == "counter" else "plain")
                    if datatype == "counter":
                        counter_keys[d].add(tr.slot_id("_root", op["key"]))
                rows.append((op, ctr, actor))
                ctr += 1
            max_ops[d] = ctr - 1
            opsets[d].apply_changes([encode_change(change)])
            per_doc_rows.append(rows)

        engine.apply_batch(tr.changes_to_batch(per_doc_rows))

    keys, ops, _visible, winners, values = engine.visible_state()
    for d in range(num_docs):
        expected = opset_visible_map(opsets[d])
        actual = tr.decode_visible(
            keys[d], ops[d], winners[d], values[d], counter_keys[d]
        )
        assert actual == expected, f"doc {d}: {actual} != {expected}"


class TestBatchedMapEngine:
    def test_basic_set_and_overwrite(self):
        engine = tpu.BatchedMapEngine(2, capacity=16)
        tr = tpu.BatchTranscoder()
        batch = tr.changes_to_batch([
            [({"action": "set", "obj": "_root", "key": "x", "value": 1, "pred": []}, 1, "aaaaaaaa"),
             ({"action": "set", "obj": "_root", "key": "y", "value": 2, "pred": []}, 2, "aaaaaaaa")],
            [({"action": "set", "obj": "_root", "key": "x", "value": 9, "pred": []}, 1, "bbbbbbbb")],
        ])
        engine.apply_batch(batch)
        batch2 = tr.changes_to_batch([
            [({"action": "set", "obj": "_root", "key": "x", "value": 5,
               "pred": ["1@aaaaaaaa"]}, 3, "aaaaaaaa")],
            [],
        ])
        engine.apply_batch(batch2)
        keys, ops, _visible, winners, values = engine.visible_state()
        doc0 = tr.decode_visible(keys[0], ops[0], winners[0], values[0])
        doc1 = tr.decode_visible(keys[1], ops[1], winners[1], values[1])
        assert doc0 == {"x": 5, "y": 2}
        assert doc1 == {"x": 9}

    def test_concurrent_conflict_max_opid_wins(self):
        engine = tpu.BatchedMapEngine(1, capacity=16)
        tr = tpu.BatchTranscoder()
        engine.apply_batch(tr.changes_to_batch([
            [({"action": "set", "obj": "_root", "key": "k", "value": "a", "pred": []}, 1, "aaaaaaaa"),
             ({"action": "set", "obj": "_root", "key": "k", "value": "b", "pred": []}, 1, "bbbbbbbb")],
        ]))
        keys, ops, _visible, winners, values = engine.visible_state()
        doc = tr.decode_visible(keys[0], ops[0], winners[0], values[0])
        assert doc == {"k": "b"}  # same counter, higher actor wins

    def test_delete(self):
        engine = tpu.BatchedMapEngine(1, capacity=16)
        tr = tpu.BatchTranscoder()
        engine.apply_batch(tr.changes_to_batch([
            [({"action": "set", "obj": "_root", "key": "k", "value": 1, "pred": []}, 1, "aaaaaaaa")],
        ]))
        engine.apply_batch(tr.changes_to_batch([
            [({"action": "del", "obj": "_root", "key": "k", "pred": ["1@aaaaaaaa"]}, 2, "aaaaaaaa")],
        ]))
        keys, ops, _visible, winners, values = engine.visible_state()
        doc = tr.decode_visible(keys[0], ops[0], winners[0], values[0])
        assert doc == {}

    def test_counter_increments(self):
        engine = tpu.BatchedMapEngine(1, capacity=16)
        tr = tpu.BatchTranscoder()
        engine.apply_batch(tr.changes_to_batch([
            [({"action": "set", "obj": "_root", "key": "c", "datatype": "counter",
               "value": 10, "pred": []}, 1, "aaaaaaaa")],
        ]))
        engine.apply_batch(tr.changes_to_batch([
            [({"action": "inc", "obj": "_root", "key": "c", "value": 3,
               "pred": ["1@aaaaaaaa"]}, 2, "aaaaaaaa"),
             ({"action": "inc", "obj": "_root", "key": "c", "value": 4,
               "pred": ["1@aaaaaaaa"]}, 2, "bbbbbbbb")],
        ]))
        ck = {tr.slot_id("_root", "c")}
        keys, ops, _visible, winners, values = engine.visible_state()
        doc = tr.decode_visible(keys[0], ops[0], winners[0], values[0], ck)
        assert doc == {"c": 17}

    def test_differential_vs_opset(self):
        run_differential(num_docs=4, num_rounds=6, ops_per_round=4, seed=42)

    def test_differential_with_counters(self):
        run_differential(num_docs=3, num_rounds=5, ops_per_round=3, seed=7, with_counters=True)


class TestNestedObjects:
    def test_make_map_and_set_inside(self):
        engine = tpu.BatchedMapEngine(1, capacity=16)
        tr = tpu.BatchTranscoder()
        engine.apply_batch(tr.changes_to_batch([
            [({"action": "makeMap", "obj": "_root", "key": "child", "pred": []}, 1, "aaaaaaaa"),
             ({"action": "set", "obj": "1@aaaaaaaa", "key": "x", "value": 7, "pred": []}, 2, "aaaaaaaa")],
        ]))
        keys, ops, _visible, winners, values = engine.visible_state()
        doc = tr.decode_visible(keys[0], ops[0], winners[0], values[0])
        assert doc == {"child": {"x": 7}}

    def test_overwriting_child_ref_hides_subtree(self):
        engine = tpu.BatchedMapEngine(1, capacity=16)
        tr = tpu.BatchTranscoder()
        engine.apply_batch(tr.changes_to_batch([
            [({"action": "makeMap", "obj": "_root", "key": "c", "pred": []}, 1, "aaaaaaaa"),
             ({"action": "set", "obj": "1@aaaaaaaa", "key": "x", "value": 1, "pred": []}, 2, "aaaaaaaa"),
             ({"action": "set", "obj": "_root", "key": "c", "value": "gone",
               "pred": ["1@aaaaaaaa"]}, 3, "aaaaaaaa")],
        ]))
        keys, ops, _visible, winners, values = engine.visible_state()
        doc = tr.decode_visible(keys[0], ops[0], winners[0], values[0])
        assert doc == {"c": "gone"}

    def test_table_rows(self):
        engine = tpu.BatchedMapEngine(1, capacity=16)
        tr = tpu.BatchTranscoder()
        engine.apply_batch(tr.changes_to_batch([
            [({"action": "makeTable", "obj": "_root", "key": "t", "pred": []}, 1, "aaaaaaaa"),
             ({"action": "makeMap", "obj": "1@aaaaaaaa", "key": "row-1", "pred": []}, 2, "aaaaaaaa"),
             ({"action": "set", "obj": "2@aaaaaaaa", "key": "name", "value": "ada", "pred": []}, 3, "aaaaaaaa")],
        ]))
        keys, ops, _visible, winners, values = engine.visible_state()
        doc = tr.decode_visible(keys[0], ops[0], winners[0], values[0])
        assert doc == {"t": {"row-1": {"name": "ada"}}}
        assert tr.object_types["1@aaaaaaaa"] == "table"

    def test_nested_differential_vs_opset(self):
        rng = random.Random(99)
        actors = ["aaaaaaaa", "bbbbbbbb"]
        num_docs, num_rounds = 3, 8

        opsets = [OpSet() for _ in range(num_docs)]
        engine = tpu.BatchedMapEngine(num_docs, capacity=128)
        tr = tpu.BatchTranscoder()
        # per-doc: objects list and last op per (obj, key)
        objects = [["_root"] for _ in range(num_docs)]
        last_op = [{} for _ in range(num_docs)]
        seqs = [dict.fromkeys(actors, 0) for _ in range(num_docs)]
        max_ops = [0] * num_docs

        for _ in range(num_rounds):
            per_doc_rows = []
            for d in range(num_docs):
                actor = rng.choice(actors)
                seqs[d][actor] += 1
                start_op = max_ops[d] + 1
                ops = []
                ctr = start_op
                for _ in range(rng.randrange(1, 5)):
                    obj = rng.choice(objects[d])
                    key = f"k{rng.randrange(4)}"
                    prev = last_op[d].get((obj, key))
                    roll = rng.random()
                    if roll < 0.25:
                        op = {"action": "makeMap", "obj": obj, "key": key,
                              "pred": [prev] if prev else []}
                        objects[d].append(f"{ctr}@{actor}")
                    elif roll < 0.35 and prev:
                        op = {"action": "del", "obj": obj, "key": key, "pred": [prev]}
                    else:
                        op = {"action": "set", "obj": obj, "key": key,
                              "datatype": "uint", "value": rng.randrange(1000),
                              "pred": [prev] if prev else []}
                    if op["action"] == "del":
                        last_op[d].pop((obj, key), None)
                    else:
                        last_op[d][(obj, key)] = f"{ctr}@{actor}"
                    ops.append(op)
                    ctr += 1
                max_ops[d] = ctr - 1
                change = {"actor": actor, "seq": seqs[d][actor], "startOp": start_op,
                          "time": 0, "deps": opsets[d].heads, "ops": ops}
                opsets[d].apply_changes([encode_change(change)])
                per_doc_rows.append([(op, start_op + i, actor) for i, op in enumerate(ops)])
            # fixed width => one compiled shape across rounds
            engine.apply_batch(tr.changes_to_batch(per_doc_rows, width=4))

        keys, ops, _visible, winners, values = engine.visible_state()
        for d in range(num_docs):
            expected = opset_visible_tree(opsets[d].get_patch()["diffs"])
            actual = tr.decode_visible(keys[d], ops[d], winners[d], values[d])
            assert actual == expected, f"doc {d}: {actual} != {expected}"
