"""End-to-end public API tests, ported from the reference suite
(/root/reference/test/test.js): document lifecycle, concurrent merges,
conflicts, save/load, history."""
import pytest

import automerge_tpu as am
from automerge_tpu.uuid import reset_factory, set_factory

from helpers import assert_equals_one_of


def set_key(key, value):
    return lambda d: d.__setitem__(key, value)


class TestInit:
    def test_initially_empty(self):
        doc = am.init()
        assert len(doc) == 0
        assert am.get_object_id(doc) == "_root"

    def test_actor_id_option(self):
        doc = am.init("0123456789abcdef")
        assert am.get_actor_id(doc) == "0123456789abcdef"

    def test_rejects_bad_actor_id(self):
        with pytest.raises(ValueError, match="hex digits"):
            am.init("not-hex!")
        with pytest.raises(ValueError, match="even number"):
            am.init("abc")

    def test_from_data(self):
        doc = am.from_data({"x": 1, "y": "two"})
        assert doc["x"] == 1
        assert doc["y"] == "two"
        history = am.get_history(doc)
        assert history[0].change["message"] == "Initialization"


class TestChange:
    def test_change_returns_new_doc(self):
        d1 = am.init()
        d2 = am.change(d1, set_key("k", "v"))
        assert len(d1) == 0
        assert d2["k"] == "v"

    def test_unchanged_doc_returned_as_is(self):
        d1 = am.change(am.init(), set_key("k", "v"))
        d2 = am.change(d1, lambda d: None)
        assert d2 is d1

    def test_no_op_assignment_not_recorded(self):
        d1 = am.change(am.init(), set_key("k", "v"))
        d2 = am.change(d1, set_key("k", "v"))
        assert d2 is d1

    def test_change_message(self):
        d1 = am.change(am.init(), "msg here", set_key("k", "v"))
        assert am.get_history(d1)[0].change["message"] == "msg here"

    def test_nested_maps(self):
        d1 = am.change(am.init(), set_key("outer", {"inner": {"deep": 42}}))
        assert d1["outer"]["inner"]["deep"] == 42
        d2 = am.change(d1, lambda d: d["outer"]["inner"].__setitem__("deep", 43))
        assert d2["outer"]["inner"]["deep"] == 43
        assert d1["outer"]["inner"]["deep"] == 42  # immutability

    def test_delete_key(self):
        d1 = am.change(am.init(), set_key("k", "v"))
        d2 = am.change(d1, lambda d: d.__delitem__("k"))
        assert "k" not in d2
        assert "k" in d1

    def test_read_only_outside_change(self):
        d1 = am.change(am.init(), set_key("k", "v"))
        with pytest.raises(TypeError, match="read-only"):
            d1["k2"] = "v2"

    def test_numbers(self):
        d1 = am.change(am.init(), lambda d: (
            d.__setitem__("int", 3),
            d.__setitem__("float", 1.5),
            d.__setitem__("uint", am.Uint(7)),
            d.__setitem__("neg", -12),
            d.__setitem__("bool", True),
            d.__setitem__("none", None),
        ))
        assert d1["int"] == 3 and isinstance(d1["int"], int)
        assert d1["float"] == 1.5
        assert d1["uint"] == 7
        assert d1["neg"] == -12
        assert d1["bool"] is True
        assert d1["none"] is None
        d2 = am.load(am.save(d1))
        assert dict(d2) == dict(d1)

    def test_empty_change(self):
        d1 = am.change(am.init(), set_key("k", "v"))
        d2 = am.empty_change(d1, "just a milestone")
        assert dict(d2) == dict(d1)
        assert am.get_history(d2)[1].change["message"] == "just a milestone"


class TestLists:
    def test_list_operations(self):
        d1 = am.change(am.init(), set_key("birds", ["chaffinch", "wren"]))
        assert list(d1["birds"]) == ["chaffinch", "wren"]
        d2 = am.change(d1, lambda d: d["birds"].append("goldfinch"))
        d3 = am.change(d2, lambda d: d["birds"].insert(1, "robin"))
        assert list(d3["birds"]) == ["chaffinch", "robin", "wren", "goldfinch"]
        d4 = am.change(d3, lambda d: d["birds"].delete_at(0))
        assert list(d4["birds"]) == ["robin", "wren", "goldfinch"]
        d5 = am.change(d4, lambda d: d["birds"].__setitem__(1, "jay"))
        assert list(d5["birds"]) == ["robin", "jay", "goldfinch"]

    def test_list_of_objects(self):
        d1 = am.change(am.init(), set_key("todos", [{"title": "a", "done": False}]))
        assert d1["todos"][0]["title"] == "a"
        d2 = am.change(d1, lambda d: d["todos"][0].__setitem__("done", True))
        assert d2["todos"][0]["done"] is True

    def test_nested_lists(self):
        d1 = am.change(am.init(), set_key("matrix", [[1, 2], [3, 4]]))
        assert list(d1["matrix"][1]) == [3, 4]
        d2 = am.change(d1, lambda d: d["matrix"][0].append(99))
        assert list(d2["matrix"][0]) == [1, 2, 99]

    def test_assignment_past_end_pads_with_none(self):
        d1 = am.change(am.init(), set_key("list", ["a"]))
        d2 = am.change(d1, lambda d: d["list"].__setitem__(3, "d"))
        assert list(d2["list"]) == ["a", None, None, "d"]

    def test_element_ids(self):
        d1 = am.change(am.init("aabbccdd"), set_key("list", ["a", "b"]))
        elem_ids = am.get_element_ids(d1["list"])
        assert elem_ids == ["2@aabbccdd", "3@aabbccdd"]

    def test_add_and_remove_same_change(self):
        d1 = am.change(am.init(), set_key("noodles", []))
        d1 = am.change(d1, lambda d: (d["noodles"].append("udon"), d["noodles"].delete_at(0)))
        assert list(d1["noodles"]) == []
        d1 = am.change(d1, lambda d: (d["noodles"].append("soba"), d["noodles"].delete_at(0)))
        assert list(d1["noodles"]) == []


class TestText:
    def test_text_editing(self):
        d1 = am.change(am.init(), set_key("text", am.Text("init")))
        assert str(d1["text"]) == "init"
        d2 = am.change(d1, lambda d: d["text"].insert_at(0, "T", "h", "e", " "))
        assert str(d2["text"]) == "The init"
        d3 = am.change(d2, lambda d: d["text"].delete_at(4, 4))
        d4 = am.change(d3, lambda d: d["text"].insert_at(4, "e", "n", "d"))
        assert str(d4["text"]) == "The end"

    def test_text_set(self):
        d1 = am.change(am.init(), set_key("text", am.Text("abc")))
        d2 = am.change(d1, lambda d: d["text"].set(1, "B"))
        assert str(d2["text"]) == "aBc"

    def test_concurrent_text_insertion_converges(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("text", am.Text("ab")))
        d2 = am.load(am.save(d1), "bbbbbbbb")
        d1 = am.change(d1, lambda d: d["text"].insert_at(1, "x"))
        d2 = am.change(d2, lambda d: d["text"].insert_at(1, "y"))
        m1 = am.merge(am.clone(d1, "cccccccc"), d2)
        m2 = am.merge(am.clone(d2, "dddddddd"), d1)
        assert str(m1["text"]) == str(m2["text"])
        assert_equals_one_of(str(m1["text"]), "axyb", "ayxb")


class TestCounter:
    def test_counter_in_map(self):
        d1 = am.change(am.init(), set_key("c", am.Counter(10)))
        d2 = am.change(d1, lambda d: d["c"].increment())
        d3 = am.change(d2, lambda d: d["c"].increment(5))
        d4 = am.change(d3, lambda d: d["c"].decrement(2))
        assert d4["c"].value == 14

    def test_concurrent_increments_add_up(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("c", am.Counter(0)))
        d2 = am.load(am.save(d1), "bbbbbbbb")
        d1 = am.change(d1, lambda d: d["c"].increment(3))
        d2 = am.change(d2, lambda d: d["c"].increment(4))
        merged = am.merge(d1, d2)
        assert merged["c"].value == 7

    def test_cannot_overwrite_counter(self):
        d1 = am.change(am.init(), set_key("c", am.Counter(0)))
        with pytest.raises(ValueError, match="Cannot overwrite a Counter"):
            am.change(d1, set_key("c", 1))


class TestTable:
    def test_table_rows(self):
        set_factory(iter([f"{i:032x}" for i in range(1, 10)]).__next__)
        try:
            d1 = am.change(am.init(), set_key("books", am.Table()))
            row_id = {}

            def add_row(d):
                row_id["id"] = d["books"].add({"title": "STP", "author": "MK"})

            d2 = am.change(d1, add_row)
            book = d2["books"].by_id(row_id["id"])
            assert book["title"] == "STP"
            assert book["id"] == row_id["id"]
            assert d2["books"].count == 1
            d3 = am.change(d2, lambda d: d["books"].remove(row_id["id"]))
            assert d3["books"].count == 0
        finally:
            reset_factory()

    def test_table_row_update(self):
        d1 = am.change(am.init(), set_key("books", am.Table()))
        holder = {}

        def add(d):
            holder["id"] = d["books"].add({"title": "old"})

        d2 = am.change(d1, add)
        d3 = am.change(d2, lambda d: d["books"].by_id(holder["id"]).__setitem__("title", "new"))
        assert d3["books"].by_id(holder["id"])["title"] == "new"


class TestMergeAndConflicts:
    def test_merge_disjoint_keys(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("a", 1))
        d2 = am.change(am.init("bbbbbbbb"), set_key("b", 2))
        merged = am.merge(d1, d2)
        assert merged["a"] == 1 and merged["b"] == 2

    def test_conflict_on_same_key(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("k", "from-a"))
        d2 = am.change(am.init("bbbbbbbb"), set_key("k", "from-b"))
        merged = am.merge(d1, d2)
        # higher actorId wins (Lamport order: same counter, actor tiebreak)
        assert merged["k"] == "from-b"
        conflicts = am.get_conflicts(merged, "k")
        assert set(conflicts.values()) == {"from-a", "from-b"}

    def test_conflict_resolution_is_symmetric(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("k", "from-a"))
        d2 = am.change(am.init("bbbbbbbb"), set_key("k", "from-b"))
        m1 = am.merge(am.clone(d1, "11111111"), d2)
        m2 = am.merge(am.clone(d2, "22222222"), d1)
        assert m1["k"] == m2["k"]

    def test_concurrent_list_edits_converge(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("l", ["a", "b", "c"]))
        d2 = am.load(am.save(d1), "bbbbbbbb")
        d1 = am.change(d1, lambda d: d["l"].insert(1, "x"))
        d2 = am.change(d2, lambda d: d["l"].delete_at(2))
        m1 = am.merge(am.clone(d1, "11111111"), d2)
        m2 = am.merge(am.clone(d2, "22222222"), d1)
        assert list(m1["l"]) == list(m2["l"])
        assert list(m1["l"]) == ["a", "x", "b"]

    def test_get_changes_and_apply(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("a", 1))
        d1_copy = am.load(am.save(d1))
        d2 = am.change(d1, set_key("b", 2))
        changes = am.get_changes(d1, d2)
        assert len(changes) == 1
        d3, patch = am.apply_changes(d1_copy, changes)
        assert d3["b"] == 2


class TestSaveLoad:
    def test_round_trip(self):
        d1 = am.change(am.init("aaaaaaaa"), lambda d: (
            d.__setitem__("map", {"k": "v"}),
            d.__setitem__("list", [1, 2, 3]),
            d.__setitem__("text", am.Text("hi")),
        ))
        data = am.save(d1)
        d2 = am.load(data)
        assert dict(d2["map"]) == {"k": "v"}
        assert list(d2["list"]) == [1, 2, 3]
        assert str(d2["text"]) == "hi"

    def test_save_deterministic(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        assert am.save(d1) == am.save(am.load(am.save(d1)))

    def test_clone(self):
        d1 = am.change(am.init("aaaaaaaa"), set_key("x", 1))
        d2 = am.clone(d1, "bbbbbbbb")
        d3 = am.change(d2, set_key("y", 2))
        assert "y" not in d1
        assert d3["x"] == 1 and d3["y"] == 2


class TestHistory:
    def test_history_snapshots(self):
        d1 = am.change(am.init("aaaaaaaa"), "first", set_key("a", 1))
        d2 = am.change(d1, "second", set_key("b", 2))
        history = am.get_history(d2)
        assert len(history) == 2
        assert [h.change["message"] for h in history] == ["first", "second"]
        assert dict(history[0].snapshot) == {"a": 1}
        assert dict(history[1].snapshot) == {"a": 1, "b": 2}


class TestObservable:
    def test_observable_callback(self):
        observable = am.Observable()
        d1 = am.init({"actorId": "aaaaaaaa", "observable": observable})
        d1 = am.change(d1, set_key("list", ["a"]))
        events = []
        observable.observe(d1["list"], lambda diff, before, after, local, changes: events.append(
            (diff["type"], local)
        ))
        d2 = am.change(d1, lambda d: d["list"].append("b"))
        assert events == [("list", True)]
