"""Frontend-layer tests ported from the reference suite
(/root/reference/test/frontend_test.js, text_test.js, proxies_test.js):
the frontend driven alone (backend mocked out via the request queue) plus
document type behaviors."""
import pytest

import automerge_tpu as am
from automerge_tpu import Frontend
from automerge_tpu.frontend.datatypes import Text


class TestFrontendStandalone:
    """Frontend without a backend: changes queue as requests
    (frontend_test.js pattern)."""

    def test_change_produces_request(self):
        d0 = Frontend.init("aaaaaaaa")  # no backend in options
        d1, req = Frontend.change(d0, lambda d: d.__setitem__("bird", "magpie"))
        assert d1["bird"] == "magpie"
        assert req["actor"] == "aaaaaaaa"
        assert req["seq"] == 1
        assert req["ops"] == [
            {"action": "set", "obj": "_root", "insert": False, "value": "magpie",
             "pred": [], "key": "bird"},
        ]

    def test_apply_patch_confirms_request(self):
        d0 = Frontend.init("aaaaaaaa")
        d1, req = Frontend.change(d0, lambda d: d.__setitem__("bird", "magpie"))
        patch = {
            "actor": "aaaaaaaa", "seq": 1, "maxOp": 1, "clock": {"aaaaaaaa": 1}, "deps": [],
            "diffs": {"objectId": "_root", "type": "map", "props": {
                "bird": {"1@aaaaaaaa": {"type": "value", "value": "magpie"}}}},
        }
        d2 = Frontend.apply_patch(d1, patch)
        assert d2["bird"] == "magpie"

    def test_mismatched_seq_rejected(self):
        d0 = Frontend.init("aaaaaaaa")
        d1, _req = Frontend.change(d0, lambda d: d.__setitem__("x", 1))
        bad_patch = {
            "actor": "aaaaaaaa", "seq": 2, "maxOp": 1, "clock": {"aaaaaaaa": 2}, "deps": [],
            "diffs": {"objectId": "_root", "type": "map", "props": {}},
        }
        with pytest.raises(ValueError, match="Mismatched sequence number"):
            Frontend.apply_patch(d1, bad_patch)

    def test_remote_patch_rebases_queued_request(self):
        d0 = Frontend.init("aaaaaaaa")
        d1, _req = Frontend.change(d0, lambda d: d.__setitem__("mine", 1))
        remote_patch = {
            "maxOp": 1, "clock": {"bbbbbbbb": 1}, "deps": [],
            "diffs": {"objectId": "_root", "type": "map", "props": {
                "theirs": {"1@bbbbbbbb": {"type": "value", "value": 2}}}},
        }
        d2 = Frontend.apply_patch(d1, remote_patch)
        # while the local change is unconfirmed, the doc keeps showing the
        # optimistic state; the remote value is held on the rebased base doc
        assert d2["mine"] == 1
        assert "theirs" not in d2
        confirm = {
            "actor": "aaaaaaaa", "seq": 1, "maxOp": 2,
            "clock": {"aaaaaaaa": 1, "bbbbbbbb": 1}, "deps": [],
            "diffs": {"objectId": "_root", "type": "map", "props": {
                "mine": {"2@aaaaaaaa": {"type": "value", "value": 1}}}},
        }
        d3 = Frontend.apply_patch(d2, confirm)
        assert d3["mine"] == 1
        assert d3["theirs"] == 2

    def test_defer_actor_id(self):
        d0 = Frontend.init({"deferActorId": True})
        assert Frontend.get_actor_id(d0) is None
        d1 = Frontend.set_actor_id(d0, "ccdd0011")
        d2, req = Frontend.change(d1, lambda d: d.__setitem__("x", 1))
        assert req["actor"] == "ccdd0011"

    def test_change_before_actor_id_fails(self):
        d0 = Frontend.init({"deferActorId": True})
        with pytest.raises(ValueError, match="Actor ID must be initialized"):
            Frontend.change(d0, lambda d: d.__setitem__("x", 1))


class TestTextType:
    def test_to_spans(self):
        d1 = am.change(am.init(), lambda d: d.__setitem__("text", am.Text("ab")))
        d2 = am.change(d1, lambda d: d["text"].insert_at(2, {"bold": True}))
        d3 = am.change(d2, lambda d: d["text"].insert_at(3, "c", "d"))
        spans = d3["text"].to_spans()
        assert spans[0] == "ab"
        assert dict(spans[1]) == {"bold": True}
        assert spans[2] == "cd"

    def test_text_equality_and_str(self):
        d = am.change(am.init(), lambda d: d.__setitem__("t", am.Text("hello")))
        assert d["t"] == "hello"
        assert d["t"] == am.Text("hello")
        assert str(d["t"]) == "hello"
        assert len(d["t"]) == 5
        assert list(d["t"]) == ["h", "e", "l", "l", "o"]

    def test_element_ids(self):
        d = am.change(am.init("aabbccdd"), lambda d: d.__setitem__("t", am.Text("ab")))
        assert am.get_element_ids(d["t"]) == ["2@aabbccdd", "3@aabbccdd"]

    def test_objects_in_text(self):
        d1 = am.change(am.init(), lambda d: d.__setitem__("t", am.Text("ab")))
        d2 = am.change(d1, lambda d: d["t"].insert_at(1, {"k": "v"}))
        assert d2["t"][1]["k"] == "v"
        assert str(d2["t"]) == "ab"  # objects skipped in string form


class TestConflictAccessors:
    def test_map_conflicts(self):
        d1 = am.change(am.init("aaaaaaaa"), lambda d: d.__setitem__("k", 1))
        d2 = am.load(am.save(d1), "bbbbbbbb")
        d1 = am.change(d1, lambda d: d.__setitem__("k", "a-wins"))
        d2 = am.change(d2, lambda d: d.__setitem__("k", "b-wins"))
        merged = am.merge(d1, d2)
        conflicts = am.get_conflicts(merged, "k")
        assert set(conflicts.values()) == {"a-wins", "b-wins"}
        assert merged["k"] == "b-wins"

    def test_list_conflicts(self):
        d1 = am.change(am.init("aaaaaaaa"), lambda d: d.__setitem__("l", ["x"]))
        d2 = am.load(am.save(d1), "bbbbbbbb")
        d1 = am.change(d1, lambda d: d["l"].__setitem__(0, "a-val"))
        d2 = am.change(d2, lambda d: d["l"].__setitem__(0, "b-val"))
        merged = am.merge(d1, d2)
        conflicts = am.get_conflicts(merged["l"], 0)
        assert set(conflicts.values()) == {"a-val", "b-val"}

    def test_no_conflict_returns_none(self):
        d = am.change(am.init(), lambda d: d.__setitem__("k", 1))
        assert am.get_conflicts(d, "k") is None


class TestProxyBehaviors:
    def test_map_iteration_and_membership(self):
        def cb(d):
            d["a"] = 1
            d["b"] = 2
            assert set(d.keys()) == {"a", "b"}
            assert "a" in d and "z" not in d
            assert len(d) == 2
            assert dict(d.items())["b"] == 2

        am.change(am.init(), cb)

    def test_list_methods(self):
        def cb(d):
            d["l"] = [1, 2, 3]
            lst = d["l"]
            assert lst[0] == 1
            assert lst[-1] == 3
            assert list(lst[1:]) == [2, 3]
            assert 2 in lst
            assert lst.index(3) == 2
            lst.extend([4, 5])
            assert len(lst) == 5
            assert lst.pop() == 5
            assert len(lst) == 4

        doc = am.change(am.init(), cb)
        assert list(doc["l"]) == [1, 2, 3, 4]

    def test_nested_object_identity_error(self):
        d1 = am.change(am.init(), lambda d: d.__setitem__("a", {"x": 1}))

        def reuse(d):
            d["b"] = d["a"]

        with pytest.raises(Exception):
            am.change(d1, reuse)

    def test_get_object_by_id(self):
        d = am.change(am.init(), lambda d: d.__setitem__("m", {"x": 1}))
        object_id = am.get_object_id(d["m"])
        assert am.get_object_by_id(d, object_id) is d["m"]


class TestEquals:
    def test_deep_equality(self):
        d1 = am.change(am.init("aaaaaaaa"), lambda d: d.update({"a": [1, {"b": 2}]}))
        d2 = am.change(am.init("bbbbbbbb"), lambda d: d.update({"a": [1, {"b": 2}]}))
        assert am.equals(d1, d2)
        d3 = am.change(am.init("cccccccc"), lambda d: d.update({"a": [1, {"b": 3}]}))
        assert not am.equals(d1, d3)


class TestLastLocalChange:
    def test_returns_binary_change(self):
        d1 = am.change(am.init("aaaaaaaa"), lambda d: d.__setitem__("x", 1))
        binary = am.get_last_local_change(d1)
        decoded = am.decode_change(binary)
        assert decoded["actor"] == "aaaaaaaa"
        assert decoded["ops"][0]["key"] == "x"
