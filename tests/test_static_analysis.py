"""Tier-1 gate for the amlint static analysis suite (automerge_tpu.analysis).

Two jobs:
1. **Ratchet**: the full rule suite runs over the installed package and must
   report zero unsuppressed findings — any commit that re-opens a packing
   hole, leaks a Python branch into traced code, or crosses the host/device
   module boundary fails tier-1.
2. **Analyzer coverage**: every rule ID is exercised against a violating, a
   clean, and a suppressed fixture under tests/analysis_fixtures/, and the
   CLI contract (exit 0 clean / exit 1 findings) is pinned.
"""
import importlib.util
import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from automerge_tpu.analysis import RULES, default_target, run_analysis
from automerge_tpu.analysis.__main__ import main as amlint_main

PACKAGE = default_target()
FIXTURES = Path(__file__).parent / "analysis_fixtures"

# every implemented rule with fixtures (AM000 is the parse-failure escape
# hatch and has no fixture triple)
RULE_IDS = sorted(r for r in RULES if r != "AM000")


def test_rule_catalog_covers_all_families():
    families = {RULES[r][0] for r in RULE_IDS}
    assert {"packing", "tracer", "boundary"} <= families
    assert len(RULE_IDS) >= 6


def test_repo_is_clean():
    """The ratchet: the package must stay free of unsuppressed findings."""
    findings = run_analysis([PACKAGE])
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


def test_repo_suppressions_are_justified():
    """Suppressed findings exist (the value-interner AM103 sites, the
    deliberate bare-raise AM401 sites, the per-call actor-rank sort
    AM105 site, the scalar-oracle byte loops AM106 marks in codecs.py,
    the scalar-oracle gate/transcode loops AM107 marks in farm.py,
    the single real-time clock default AM402 site, the mesh
    worker's record-locally/ship-deltas registry and flight shipping-
    buffer sites AM502/AM305 mark in parallel/workers.py, the pickle
    parity-oracle send path AM504 marks in parallel/workers.py (the one
    blessed pickle on the shm transport's data plane), and the store
    tier's own write primitives — the atomic writer's tmp-file handle
    and the WAL's checksummed appender — which AM601 marks in
    store/atomic.py and store/wal.py, and the pad-to-pow2-bucket
    concatenate in tpu/sync_farm.py whose resulting leading dim is
    shape-stable by construction even though AM701's dataflow engine
    sees a raw ``len()`` feeding it), proving the suppression path is
    exercised in-tree, and each sits on a line whose surrounding comment
    carries a justification."""
    everything = run_analysis([PACKAGE], include_suppressed=True)
    suppressed = [f for f in everything if f.suppressed]
    assert suppressed, "expected in-tree justified suppressions"
    assert {f.rule_id for f in suppressed} == {
        "AM103", "AM105", "AM106", "AM107", "AM305", "AM401", "AM402",
        "AM502", "AM504", "AM601", "AM701",
    }


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_violating_fixture_fires(rule_id):
    path = FIXTURES / f"{rule_id.lower()}_violation.py"
    findings = run_analysis([path])
    assert any(f.rule_id == rule_id for f in findings), (
        f"{path.name} should trigger {rule_id}; got "
        f"{[f.format() for f in findings]}"
    )


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_clean_fixture_is_clean(rule_id):
    path = FIXTURES / f"{rule_id.lower()}_clean.py"
    findings = run_analysis([path])
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_suppressed_fixture_is_silenced(rule_id):
    path = FIXTURES / f"{rule_id.lower()}_suppressed.py"
    assert run_analysis([path]) == []
    everything = run_analysis([path], include_suppressed=True)
    hits = [f for f in everything if f.rule_id == rule_id]
    assert hits and all(f.suppressed for f in hits), (
        f"{path.name} should carry a suppressed {rule_id} finding"
    )


def test_cli_exit_codes_in_process():
    assert amlint_main(["-q", str(PACKAGE)]) == 0
    for rule_id in RULE_IDS:
        path = FIXTURES / f"{rule_id.lower()}_violation.py"
        assert amlint_main(["-q", str(path)]) == 1, rule_id


def test_cli_subprocess_contract():
    """`python -m automerge_tpu.analysis` exits 0 on the repo and non-zero
    on a violating fixture (the acceptance-criteria contract)."""
    ok = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.analysis", str(PACKAGE)],
        capture_output=True, text=True,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.analysis",
         str(FIXTURES / "am102_violation.py")],
        capture_output=True, text=True,
    )
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "AM102" in bad.stdout


def test_unparseable_file_reports_am000(tmp_path):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    findings = run_analysis([broken])
    assert [f.rule_id for f in findings] == ["AM000"]


def test_am304_reverse_direction_flags_stale_catalog_rows(tmp_path):
    """AM304's vice-versa check: on a whole-package scan (detected by
    obs/metrics.py being present), a README catalog row naming nothing the
    code records is flagged, anchored on the README line."""
    pkg = tmp_path / "automerge_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "obs" / "metrics.py").write_text(
        '"""mini registry."""\n', encoding="utf-8"
    )
    (pkg / "work.py").write_text(
        "from .obs.metrics import get_metrics\n"
        'get_metrics().counter("mini.live.metric").inc()\n',
        encoding="utf-8",
    )
    (tmp_path / "README.md").write_text(
        "# mini\n\n### Metric catalog\n\n"
        "| Metric | Type | Meaning |\n|---|---|---|\n"
        "| `mini.live.metric` | counter | lives in code |\n"
        "| `mini.stale.metric` | counter | nothing records this |\n",
        encoding="utf-8",
    )
    findings = run_analysis([pkg])
    stale = [f for f in findings if f.rule_id == "AM304"]
    assert len(stale) == 1, [f.format() for f in findings]
    assert "mini.stale.metric" in stale[0].message
    assert stale[0].path.endswith("README.md")


def test_am304_catalog_shorthand_and_placeholders_parse():
    """The README row grammar: `.suffix` shorthand expands against the
    previous full name, `<placeholder>` rows match dynamic registrations,
    and only metric/event-catalog section tables participate (the amlint
    rule-catalog table's `time.time` must NOT parse as a metric)."""
    from automerge_tpu.analysis.catalog import catalog_names

    text = (REPO_README.read_text(encoding="utf-8")
            if REPO_README.exists() else "")
    names = catalog_names(text)
    assert "farm.pages.free" in names           # `.free` shorthand
    assert "farm.quarantine.causes.<kind>" in names
    assert "session.retransmit" in names        # event catalog included
    assert "time.time" not in names             # rule catalog excluded
    assert "automerge_tpu/__init__.py" not in names


REPO_README = Path(__file__).parent.parent / "README.md"


# --------------------------------------------------------------------- #
# meta-coverage: rules <-> fixtures <-> README catalog, both directions


def test_every_rule_has_fixture_triple_and_readme_row():
    """Forward direction: registering a rule obliges a violating/clean/
    suppressed fixture triple AND a README rule-catalog row — a rule
    cannot ship undocumented or untested."""
    text = REPO_README.read_text(encoding="utf-8")
    rows = set(re.findall(r"\|\s*(AM\d{3})\b", text))
    for rule_id in RULE_IDS:
        for kind in ("violation", "clean", "suppressed"):
            fixture = FIXTURES / f"{rule_id.lower()}_{kind}.py"
            assert fixture.exists(), f"missing fixture {fixture.name}"
        assert rule_id in rows, f"README catalog row missing for {rule_id}"


def test_fixtures_and_readme_rows_name_registered_rules():
    """Reverse direction: every fixture file and every README table cell
    that names a rule id must point at a *registered* rule — deleting a
    rule obliges cleaning up its fixtures and docs."""
    for path in sorted(FIXTURES.glob("*.py")):
        m = re.match(r"(am\d{3})_(violation|clean|suppressed)$", path.stem)
        assert m, f"stray fixture file {path.name}"
        assert m.group(1).upper() in RULES, (
            f"{path.name} names unregistered rule {m.group(1).upper()}"
        )
    text = REPO_README.read_text(encoding="utf-8")
    rows = set(re.findall(r"\|\s*(AM\d{3})\b", text))
    unknown = sorted(rows - set(RULES))
    assert not unknown, f"README names unregistered rule(s): {unknown}"


# --------------------------------------------------------------------- #
# whole-program call graph + transitive reachability diagnostics


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text, encoding="utf-8")
    return p


def test_callgraph_reachable_chain(tmp_path):
    """Cross-module call resolution (module-alias attribute + local name)
    and the shortest-discovery-path chain reachable() reports."""
    from automerge_tpu.analysis.core import FileContext
    from automerge_tpu.analysis.graph import CallGraph

    a = _write(tmp_path, "alpha.py",
               "import beta\n\n\ndef entry():\n    beta.helper()\n")
    b = _write(tmp_path, "beta.py",
               "def helper():\n    leaf()\n\n\ndef leaf():\n    pass\n")
    graph = CallGraph([FileContext(a, str(a)), FileContext(b, str(b))])
    root = graph.modules["alpha"].functions["entry"]
    reached = graph.reachable([root])
    chains = {fi.label: chain for fi, chain in reached.values()}
    assert chains["beta.leaf"] == ("alpha.entry", "beta.helper", "beta.leaf")
    assert chains["beta.helper"] == ("alpha.entry", "beta.helper")


def test_callgraph_import_closures(tmp_path):
    """import_closure walks transitive imports with the first-hop anchor;
    importers_closure inverts the edges (the --changed widening set)."""
    from automerge_tpu.analysis.core import FileContext
    from automerge_tpu.analysis.graph import CallGraph

    files = [
        _write(tmp_path, "top.py", '"""top."""\nimport mid\n'),
        _write(tmp_path, "mid.py", '"""mid."""\nimport leafmod\n'),
        _write(tmp_path, "leafmod.py", '"""leaf."""\n'),
        _write(tmp_path, "loner.py", '"""unrelated."""\n'),
    ]
    graph = CallGraph([FileContext(p, str(p)) for p in files])
    closure = graph.import_closure("top")
    assert closure["leafmod"][0] == ("top", "mid", "leafmod")
    assert "loner" not in closure
    assert graph.importers_closure({"leafmod"}) == {"top", "mid"}


def test_am403_transitive_finding_prints_call_chain(tmp_path):
    """A blocking call in a helper module outside serve scope is flagged
    when a serve event-loop function reaches it through the call graph,
    and the diagnostic carries the actual call path."""
    _write(tmp_path, "srv.py",
           "# amlint: serve-event-loop\nimport helper\n\n\n"
           "def handle():\n    helper.drain()\n")
    _write(tmp_path, "helper.py",
           "import time\n\n\ndef drain():\n    time.sleep(0.1)\n")
    findings = [f for f in run_analysis([tmp_path]) if f.rule_id == "AM403"]
    assert len(findings) == 1, [f.format() for f in findings]
    assert findings[0].path.endswith("helper.py")
    assert ("[reachable via srv.handle -> helper.drain]"
            in findings[0].message)


def test_am502_transitive_import_chain(tmp_path):
    """A worker module reaching a controller module through an innocent
    intermediary is flagged at the first-hop import, with the module
    chain in the diagnostic."""
    for name, body in [
        ("workers.py", '"""worker."""\nimport innocent\n'),
        ("innocent.py", '"""glue."""\nimport meshfarm\n'),
        ("meshfarm.py", '"""controller."""\n'),
    ]:
        _write(tmp_path, name, body)
    findings = [f for f in run_analysis([tmp_path]) if f.rule_id == "AM502"]
    assert len(findings) == 1, [f.format() for f in findings]
    assert findings[0].path.endswith("workers.py")
    assert ("[reachable via workers -> innocent -> meshfarm]"
            in findings[0].message)


# --------------------------------------------------------------------- #
# AM701 <-> amprof storm parity: the static rule and the runtime
# detector must agree on the same fixture pair


def _load_fixture_module(stem):
    spec = importlib.util.spec_from_file_location(
        stem, FIXTURES / f"{stem}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_am701_static_and_runtime_storm_parity():
    """The acceptance contract for the shape family: the violating
    fixture provokes ``prof.recompile.storm`` at runtime (four distinct
    batch lengths = four compiles inside the storm window) AND is
    statically flagged with a dataflow chain; the pow2-bucketed twin is
    quiet on both sides."""
    from automerge_tpu.obs.flight import enabled_flight
    from automerge_tpu.obs.prof import enabled_observatory, get_observatory

    batches = [[0] * n for n in (33, 57, 91, 123)]
    storms = {}
    for stem in ("am701_violation", "am701_clean"):
        mod = _load_fixture_module(stem)
        get_observatory().reset()
        with enabled_observatory(), enabled_flight() as flight:
            mod.drive(batches)
            storms[stem] = [
                e for e in flight.snapshot()
                if e["event"] == "prof.recompile.storm"
            ]
    assert any(e["fields"]["program"] == "fixture.shape.raw"
               for e in storms["am701_violation"]), (
        "raw-length fixture must trip the runtime storm detector"
    )
    assert not any(e["fields"].get("program") == "fixture.shape.bucketed"
                   for e in storms["am701_clean"]), (
        "bucketed fixture must stay under the storm threshold"
    )
    raw = run_analysis([FIXTURES / "am701_violation.py"])
    assert any(f.rule_id == "AM701" and "[dataflow:" in f.message
               for f in raw), [f.format() for f in raw]
    assert run_analysis([FIXTURES / "am701_clean.py"]) == []


# --------------------------------------------------------------------- #
# CLI contract: usage errors exit 2 with one-line stderr, --select,
# --changed and --json


def test_cli_usage_errors_exit_2(tmp_path, capsys):
    assert amlint_main(
        ["--select", "AM999", str(FIXTURES / "am101_clean.py")]
    ) == 2
    assert amlint_main([str(tmp_path / "does_not_exist.py")]) == 2
    typo = _write(tmp_path, "typo.py", "x = 1  # amlint: disable=AM999\n")
    assert amlint_main([str(typo)]) == 2
    err = capsys.readouterr().err
    assert err.count("amlint: error:") == 3, err
    assert "Traceback" not in err


def test_cli_usage_error_subprocess_never_tracebacks():
    proc = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.analysis",
         "--select", "AMXXX", str(FIXTURES / "am101_clean.py")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert proc.stderr.strip().startswith("amlint: error:")
    assert "Traceback" not in proc.stderr


def test_cli_changed_bad_ref_exits_2():
    proc = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.analysis",
         "--changed", "no-such-ref-xyz", "automerge_tpu"],
        capture_output=True, text=True,
        cwd=str(Path(__file__).parent.parent),
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "amlint: error:" in proc.stderr
    assert "Traceback" not in proc.stderr


def test_cli_changed_incremental_and_full_scan_modes(tmp_path):
    """--changed lints changed files plus transitive importers; touching
    a module in the import graph of a rule-scoped one (here: an
    untracked workers.py) falls back to the full scan. The chosen mode
    is announced on stderr either way."""
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = str(Path(__file__).parent.parent)

    def git(*argv):
        subprocess.run(["git", *argv], cwd=tmp_path, check=True,
                       capture_output=True)

    git("init", "-q")
    git("config", "user.email", "t@example.invalid")
    git("config", "user.name", "t")
    _write(tmp_path, "base.py", '"""base."""\nX = 1\n')
    _write(tmp_path, "user.py", '"""user."""\nimport base\n')
    _write(tmp_path, "loner.py", '"""unrelated."""\n')
    git("add", ".")
    git("commit", "-qm", "seed")
    _write(tmp_path, "base.py", '"""base."""\nX = 2\n')

    argv = [sys.executable, "-m", "automerge_tpu.analysis",
            "--changed", "HEAD", "--json", str(tmp_path)]
    proc = subprocess.run(argv, capture_output=True, text=True,
                          cwd=str(tmp_path), env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "incremental: 2 of 3 file(s)" in proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["active"] == 0 and payload["findings"] == []

    _write(tmp_path, "workers.py", '"""worker."""\nimport base\n')
    proc = subprocess.run(argv, capture_output=True, text=True,
                          cwd=str(tmp_path), env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "full scan:" in proc.stderr


def test_cli_json_output_in_process(capsys):
    rc = amlint_main(["--json", str(FIXTURES / "am102_violation.py")])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["active"] >= 1
    assert any(f["rule"] == "AM102" for f in payload["findings"])
    assert {"rule", "path", "line", "col", "message", "suppressed"} <= set(
        payload["findings"][0]
    )


def test_cli_select_filters_report(capsys):
    rc = amlint_main(
        ["--select", "AM503", "--json",
         str(FIXTURES / "am503_violation.py")]
    )
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["findings"]
    assert all(f["rule"] == "AM503" for f in payload["findings"])
