"""Tier-1 gate for the amlint static analysis suite (automerge_tpu.analysis).

Two jobs:
1. **Ratchet**: the full rule suite runs over the installed package and must
   report zero unsuppressed findings — any commit that re-opens a packing
   hole, leaks a Python branch into traced code, or crosses the host/device
   module boundary fails tier-1.
2. **Analyzer coverage**: every rule ID is exercised against a violating, a
   clean, and a suppressed fixture under tests/analysis_fixtures/, and the
   CLI contract (exit 0 clean / exit 1 findings) is pinned.
"""
import subprocess
import sys
from pathlib import Path

import pytest

from automerge_tpu.analysis import RULES, default_target, run_analysis
from automerge_tpu.analysis.__main__ import main as amlint_main

PACKAGE = default_target()
FIXTURES = Path(__file__).parent / "analysis_fixtures"

# every implemented rule with fixtures (AM000 is the parse-failure escape
# hatch and has no fixture triple)
RULE_IDS = sorted(r for r in RULES if r != "AM000")


def test_rule_catalog_covers_all_families():
    families = {RULES[r][0] for r in RULE_IDS}
    assert {"packing", "tracer", "boundary"} <= families
    assert len(RULE_IDS) >= 6


def test_repo_is_clean():
    """The ratchet: the package must stay free of unsuppressed findings."""
    findings = run_analysis([PACKAGE])
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


def test_repo_suppressions_are_justified():
    """Suppressed findings exist (the value-interner AM103 sites, the
    deliberate bare-raise AM401 sites, the per-call actor-rank sort
    AM105 site, the scalar-oracle byte loops AM106 marks in codecs.py,
    the scalar-oracle gate/transcode loops AM107 marks in farm.py,
    the single real-time clock default AM402 site, the mesh
    worker's record-locally/ship-deltas registry and flight shipping-
    buffer sites AM502/AM305 mark in parallel/workers.py, and the store
    tier's own write primitives — the atomic writer's tmp-file handle
    and the WAL's checksummed appender — which AM601 marks in
    store/atomic.py and store/wal.py), proving the suppression path is
    exercised in-tree, and each sits on a line whose surrounding comment
    carries a justification."""
    everything = run_analysis([PACKAGE], include_suppressed=True)
    suppressed = [f for f in everything if f.suppressed]
    assert suppressed, "expected in-tree justified suppressions"
    assert {f.rule_id for f in suppressed} == {
        "AM103", "AM105", "AM106", "AM107", "AM305", "AM401", "AM402",
        "AM502", "AM601",
    }


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_violating_fixture_fires(rule_id):
    path = FIXTURES / f"{rule_id.lower()}_violation.py"
    findings = run_analysis([path])
    assert any(f.rule_id == rule_id for f in findings), (
        f"{path.name} should trigger {rule_id}; got "
        f"{[f.format() for f in findings]}"
    )


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_clean_fixture_is_clean(rule_id):
    path = FIXTURES / f"{rule_id.lower()}_clean.py"
    findings = run_analysis([path])
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_suppressed_fixture_is_silenced(rule_id):
    path = FIXTURES / f"{rule_id.lower()}_suppressed.py"
    assert run_analysis([path]) == []
    everything = run_analysis([path], include_suppressed=True)
    hits = [f for f in everything if f.rule_id == rule_id]
    assert hits and all(f.suppressed for f in hits), (
        f"{path.name} should carry a suppressed {rule_id} finding"
    )


def test_cli_exit_codes_in_process():
    assert amlint_main(["-q", str(PACKAGE)]) == 0
    for rule_id in RULE_IDS:
        path = FIXTURES / f"{rule_id.lower()}_violation.py"
        assert amlint_main(["-q", str(path)]) == 1, rule_id


def test_cli_subprocess_contract():
    """`python -m automerge_tpu.analysis` exits 0 on the repo and non-zero
    on a violating fixture (the acceptance-criteria contract)."""
    ok = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.analysis", str(PACKAGE)],
        capture_output=True, text=True,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "automerge_tpu.analysis",
         str(FIXTURES / "am102_violation.py")],
        capture_output=True, text=True,
    )
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "AM102" in bad.stdout


def test_unparseable_file_reports_am000(tmp_path):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    findings = run_analysis([broken])
    assert [f.rule_id for f in findings] == ["AM000"]


def test_am304_reverse_direction_flags_stale_catalog_rows(tmp_path):
    """AM304's vice-versa check: on a whole-package scan (detected by
    obs/metrics.py being present), a README catalog row naming nothing the
    code records is flagged, anchored on the README line."""
    pkg = tmp_path / "automerge_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "obs" / "metrics.py").write_text(
        '"""mini registry."""\n', encoding="utf-8"
    )
    (pkg / "work.py").write_text(
        "from .obs.metrics import get_metrics\n"
        'get_metrics().counter("mini.live.metric").inc()\n',
        encoding="utf-8",
    )
    (tmp_path / "README.md").write_text(
        "# mini\n\n### Metric catalog\n\n"
        "| Metric | Type | Meaning |\n|---|---|---|\n"
        "| `mini.live.metric` | counter | lives in code |\n"
        "| `mini.stale.metric` | counter | nothing records this |\n",
        encoding="utf-8",
    )
    findings = run_analysis([pkg])
    stale = [f for f in findings if f.rule_id == "AM304"]
    assert len(stale) == 1, [f.format() for f in findings]
    assert "mini.stale.metric" in stale[0].message
    assert stale[0].path.endswith("README.md")


def test_am304_catalog_shorthand_and_placeholders_parse():
    """The README row grammar: `.suffix` shorthand expands against the
    previous full name, `<placeholder>` rows match dynamic registrations,
    and only metric/event-catalog section tables participate (the amlint
    rule-catalog table's `time.time` must NOT parse as a metric)."""
    from automerge_tpu.analysis.catalog import catalog_names

    text = (REPO_README.read_text(encoding="utf-8")
            if REPO_README.exists() else "")
    names = catalog_names(text)
    assert "farm.pages.free" in names           # `.free` shorthand
    assert "farm.quarantine.causes.<kind>" in names
    assert "session.retransmit" in names        # event catalog included
    assert "time.time" not in names             # rule catalog excluded
    assert "automerge_tpu/__init__.py" not in names


REPO_README = Path(__file__).parent.parent / "README.md"
